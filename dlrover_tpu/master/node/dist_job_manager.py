"""Distributed job manager: node lifecycle across a cluster backend.

Parity: reference dlrover/python/master/node/dist_job_manager.py:107-1568
(DistributedJobManager.start/_monitor_nodes/_process_event/
_should_relaunch/_relaunch_node) — creates/monitors/relaunches worker
nodes through a Scaler + NodeWatcher pair, detects dead nodes by
heartbeat timeout (reference :532-610), and applies the exit-reason
relaunch policy (:996).

TPU specifics vs the reference: node groups map to TPU hosts of a slice;
a relaunch of a host keeps its rank_index so the slice's physical mesh
coordinates stay valid; hardware-broken hosts are replaced rather than
restarted (ICI requires the full slice, so the rendezvous holds workers
until the replacement arrives).
"""

import threading
import time
from typing import Dict, List, Optional

from dlrover_tpu.common import comm
from dlrover_tpu.common.constants import (
    JobStage,
    NodeEventType,
    NodeExitReason,
    NodeStatus,
    NodeType,
    TrainingExceptionLevel,
)
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.node import Node, NodeEvent, NodeGroupResource
from dlrover_tpu.diagnosis.actions import DiagnosisAction, NodeAction
from dlrover_tpu.master.node.event_callback import NodeEventCallback
from dlrover_tpu.master.node.exit_reason import classify_exit
from dlrover_tpu.master.node.job_context import get_job_context
from dlrover_tpu.master.node.training_node import (
    WorkerManager,
    create_role_manager,
)
from dlrover_tpu.master.scaler.base_scaler import ScalePlan, Scaler
from dlrover_tpu.master.watcher.base_watcher import NodeWatcher
from dlrover_tpu.training_event import MasterEvents

_MONITOR_INTERVAL_S = 1.0


class DistributedJobManager:
    def __init__(
        self,
        job_name: str,
        node_groups: Dict[str, NodeGroupResource],
        scaler: Scaler,
        watcher: NodeWatcher,
        max_relaunch_count: int = 3,
        heartbeat_timeout_s: float = 600.0,
        pending_timeout_s: float = 900.0,
        relaunch_on_worker_failure: bool = True,
        node_group_size: int = 0,
    ):
        self._job_name = job_name
        self._job_context = get_job_context()
        self._scaler = scaler
        self._watcher = watcher
        self._max_relaunch_count = max_relaunch_count
        self._heartbeat_timeout_s = heartbeat_timeout_s
        self._pending_timeout_s = pending_timeout_s
        self._relaunch_on_worker_failure = relaunch_on_worker_failure
        # Hosts per TPU slice (0/1 = no grouping): drives group
        # assignment at init and whole-block relaunch on hardware
        # faults (reference dist_job_manager.py:1128
        # _relaunch_node_group).
        self._node_group_size = node_group_size
        self._node_event_callbacks: List[NodeEventCallback] = []
        self._stopped = threading.Event()
        self._threads: List[threading.Thread] = []
        self._id_lock = threading.Lock()
        self._next_node_id = 0
        # Serializes status transitions: events arrive from the watcher
        # thread, the heartbeat monitor, and RPC servicer threads.
        self._event_lock = threading.Lock()
        # Agent-reported node ids may differ from the master's internal
        # record ids (e.g. a relaunched pod keeps NODE_ID of its rank);
        # handle_node_joined records the mapping here.
        self._id_alias: Dict[int, int] = {}

        # Per-role managers (reference runs worker/chief/evaluator/ps
        # manager instances side by side; TF PS is by-design absent).
        groups = dict(node_groups)
        groups.setdefault(NodeType.WORKER, NodeGroupResource(count=1))
        self._managers = {
            node_type: create_role_manager(
                node_type,
                group,
                self._new_node_id,
                max_relaunch_count,
                node_group_size=node_group_size,
            )
            for node_type, group in groups.items()
        }
        self._worker_manager = self._managers[NodeType.WORKER]

    # ---- wiring ------------------------------------------------------------

    def add_node_event_callback(self, callback: NodeEventCallback):
        self._node_event_callbacks.append(callback)

    @property
    def worker_manager(self) -> WorkerManager:
        return self._worker_manager

    @property
    def role_managers(self):
        return dict(self._managers)

    def set_master_addr(self, addr: str):
        self._scaler.set_master_addr(addr)

    def _new_node_id(self) -> int:
        with self._id_lock:
            node_id = self._next_node_id
            self._next_node_id += 1
            return node_id

    # ---- lifecycle ---------------------------------------------------------

    def start(self):
        self._job_context.set_job_stage(JobStage.PENDING)
        self._scaler.start()
        # Reconcile: adopt nodes that already exist in the backend (master
        # restart while workers keep running, reference
        # dist_job_manager.py _init_nodes), launch only the missing ranks.
        backend_nodes = [
            n
            for n in self._watcher.list()
            if n.status not in NodeStatus.end_states()
        ]
        plan = ScalePlan()
        for node_type, manager in self._managers.items():
            existing = {
                n.rank_index: n
                for n in backend_nodes
                if n.type == node_type
            }
            for node in manager.init_nodes():
                alive = existing.get(node.rank_index)
                if alive is not None:
                    manager.remove_node(node.id)
                    manager.update_node(alive)
                    self._job_context.update_node(alive)
                    logger.info("adopted existing node %s", alive.name)
                else:
                    self._job_context.update_node(node)
                    plan.launch_nodes.append(node)
        if not plan.empty():
            self._scaler.scale(plan)
        self._job_context.set_job_stage(JobStage.RUNNING)
        for target in (self._monitor_nodes, self._monitor_heartbeats):
            t = threading.Thread(
                target=target, name=target.__name__, daemon=True
            )
            t.start()
            self._threads.append(t)
        logger.info(
            "distributed job manager started: %d workers",
            self._worker_manager.group_resource.count,
        )

    def stop(self):
        self._stopped.set()
        self._job_context.set_job_stage(JobStage.STOPPING)
        self._watcher.stop()
        self._scaler.stop()

    def join(self, timeout: float = 5.0):
        for t in self._threads:
            t.join(timeout)

    # ---- monitor loops ------------------------------------------------------

    def _monitor_nodes(self):
        """Consume watcher events (reference dist_job_manager.py:516)."""
        while not self._stopped.is_set():
            try:
                for event in self._watcher.watch():
                    if self._stopped.is_set():
                        return
                    self._process_event(event)
            except Exception:
                logger.exception("node watch stream failed; retrying")
                time.sleep(1.0)

    def _monitor_heartbeats(self):
        """Detect dead nodes whose process stopped reporting
        (reference dist_job_manager.py:543 _monitor_node_heart_beat)."""
        while not self._stopped.is_set():
            time.sleep(_MONITOR_INTERVAL_S)
            now = time.time()
            for node in self._all_running_nodes():
                if node.heartbeat_time <= 0:
                    continue
                if now - node.heartbeat_time > self._heartbeat_timeout_s:
                    logger.warning(
                        "node %s heartbeat lost for %.0fs; marking failed",
                        node.name,
                        now - node.heartbeat_time,
                    )
                    self._observe_failure(node, NodeExitReason.KILLED)

    def _all_running_nodes(self):
        nodes = []
        for manager in self._managers.values():
            nodes.extend(manager.running_nodes())
        return nodes

    def _manager_of(self, node: Node):
        return self._managers.get(node.type, self._worker_manager)

    def pending_timed_out(self) -> bool:
        times = [
            m.first_pending_since() for m in self._managers.values()
        ]
        times = [t for t in times if t]
        since = min(times) if times else 0.0
        return bool(since) and (time.time() - since) > self._pending_timeout_s

    # ---- event processing ----------------------------------------------------

    def _observe_failure(
        self,
        node: Node,
        exit_reason: str,
        status: str = NodeStatus.FAILED,
    ):
        """Feed a synthetic failure observation through the normal event
        path (detached copy: _process_event diffs observed vs recorded)."""
        observed = Node(
            node_type=node.type,
            node_id=node.id,
            rank_index=node.rank_index,
            name=node.name,
            status=status,
        )
        observed.exit_reason = exit_reason
        self._process_event(NodeEvent(NodeEventType.MODIFIED, observed))

    def _process_event(self, event: NodeEvent):
        if event.node is None:
            return
        with self._event_lock:
            self._process_event_locked(event)

    def _process_event_locked(self, event: NodeEvent):
        observed = event.node
        node = None
        for manager in self._managers.values():
            node = manager.get_node(observed.id)
            if node is not None:
                break
        if node is None:
            # A node created outside our records (e.g. scaler raced the
            # watcher at startup): adopt it under its role's manager.
            node = observed
            self._manager_of(node).update_node(node)
        node.host_name = observed.host_name or node.host_name
        node.host_ip = observed.host_ip or node.host_ip
        if observed.exit_reason:
            node.exit_reason = observed.exit_reason

        new_status = observed.status
        if event.event_type == NodeEventType.DELETED:
            # Deletion of a non-finished pod means the host was reclaimed.
            if node.status not in NodeStatus.end_states():
                new_status = NodeStatus.DELETED
            node.is_released = True
        retired = not node.relaunchable
        old_status = node.status
        if not node.update_status(new_status):
            return
        if new_status == old_status:
            return
        self._job_context.update_node(node)
        logger.info(
            "node %s: %s -> %s (%s)",
            node.name,
            old_status,
            new_status,
            node.exit_reason or event.event_type,
        )
        MasterEvents.node_status(
            node.id, new_status, node.exit_reason or event.event_type
        )

        if new_status == NodeStatus.RUNNING:
            for cb in self._node_event_callbacks:
                cb.on_node_started(node)
        elif new_status == NodeStatus.SUCCEEDED:
            for cb in self._node_event_callbacks:
                cb.on_node_succeeded(node)
        elif new_status in (NodeStatus.FAILED, NodeStatus.BREAKDOWN):
            self._job_context.inc_failure_count()
            for cb in self._node_event_callbacks:
                cb.on_node_failed(node)
            # An intentionally-retired record (e.g. a healthy block
            # member torn down by a group relaunch) must not write into
            # the lineage's exit history — that would silently erode
            # the budget of a host that never failed.
            if not retired:
                # exit_reason and the recorded history must agree — the
                # budget check counts exit_history entries matching
                # exit_reason (common/node.py is_unrecoverable_failure).
                node.exit_reason = (
                    node.exit_reason or NodeExitReason.UNKNOWN
                )
                node.record_exit(node.exit_reason)
                self._handle_node_gone(node)
        elif new_status == NodeStatus.DELETED:
            for cb in self._node_event_callbacks:
                cb.on_node_deleted(node)
            # Deleting an already-finished node is cleanup, not a new
            # failure: relaunch only on the first transition into an
            # end state.
            if old_status not in NodeStatus.end_states() and not retired:
                node.exit_reason = (
                    node.exit_reason or NodeExitReason.KILLED
                )
                node.record_exit(node.exit_reason)
                self._handle_node_gone(node)

    def _handle_node_gone(self, node: Node):
        if (
            self._node_group_size > 1
            and node.type == NodeType.WORKER
            and node.node_group >= 0
            and node.exit_reason == NodeExitReason.HARDWARE_ERROR
            and self._should_relaunch(node)
        ):
            # A broken host invalidates its whole ICI slice: the block's
            # hosts must be replaced TOGETHER (a fresh slice), while
            # other blocks keep their processes and simply re-rendezvous
            # when the replacement block arrives.
            self._relaunch_node_group(node.node_group)
            return
        if self._should_relaunch(node):
            new_node, plan = self._manager_of(node).relaunch_node(node)
            if new_node is not None:
                logger.info(
                    "relaunching %s as %s (attempt %d/%d)",
                    node.name,
                    new_node.name,
                    new_node.relaunch_count,
                    node.max_relaunch_count,
                )
                self._job_context.update_node(new_node)
                MasterEvents.node_relaunch(
                    node.id, node.rank_index, node.exit_reason
                )
                self._scaler.scale(plan)
                return
        logger.warning("node %s will not be relaunched", node.name)

    def _relaunch_node_group(self, group_idx: int):
        """Relaunch every member of a slice block in one scale plan
        (reference dist_job_manager.py:1128 _relaunch_node_group)."""
        members = [
            n
            for n in self._worker_manager.latest_nodes()
            if n.node_group == group_idx
        ]
        plan = ScalePlan()
        relaunched = []
        for m in members:
            new_node, p = self._worker_manager.relaunch_node(m)
            # The old incarnation gets torn down by this plan; its later
            # DELETED event must not trigger a second relaunch.
            m.relaunchable = False
            self._job_context.update_node(m)
            if new_node is None:
                continue
            new_node.node_group = group_idx
            new_node.relaunchable = True
            self._job_context.update_node(new_node)
            relaunched.append((m, new_node))
            plan.launch_nodes.extend(p.launch_nodes)
            plan.remove_nodes.extend(p.remove_nodes)
        logger.warning(
            "relaunching slice block %d: %s",
            group_idx,
            [f"{m.name}->{n.name}" for m, n in relaunched],
        )
        for m, n in relaunched:
            MasterEvents.node_relaunch(m.id, m.rank_index, m.exit_reason)
        if not plan.empty():
            self._scaler.scale(plan)

    def _should_relaunch(self, node: Node) -> bool:
        """Exit-reason relaunch policy (reference
        dist_job_manager.py:996 _should_relaunch).

        Each classified reason spends its own relaunch budget
        (common.constants.RELAUNCH_BUDGET_FACTOR via
        Node.is_unrecoverable_failure): preemptions are near-free,
        kills get double budget, OOM/hardware/software one budget
        (OOM additionally triggers the optimizer's memory bump and the
        strategy generator's remat escalation), fatal never relaunches.
        """
        if self._job_context.job_stage != JobStage.RUNNING:
            return False
        if not self._relaunch_on_worker_failure:
            return False
        if node.status == NodeStatus.SUCCEEDED:
            return False
        blocker = node.is_unrecoverable_failure()
        if blocker:
            logger.warning(
                "no relaunch for %s (%s): %s",
                node.name,
                node.exit_reason or "unclassified",
                blocker,
            )
            return False
        return True

    # ---- servicer surface (shared with LocalJobManager) ----------------------

    def _resolve_node(self, reported_id: int) -> Optional[Node]:
        """Map an agent-reported node id to the master's record, via the
        alias recorded at join time if the ids diverged."""
        for node_id in (reported_id, self._id_alias.get(reported_id)):
            if node_id is None:
                continue
            for manager in self._managers.values():
                node = manager.get_node(node_id)
                if node is not None:
                    return node
        return None

    def handle_node_joined(self, node_id: int, node_rank: int):
        # Direct id lookup across EVERY role manager first — a chief or
        # evaluator agent must never be mis-attributed to a same-rank
        # worker record.
        node = None
        for manager in self._managers.values():
            node = manager.get_node(node_id)
            if node is not None:
                break
        if node is None:
            # Agent ids are assigned by the backend; match the newest
            # live incarnation of the rank and remember the alias. Only
            # workers use backend-assigned ids this way (their ranks
            # come from the elastic rendezvous protocol).
            candidates = [
                n
                for n in self._worker_manager.nodes.values()
                if n.rank_index == node_rank and not n.is_end()
            ]
            if candidates:
                node = max(candidates, key=lambda n: n.id)
                self._id_alias[node_id] = node.id
        if node is None:
            node = Node(NodeType.WORKER, node_id, rank_index=node_rank)
            self._worker_manager.update_node(node)
        node.update_status(NodeStatus.RUNNING)
        node.heartbeat_time = time.time()
        self._job_context.update_node(node)

    def collect_node_heartbeat(
        self, node_id: int, timestamp: float
    ) -> List[DiagnosisAction]:
        node = self._resolve_node(node_id)
        if node is not None:
            node.heartbeat_time = timestamp
            node_id = node.id
        return self._job_context.drain_node_actions(node_id)

    def handle_node_failure(self, report: comm.NodeFailureReport):
        node = self._resolve_node(report.node_id)
        if node is None:
            return
        node.relaunch_count = max(node.relaunch_count, report.restart_count)
        # Classify from the agent's evidence (exit code + reason hint /
        # log markers); the watcher's container-status reason, if any,
        # stays authoritative.
        reason = classify_exit(report.exit_code, report.error_data)
        if reason and not node.exit_reason:
            node.exit_reason = reason
        if report.level == TrainingExceptionLevel.NODE_ERROR:
            self._observe_failure(
                node, node.exit_reason or NodeExitReason.KILLED
            )

    def handle_node_succeeded(self, node_id: int):
        node = self._resolve_node(node_id)
        if node is not None:
            node.reported_status = NodeStatus.SUCCEEDED

    def handle_reported_node_event(self, report: comm.NodeEventReport):
        logger.info(
            "node %d event %s: %s %s",
            report.node_id,
            report.event_type,
            report.reason,
            report.message,
        )
        if report.event_type == NodeEventType.NODE_CHECK_FAILED:
            node = self._resolve_node(report.node_id)
            if node is not None:
                self._observe_failure(
                    node,
                    NodeExitReason.HARDWARE_ERROR,
                    status=NodeStatus.BREAKDOWN,
                )

    def update_node_resource_usage(self, stats: comm.ResourceStats):
        node = self._resolve_node(stats.node_id)
        if node is not None:
            node.update_from_resource_stats(stats.cpu_percent, stats.memory_mb)

    def update_ckpt_step(self, node_id: int, step: int, committed: bool):
        self._job_context.update_ckpt_step(node_id, step, committed)

    def get_committed_ckpt_step(self) -> int:
        return self._job_context.committed_ckpt_step()

    def set_strategy_generator(self, generator):
        self._strategy_generator = generator

    def get_parallel_config(self) -> Optional[comm.ParallelConfig]:
        generator = getattr(self, "_strategy_generator", None)
        if generator is None:
            return None
        return generator.generate()

    def get_job_detail(self) -> comm.JobDetailResponse:
        nodes = {}
        for manager in self._managers.values():
            for node_id, node in manager.nodes.items():
                nodes[node_id] = {
                    "type": node.type,
                    "rank": node.rank_index,
                    "status": node.status,
                    "relaunch_count": node.relaunch_count,
                    "host": node.host_name,
                }
        return comm.JobDetailResponse(
            job_name=self._job_name,
            stage=self._job_context.job_stage,
            nodes=nodes,
        )

    # ---- run-loop queries ----------------------------------------------------

    def _success_gating_managers(self):
        """Roles whose completion gates job success: workers and the
        chief. Evaluators are auxiliary — a finished training job tears
        them down rather than waiting on them."""
        return [
            m
            for t, m in self._managers.items()
            if t in (NodeType.WORKER, NodeType.CHIEF)
        ]

    def all_workers_exited(self) -> bool:
        return all(
            m.all_nodes_exited() for m in self._success_gating_managers()
        )

    def all_workers_succeeded(self) -> bool:
        return all(
            m.all_nodes_succeeded()
            for m in self._success_gating_managers()
        )

    def all_running_node_hanged(self) -> bool:
        running = self._all_running_nodes()
        if not running:
            return False
        now = time.time()
        return all(
            n.heartbeat_time > 0
            and now - n.heartbeat_time > self._heartbeat_timeout_s / 2
            for n in running
        )

    def restart_worker_processes(self, reason: str):
        """Queue an in-place worker restart on every running node."""
        for node in self._worker_manager.running_nodes():
            self._job_context.enqueue_action(
                NodeAction(
                    instance=node.id,
                    node_id=node.id,
                    reason=reason,
                )
            )
