"""Kubernetes Pod scaler for TPU worker hosts.

Parity: reference dlrover/python/master/scaler/pod_scaler.py:84 (891 LoC)
— the master converges the cluster to a ScalePlan by creating/deleting
worker Pods directly against the k8s API through a background queue.

TPU specifics: one worker Pod per TPU host; the Pod requests
``google.com/tpu`` chips and carries a TPU topology nodeSelector (GKE
schedules it onto the right slice host); agent env (NODE_ID/NODE_RANK/
MASTER_ADDR) is injected so the launched `dlrover_tpu.run` agent dials
home.
"""

import queue
import threading
from typing import Dict, Optional

from dlrover_tpu.common.constants import NodeEnv
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.node import Node, NodeResource
from dlrover_tpu.master.scaler.base_scaler import ScalePlan, Scaler
from dlrover_tpu.master.scheduler.k8s_client import K8sApi, get_k8s_api

_QUEUE_STOP = object()


def pod_name(job_name: str, node: Node) -> str:
    return f"{job_name}-worker-{node.id}"


def build_worker_pod_manifest(
    job_name: str,
    node: Node,
    master_addr: str,
    image: str,
    command: Optional[list] = None,
    tpu_topology: str = "",
) -> Dict:
    res: NodeResource = node.config_resource
    limits: Dict[str, str] = {}
    if res.cpu > 0:
        limits["cpu"] = str(res.cpu)
    if res.memory_mb > 0:
        limits["memory"] = f"{int(res.memory_mb)}Mi"
    if res.tpu_chips > 0:
        limits["google.com/tpu"] = str(res.tpu_chips)
    node_selector: Dict[str, str] = {}
    if res.tpu_type:
        node_selector["cloud.google.com/gke-tpu-accelerator"] = res.tpu_type
    if tpu_topology:
        node_selector["cloud.google.com/gke-tpu-topology"] = tpu_topology
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": pod_name(job_name, node),
            "labels": {
                "app": "dlrover-tpu",
                "job-name": job_name,
                "node-id": str(node.id),
                "rank-index": str(node.rank_index),
                "node-type": node.type,
            },
        },
        "spec": {
            "restartPolicy": "Never",
            "nodeSelector": node_selector,
            "containers": [
                {
                    "name": "worker",
                    "image": image,
                    "command": command
                    or ["python", "-m", "dlrover_tpu.run"],
                    "env": [
                        {"name": NodeEnv.NODE_ID, "value": str(node.id)},
                        {
                            "name": NodeEnv.NODE_RANK,
                            "value": str(node.rank_index),
                        },
                        {"name": NodeEnv.MASTER_ADDR, "value": master_addr},
                        {"name": NodeEnv.JOB_NAME, "value": job_name},
                    ],
                    "resources": {"limits": limits, "requests": limits},
                }
            ],
        },
    }


class PodScaler(Scaler):
    def __init__(
        self,
        job_name: str,
        namespace: str = "default",
        master_addr: str = "",
        image: str = "dlrover-tpu:latest",
        command: Optional[list] = None,
        tpu_topology: str = "",
        api: Optional[K8sApi] = None,
    ):
        super().__init__(job_name)
        self._namespace = namespace
        self._master_addr = master_addr
        self._image = image
        self._command = command
        self._tpu_topology = tpu_topology
        self._api = api or get_k8s_api()
        self._queue: "queue.Queue" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._create_attempts: Dict[int, int] = {}
        self._max_create_attempts = 5
        self._retry_delay_s = 5.0
        # Node ids removed since their (possibly failed) create: a retry
        # must not resurrect a pod that was scaled away in the meantime.
        self._removed_ids: set = set()
        self._retry_timers: list = []
        self._stopped = False

    def set_master_addr(self, addr: str):
        if not self._master_addr:
            self._master_addr = addr

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._worker_loop, name="pod-scaler", daemon=True
            )
            self._thread.start()

    def stop(self):
        self._stopped = True
        for timer in self._retry_timers:
            timer.cancel()
        self._queue.put(_QUEUE_STOP)

    def scale(self, plan: ScalePlan):
        """Queue the plan; pod API calls run on the scaler thread so a
        slow API server never blocks event processing (reference
        pod_scaler queue design)."""
        self._queue.put(plan)

    def scale_now(self, plan: ScalePlan):
        """Synchronous variant for tests/shutdown paths."""
        self._apply(plan)

    def _worker_loop(self):
        while True:
            item = self._queue.get()
            if item is _QUEUE_STOP:
                return
            try:
                self._apply(item)
            except Exception:
                logger.exception("scale plan application failed")

    def _apply(self, plan: ScalePlan):
        retry = ScalePlan()
        for node in plan.launch_nodes:
            if node.id in self._removed_ids:
                continue  # scaled away while a retry was pending
            manifest = build_worker_pod_manifest(
                self._job_name,
                node,
                self._master_addr,
                self._image,
                self._command,
                self._tpu_topology,
            )
            if self._api.create_pod(self._namespace, manifest):
                self._create_attempts.pop(node.id, None)
                continue
            # The scale() contract is convergence: a transient API-server
            # failure must not permanently orphan the rank.
            attempts = self._create_attempts.get(node.id, 0) + 1
            self._create_attempts[node.id] = attempts
            if attempts < self._max_create_attempts:
                logger.warning(
                    "pod create for %s failed (attempt %d); will retry",
                    node.name,
                    attempts,
                )
                retry.launch_nodes.append(node)
            else:
                logger.error(
                    "pod create for %s failed %d times; giving up",
                    node.name,
                    attempts,
                )
        for node in plan.remove_nodes:
            self._removed_ids.add(node.id)
            self._api.delete_pod(
                self._namespace, pod_name(self._job_name, node)
            )
        if retry.launch_nodes and not self._stopped:
            timer = threading.Timer(
                self._retry_delay_s, self._queue.put, args=(retry,)
            )
            timer.daemon = True
            timer.start()
            self._retry_timers = [
                t for t in self._retry_timers if t.is_alive()
            ] + [timer]
