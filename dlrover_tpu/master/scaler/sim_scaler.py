"""SimClusterScaler: a working, non-k8s ScalePlan backend.

The ``master/scaler`` package shipped with an ABC and two k8s-facing
scalers that no test could run; the simulator backend
(``testing/sim_cluster.py``) exists but needs the whole
cluster/watcher apparatus. This scaler is the missing middle: a
self-contained in-memory backend implementing the
:class:`~dlrover_tpu.master.scaler.base_scaler.Scaler` contract —
idempotent convergence of ``node_group_resources``, explicit
``launch_nodes`` / ``remove_nodes``, capacity bounds, and an
``on_scale`` callback so a harness (the autoscale soak, the contract
tests) can observe every transition without polling.

It is the actuation substrate of the §30 closed-loop autoscaler's
sim-cluster validation: evict-and-replace plans, world resizes and the
bench's static/autoscaled A/B all land here through real ScalePlans.
"""

import time
from typing import Callable, Dict, List, Optional

from dlrover_tpu.common.constants import NodeStatus, NodeType
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.node import Node
from dlrover_tpu.master.scaler.base_scaler import (
    ScalePlan,
    Scaler,
    new_node_id_iter,
)


class SimClusterScaler(Scaler):
    """In-memory Scaler: converges a node table to each ScalePlan.

    Semantics match the k8s scalers' contract:

    - ``node_group_resources[type].count`` is a declarative group size:
      missing seats are launched (lowest free rank first), surplus
      seats are removed (highest rank first) — applying the same plan
      twice is a no-op (idempotence is part of the ABC contract).
    - ``launch_nodes`` / ``remove_nodes`` are explicit singles (evict-
      and-replace, hot migration); launching an already-present node id
      or removing an absent one is a no-op, not an error.
    - ``capacity`` bounds the total node count (a sim "cluster full"):
      launches beyond it are dropped and counted, mirroring a cloud
      that stops scheduling — callers observe the shortfall through
      ``alive_nodes()``, exactly like a pending-timeout path would.
    """

    def __init__(
        self,
        job_name: str,
        capacity: int = 64,
        on_scale: Optional[Callable[[str, List[Node], List[Node]], None]]
        = None,
        clock: Callable[[], float] = time.time,
    ):
        super().__init__(job_name)
        self._capacity = max(int(capacity), 1)
        self._on_scale = on_scale
        self._clock = clock
        self._nodes: Dict[int, Node] = {}
        self._id_iter = new_node_id_iter(0)
        self.launches_dropped = 0
        self.plans_applied = 0

    # ---- backend surface ---------------------------------------------------

    def next_node_id(self) -> int:
        with self._lock:
            return next(self._id_iter)

    def alive_nodes(self, node_type: Optional[str] = None) -> List[Node]:
        with self._lock:
            nodes = [
                n for n in self._nodes.values()
                if n.status not in NodeStatus.end_states()
                and (node_type is None or n.type == node_type)
            ]
        return sorted(nodes, key=lambda n: (n.type, n.rank_index, n.id))

    def world_size(self, node_type: str = NodeType.WORKER) -> int:
        return len(self.alive_nodes(node_type))

    def find_rank(self, rank: int,
                  node_type: str = NodeType.WORKER) -> Optional[Node]:
        for node in self.alive_nodes(node_type):
            if node.rank_index == rank:
                return node
        return None

    # ---- the Scaler contract -----------------------------------------------

    def scale(self, plan: ScalePlan):
        launched: List[Node] = []
        removed: List[Node] = []
        with self._lock:
            for node in plan.remove_nodes:
                gone = self._remove_locked(node.id)
                if gone is not None:
                    removed.append(gone)
            for node in plan.launch_nodes:
                live = self._launch_locked(node)
                if live is not None:
                    launched.append(live)
            for group_name, group in plan.node_group_resources.items():
                up, down = self._converge_group_locked(group_name, group)
                launched.extend(up)
                removed.extend(down)
            self.plans_applied += 1
        if (launched or removed) and self._on_scale is not None:
            self._on_scale(self._job_name, launched, removed)

    # ---- internals ---------------------------------------------------------

    def _launch_locked(self, node: Node) -> Optional[Node]:
        if node.id in self._nodes:
            return None  # idempotent re-launch
        alive = sum(
            1 for n in self._nodes.values()
            if n.status not in NodeStatus.end_states()
        )
        if alive >= self._capacity:
            self.launches_dropped += 1
            logger.warning(
                "sim scaler: capacity %d full; dropping launch of "
                "node %d", self._capacity, node.id,
            )
            return None
        live = Node(
            node_type=node.type,
            node_id=node.id,
            rank_index=node.rank_index,
            name=node.name or f"{node.type}-{node.id}",
            status=NodeStatus.RUNNING,
            config_resource=node.config_resource,
        )
        live.create_time = self._clock()
        live.host_name = f"sim-host-{node.id}"
        self._nodes[live.id] = live
        return live

    def _remove_locked(self, node_id: int) -> Optional[Node]:
        node = self._nodes.pop(node_id, None)
        if node is None:
            return None
        node.status = NodeStatus.DELETED
        return node

    def _converge_group_locked(self, node_type: str, group):
        alive = sorted(
            (
                n for n in self._nodes.values()
                if n.type == node_type
                and n.status not in NodeStatus.end_states()
            ),
            key=lambda n: n.rank_index,
        )
        delta = group.count - len(alive)
        launched: List[Node] = []
        removed: List[Node] = []
        if delta > 0:
            used_ranks = {n.rank_index for n in alive}
            rank = 0
            for _ in range(delta):
                while rank in used_ranks:
                    rank += 1
                used_ranks.add(rank)
                live = self._launch_locked(Node(
                    node_type,
                    next(self._id_iter),
                    rank_index=rank,
                    config_resource=group.node_resource,
                ))
                if live is not None:
                    launched.append(live)
        elif delta < 0:
            for node in sorted(alive, key=lambda n: -n.rank_index)[:-delta]:
                gone = self._remove_locked(node.id)
                if gone is not None:
                    removed.append(gone)
        return launched, removed
