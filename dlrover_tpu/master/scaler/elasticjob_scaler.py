"""ScalePlan CRD scaler: declare scale intent for an external operator.

Parity: reference dlrover/python/master/scaler/elasticjob_scaler.py:118-255
(ElasticJobScaler + ScalePlanCrd) — instead of touching pods directly,
the master emits a ScalePlan custom resource that the ElasticJob operator
(or a GKE JobSet controller in the TPU deployment) reconciles. Useful
when pod creation requires cluster-level privileges the master lacks.
"""

import itertools
import time
from typing import Dict, Optional

from dlrover_tpu.common.log import logger
from dlrover_tpu.master.scaler.base_scaler import ScalePlan, Scaler
from dlrover_tpu.master.scheduler.k8s_client import (
    ELASTICJOB_GROUP,
    ELASTICJOB_VERSION,
    SCALEPLAN_PLURAL,
    K8sApi,
    get_k8s_api,
)


def scale_plan_crd(
    job_name: str, plan: ScalePlan, index, epoch: str = ""
) -> Dict:
    group_specs = {}
    for role, group in plan.node_group_resources.items():
        group_specs[role] = {
            "replicas": group.count,
            "resource": {
                "cpu": group.node_resource.cpu,
                "memory_mb": group.node_resource.memory_mb,
                "tpu_chips": group.node_resource.tpu_chips,
                "tpu_type": group.node_resource.tpu_type,
            },
        }
    return {
        "apiVersion": f"{ELASTICJOB_GROUP}/{ELASTICJOB_VERSION}",
        "kind": "ScalePlan",
        "metadata": {
            # The epoch token keeps names unique across master restarts:
            # a fresh master's counter restarts at 0 and a bare index
            # would collide with CRs from the previous incarnation.
            "name": f"{job_name}-scaleplan-{epoch}{index}",
            "labels": {"job-name": job_name},
        },
        "spec": {
            "ownerJob": job_name,
            "replicaResourceSpecs": group_specs,
            "createPods": [
                {
                    "name": f"{job_name}-worker-{n.id}",
                    "type": n.type,
                    "id": n.id,
                    "rankIndex": n.rank_index,
                }
                for n in plan.launch_nodes
            ],
            "removePods": [
                f"{job_name}-worker-{n.id}" for n in plan.remove_nodes
            ],
        },
    }


class ElasticJobScaler(Scaler):
    def __init__(
        self,
        job_name: str,
        namespace: str = "default",
        api: Optional[K8sApi] = None,
    ):
        super().__init__(job_name)
        self._namespace = namespace
        self._api = api or get_k8s_api()
        self._index = itertools.count(0)
        self._epoch = f"{int(time.time())}-"

    def scale(self, plan: ScalePlan):
        if plan.empty():
            return
        body = scale_plan_crd(
            self._job_name, plan, next(self._index), self._epoch
        )
        if not self._api.create_custom_object(
            self._namespace, SCALEPLAN_PLURAL, body
        ):
            logger.error("ScalePlan CR emit failed")
