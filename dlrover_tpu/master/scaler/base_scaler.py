"""Scaler abstraction: apply a ScalePlan to the cluster backend.

Parity: reference dlrover/python/master/scaler/base_scaler.py (Scaler,
ScalePlan). A ScalePlan is the master's declarative "make the cluster look
like this" order: per-role group sizes plus explicit node launches/removals.
Backends: the in-memory simulator (testing/sim_cluster.py), the k8s Pod
scaler (pod_scaler.py, reference pod_scaler.py:84), and the GKE JobSet
flavor for TPU slices.
"""

import abc
import threading
from dataclasses import dataclass, field
from typing import Dict, List

from dlrover_tpu.common.node import Node, NodeGroupResource


@dataclass
class ScalePlan:
    """Declarative scale order emitted by the job manager / auto-scaler.

    ``node_group_resources`` sets the target size+resource of each role
    group; ``launch_nodes`` / ``remove_nodes`` are explicit singles (used
    for relaunch and hot migration, reference base_scaler.py ScalePlan).
    """

    node_group_resources: Dict[str, NodeGroupResource] = field(
        default_factory=dict
    )
    launch_nodes: List[Node] = field(default_factory=list)
    remove_nodes: List[Node] = field(default_factory=list)
    ps_addrs: List[str] = field(default_factory=list)

    def empty(self) -> bool:
        return (
            not self.node_group_resources
            and not self.launch_nodes
            and not self.remove_nodes
        )

    def merge(self, other: "ScalePlan"):
        self.node_group_resources.update(other.node_group_resources)
        self.launch_nodes.extend(other.launch_nodes)
        self.remove_nodes.extend(other.remove_nodes)
        if other.ps_addrs:
            self.ps_addrs = other.ps_addrs


class Scaler(abc.ABC):
    """Applies ScalePlans to a concrete cluster backend."""

    def __init__(self, job_name: str):
        self._job_name = job_name
        self._lock = threading.Lock()

    def start(self):
        pass

    def stop(self):
        pass

    def set_master_addr(self, addr: str):
        """Late-bind the master's RPC address (known only once the server
        starts) into whatever the backend injects into workers. No-op for
        backends that don't launch agent processes."""

    @abc.abstractmethod
    def scale(self, plan: ScalePlan):
        """Make the backend converge to the plan. Must be idempotent."""


def new_node_id_iter(start: int = 0):
    """Monotonic node-id allocator shared by scalers."""
    next_id = start
    while True:
        yield next_id
        next_id += 1
