"""Master-side rendezvous.

Parity: reference dlrover/python/master/elastic_training/rdzv_manager.py
(RendezvousManager:69, ElasticTrainingRendezvousManager:497,
NetworkCheckRendezvousManager:599). Re-designed for JAX: a completed round
hands agents the ``jax.distributed.initialize`` triple (coordinator node,
process count, per-node process id) instead of a torch process-group world.

TPU specifics: the ``node_unit`` constraint generalizes to *legal topology
sizes* — a TPU slice can only form meshes whose host count divides the
physical topology, so a round is truncated to the largest legal node count
<= the waiting set.
"""

import math
import statistics
import threading
import time
from abc import ABC, abstractmethod
from dataclasses import dataclass
from typing import Callable, Dict, List, Set, Tuple

from dlrover_tpu.common.constants import NetworkCheckConstant, RendezvousName
from dlrover_tpu.common.log import logger


@dataclass
class RendezvousParameters:
    min_nodes: int = 1
    max_nodes: int = 1
    node_unit: int = 1
    waiting_timeout: float = 30.0  # secs after min reached before closing
    join_timeout: float = 600.0


@dataclass
class _WaitingNode:
    node_id: int
    node_rank: int
    local_world_size: int
    join_time: float
    node_ip: str = ""
    node_group: int = -1  # TPU slice/block index; -1 = ungrouped


def default_legal_node_counts(max_nodes: int, node_unit: int) -> List[int]:
    """Node counts that can form a legal mesh: multiples of node_unit."""
    counts = [
        n for n in range(node_unit, max_nodes + 1, node_unit)
    ]
    return counts or [max_nodes]


def _rdzv_metrics():
    """Rendezvous observability (PR-1 registry, scraped at /metrics):
    rounds completed, nodes currently waiting, and time-to-quorum per
    rendezvous domain."""
    from dlrover_tpu.observability.registry import default_registry

    reg = default_registry()
    return {
        "rounds": reg.counter(
            "rdzv_rounds_total",
            "completed rendezvous rounds",
            labelnames=("rdzv",),
        ),
        "waiting": reg.gauge(
            "rdzv_nodes_waiting",
            "nodes currently waiting in the rendezvous",
            labelnames=("rdzv",),
        ),
        "quorum": reg.histogram(
            "rdzv_time_to_quorum_seconds",
            "first join of a round to round completion",
            labelnames=("rdzv",),
        ),
        # World size of the latest completed round, next to the quorum
        # histogram so "time-to-quorum vs world size" reads off one
        # scrape (§32: the load harness sweeps {8,64,256,1024}).
        "world": reg.gauge(
            "rdzv_world_size",
            "node count of the latest completed world",
            labelnames=("rdzv",),
        ),
    }


class RendezvousManager(ABC):
    """Holds the waiting set and completed rounds for one rendezvous name."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.RLock()
        self._params = RendezvousParameters()
        self._waiting: Dict[int, _WaitingNode] = {}  # node_rank -> record
        self._latest_world: Dict[int, int] = {}  # rank -> local_world_size
        self._rdzv_round = 0
        self._round_start_time = 0.0
        self._alive_nodes: Set[int] = set()
        self._node_times: Dict[int, float] = {}
        self._legal_counts_fn: Callable[[int, int], List[int]] = (
            default_legal_node_counts
        )
        self._metrics = _rdzv_metrics()

    # ---- configuration -----------------------------------------------------

    def update_rdzv_params(
        self,
        min_nodes: int,
        max_nodes: int,
        waiting_timeout: float = 30.0,
        node_unit: int = 1,
        join_timeout: float = 600.0,
    ):
        with self._lock:
            self._params = RendezvousParameters(
                min_nodes=min_nodes,
                max_nodes=max_nodes,
                node_unit=node_unit,
                waiting_timeout=waiting_timeout,
                join_timeout=join_timeout,
            )

    def set_legal_counts_fn(self, fn: Callable[[int, int], List[int]]):
        """Install slice-topology-aware legal node counts."""
        self._legal_counts_fn = fn

    def set_node_unit(self, node_unit: int):
        with self._lock:
            if node_unit >= 1:
                self._params.node_unit = node_unit

    def add_alive_node(self, node_rank: int):
        with self._lock:
            self._alive_nodes.add(node_rank)

    def remove_alive_node(self, node_rank: int):
        with self._lock:
            self._alive_nodes.discard(node_rank)
            # A dead node must not keep a pending round open.
            if node_rank in self._waiting:
                del self._waiting[node_rank]
                self._metrics["waiting"].set(
                    len(self._waiting), rdzv=self.name
                )

    def restore_committed_world(self, rdzv_round: int, world: Dict[int, int]):
        """Master-journal rehydration (DESIGN.md §37): a restarted
        master re-serves the last committed world at the right round so
        riding-through workers polling ``get_comm_world`` see their own
        world again instead of an empty round-0 — and a genuinely new
        join still starts the next round above the journaled one."""
        with self._lock:
            if rdzv_round + 1 <= self._rdzv_round:
                return
            self._rdzv_round = rdzv_round + 1
            self._latest_world = {int(r): int(n) for r, n in world.items()}

    def _record_round_completed(self):
        """Call under self._lock, right after a round's waiters moved
        into the completed world."""
        self._metrics["rounds"].inc(rdzv=self.name)
        self._metrics["waiting"].set(len(self._waiting), rdzv=self.name)
        self._metrics["world"].set(
            len(self._latest_world), rdzv=self.name
        )
        if self._round_start_time > 0:
            self._metrics["quorum"].observe(
                max(time.time() - self._round_start_time, 0.0),
                rdzv=self.name,
            )

    # ---- join / query ------------------------------------------------------

    def join_rendezvous(
        self,
        node_id: int,
        node_rank: int,
        local_world_size: int,
        node_ip: str = "",
        node_group: int = -1,
    ) -> int:
        with self._lock:
            if not self._waiting:
                self._round_start_time = time.time()
            self._waiting[node_rank] = _WaitingNode(
                node_id=node_id,
                node_rank=node_rank,
                local_world_size=local_world_size,
                join_time=time.time(),
                node_ip=node_ip,
                node_group=node_group,
            )
            self._metrics["waiting"].set(len(self._waiting), rdzv=self.name)
            logger.info(
                "rdzv[%s] round %d: node rank %d joined (%d waiting)",
                self.name,
                self._rdzv_round,
                node_rank,
                len(self._waiting),
            )
            return self._rdzv_round

    def num_nodes_waiting(self) -> int:
        """Non-zero signals running agents that membership wants to change
        (reference rdzv_manager.py num_nodes_waiting / training.py
        _membership_changed)."""
        with self._lock:
            return len(self._waiting)

    @abstractmethod
    def get_comm_world(
        self, node_rank: int
    ) -> Tuple[int, int, Dict[int, int]]:
        """Return (round, group, world) — world empty if round incomplete."""

    # ---- round completion --------------------------------------------------

    def _legal_world_size(self, waiting_count: int) -> int:
        p = self._params
        counts = [
            c
            for c in self._legal_counts_fn(p.max_nodes, p.node_unit)
            if c <= waiting_count
        ]
        return max(counts) if counts else 0

    def _round_ready(self) -> int:
        """Return the node count for a completable round, else 0."""
        p = self._params
        n = len(self._waiting)
        if n == 0:
            return 0
        if n >= p.max_nodes:
            return self._legal_world_size(p.max_nodes)
        elapsed = time.time() - self._round_start_time
        if n >= p.min_nodes and elapsed >= p.waiting_timeout:
            return self._legal_world_size(n)
        return 0

    def _grouped(self) -> bool:
        return any(w.node_group >= 0 for w in self._waiting.values())

    def _select_waiters(self, size: int) -> List[_WaitingNode]:
        """Round participants, longest-waiting first (lowest rank on tie
        so a flapping late joiner cannot evict a stable participant).

        With node groups (TPU slices), only COMPLETE groups are eligible
        — an ICI slice cannot run collectives with a missing host, and
        holding back an incomplete block keeps the other blocks training
        while its replacement host arrives. ``node_unit`` is the hosts-
        per-slice count."""
        waiters = sorted(
            self._waiting.values(),
            key=lambda w: (w.join_time, w.node_rank),
        )
        unit = self._params.node_unit
        if unit <= 1 or not self._grouped():
            return waiters[:size]
        by_group: Dict[int, List[_WaitingNode]] = {}
        for w in waiters:
            by_group.setdefault(w.node_group, []).append(w)
        complete = [
            members[:unit]
            for members in by_group.values()
            if len(members) >= unit
        ]
        complete.sort(key=lambda g: min(w.join_time for w in g))
        chosen: List[_WaitingNode] = []
        for members in complete:
            if len(chosen) + unit > size:
                break
            chosen.extend(members)
        return chosen


class ElasticTrainingRendezvousManager(RendezvousManager):
    """The training rendezvous: single group 0, ranks 0..n-1.

    Reference: rdzv_manager.py:497 (ElasticTrainingRendezvousManager)."""

    def __init__(self):
        super().__init__(RendezvousName.TRAINING)
        self._topology_sorter = None
        self._latest_groups = {}

    def latest_node_groups(self):
        """node_rank -> node_group of the latest completed world."""
        with self._lock:
            return dict(self._latest_groups)

    def get_comm_world_and_groups(self, node_rank: int):
        """(round, group, world, node_groups) under ONE lock hold — a
        round completing between separate calls would pair round-N's
        world with round-N+1's groups."""
        with self._lock:
            rdzv_round, group, world = self.get_comm_world(node_rank)
            return rdzv_round, group, world, dict(self._latest_groups)

    def set_topology_sorter(self, sorter):
        """Install a TopologySorter (net_topology.DpTopologySorter): the
        completed world's ORDER then follows physical blocks, and agents
        assign process ids in that order."""
        self._topology_sorter = sorter

    def _order_world(self, world: Dict[int, int], chosen) -> Dict[int, int]:
        groups = {w.node_rank: w.node_group for w in chosen}
        self._latest_groups = groups
        if any(g >= 0 for g in groups.values()):
            # Group-major order: each slice's hosts are contiguous in
            # the rank order, so dp/allreduce neighbors ride ICI and
            # only block boundaries cross DCN.
            order = sorted(world, key=lambda r: (groups.get(r, -1), r))
            return {rank: world[rank] for rank in order}
        if self._topology_sorter is None:
            return dict(sorted(world.items()))
        node_ips = {w.node_rank: w.node_ip for w in chosen}
        try:
            order = self._topology_sorter.sort(world, node_ips)
        except Exception:
            logger.exception("topology sort failed; numeric order used")
            return dict(sorted(world.items()))
        return {rank: world[rank] for rank in order}

    def get_comm_world(self, node_rank: int):
        with self._lock:
            if node_rank in self._latest_world and node_rank not in self._waiting:
                return self._rdzv_round - 1, 0, dict(self._latest_world)
            size = self._round_ready()
            chosen = self._select_waiters(size) if size else []
            if chosen:
                world = {
                    w.node_rank: w.local_world_size for w in chosen
                }
                self._latest_world = self._order_world(world, chosen)
                for w in chosen:
                    del self._waiting[w.node_rank]
                self._record_round_completed()
                if self._waiting:
                    # Unchosen nodes start the next pending round now.
                    self._round_start_time = time.time()
                self._rdzv_round += 1
                logger.info(
                    "rdzv[%s] round %d completed: world=%s",
                    self.name,
                    self._rdzv_round - 1,
                    self._latest_world,
                )
                from dlrover_tpu.training_event import MasterEvents

                MasterEvents.rdzv_round(
                    self.name, self._rdzv_round - 1, len(self._latest_world)
                )
            if (
                node_rank in self._latest_world
                and node_rank not in self._waiting
            ):
                return self._rdzv_round - 1, 0, dict(self._latest_world)
            # Waiting for the next round: a node that RE-joined (its
            # worker died and it came back) must never be handed the
            # stale world it used to belong to — that world may contain
            # dead peers and would make it restart-loop against them.
            return self._rdzv_round, 0, {}


class NetworkCheckRendezvousManager(RendezvousManager):
    """Rendezvous for the node/network check (reference rdzv_manager.py:599).

    Round 0 groups nodes in pairs to run collective probes; round 1 pairs
    each suspect with a known-healthy node so a failing pair is bisected to
    the faulty member. Stragglers are nodes slower than
    ``straggler_ratio x median``.
    """

    def __init__(self):
        super().__init__(RendezvousName.NETWORK_CHECK)
        self._node_status: Dict[int, bool] = {}
        self._node_groups: List[Dict[int, int]] = []
        self._check_round = 0
        self._stragglers: Set[int] = set()
        self._reported: Dict[int, float] = {}
        # check_round -> evaluated fault list (evaluation happens eagerly
        # when the last report of a round arrives, so agents can poll for
        # a round's verdict without racing the round transition).
        self._eval_results: Dict[int, List[int]] = {}

    def _check_concluded(self) -> bool:
        """Final verdict reached: round 0 clean, or round 1 evaluated."""
        return (
            self._check_round == 0 and 0 in self._eval_results
        ) or 1 in self._eval_results

    def join_rendezvous(
        self,
        node_id: int,
        node_rank: int,
        local_world_size: int,
        node_ip: str = "",
        node_group: int = -1,
    ) -> int:
        # A join after a concluded check starts a FRESH check cycle
        # (e.g. a relaunched node re-running its health probes, or a
        # scheduled re-check); stale verdicts must not leak into it.
        with self._lock:
            if self._check_concluded():
                self._reset_check_locked()
        return super().join_rendezvous(
            node_id, node_rank, local_world_size, node_ip, node_group
        )

    def get_comm_world(self, node_rank: int):
        with self._lock:
            if not self._node_groups or all(
                node_rank not in g for g in self._node_groups
            ):
                size = self._round_ready()
                if size:
                    chosen = sorted(
                        self._waiting.values(),
                        key=lambda w: (w.join_time, w.node_rank),
                    )[:size]
                    world = {w.node_rank: w.local_world_size for w in chosen}
                    for w in chosen:
                        del self._waiting[w.node_rank]
                    # World BEFORE the completion record (training-
                    # manager ordering): the rdzv_world_size gauge
                    # must describe the round that just formed.
                    self._latest_world = dict(sorted(world.items()))
                    self._record_round_completed()
                    self._node_groups = self._group_nodes(
                        self._check_round, self._latest_world
                    )
                    self._reported.clear()
                    self._rdzv_round += 1
                    logger.info(
                        "network-check round %d groups: %s",
                        self._check_round,
                        self._node_groups,
                    )
            for group_idx, group in enumerate(self._node_groups):
                if node_rank in group:
                    return self._rdzv_round - 1, group_idx, dict(group)
            return self._rdzv_round, 0, {}

    @staticmethod
    def _pair_adjacent(
        ranks: List[int], world: Dict[int, int]
    ) -> List[Dict[int, int]]:
        """Pairs (0,1) (2,3) ...; an odd node joins the last group."""
        groups: List[Dict[int, int]] = []
        for i in range(0, len(ranks) - 1, 2):
            groups.append({r: world[r] for r in (ranks[i], ranks[i + 1])})
        if len(ranks) % 2 == 1:
            if groups:
                groups[-1][ranks[-1]] = world[ranks[-1]]
            else:
                groups.append({ranks[-1]: world[ranks[-1]]})
        return groups

    def _pair_suspects(
        self, suspects: List[int], healthy: List[int], world
    ) -> List[Dict[int, int]]:
        """Each suspect pairs with a healthy node (bisection); leftover
        healthy nodes pair adjacently; a partnerless suspect probes
        solo."""
        groups: List[Dict[int, int]] = []
        pool = list(healthy)
        for s in suspects:
            if pool:
                h = pool.pop(0)
                groups.append({s: world[s], h: world[h]})
            else:
                groups.append({s: world[s]})
        groups.extend(self._pair_adjacent(pool, world))
        return groups

    def _group_nodes(
        self, check_round: int, world: Dict[int, int]
    ) -> List[Dict[int, int]]:
        ranks = sorted(world)
        if check_round == 0 or not self._node_status:
            return self._pair_adjacent(ranks, world)
        suspects = [r for r in ranks if not self._node_status.get(r, True)]
        healthy = [r for r in ranks if self._node_status.get(r, True)]
        return self._pair_suspects(suspects, healthy, world)

    def report_network_check_result(
        self, node_rank: int, succeeded: bool, elapsed: float
    ):
        with self._lock:
            self._reported[node_rank] = elapsed if succeeded else math.inf
            # Round 0: failure marks the node suspect. Round 1: the verdict
            # of the suspect+healthy pairing is final for this node.
            self._node_status[node_rank] = succeeded
            self._maybe_evaluate_round()

    def _maybe_evaluate_round(self):
        """Evaluate the current check round once every node reported."""
        expected = set(self._latest_world)
        if not expected or not (set(self._reported) >= expected):
            return
        if self._check_round in self._eval_results:
            return
        suspects = {r for r, ok in self._node_status.items() if not ok}
        self._evaluate_stragglers()  # only ever on a COMPLETE report set
        if self._check_round == 0 and suspects:
            # bisection round needed; no verdict yet
            self._eval_results[0] = []
            self._check_round = 1
            self._node_groups = []  # force suspect+healthy regrouping
            self._reported = {}
            logger.info(
                "network check round 0: suspects %s; running verification "
                "round",
                sorted(suspects),
            )
        else:
            self._eval_results[self._check_round] = sorted(suspects)
            logger.info(
                "network check round %d verdict: faults=%s",
                self._check_round,
                sorted(suspects),
            )

    def check_fault_node(self) -> Tuple[List[int], int, bool]:
        """Return (faults_of_last_evaluated_round, last_evaluated_round,
        needs_round2). last_evaluated_round == -1 while nothing concluded."""
        with self._lock:
            if not self._eval_results:
                return [], -1, False
            last = max(self._eval_results)
            needs_round2 = self._check_round == 1 and 1 not in self._eval_results
            return list(self._eval_results[last]), last, needs_round2

    def _evaluate_stragglers(self):
        """Called under self._lock, ONLY when a round's reports are
        complete — a partial report set would produce false positives.
        Replace (not accumulate) so a later full round corrects earlier
        transients."""
        times = {
            r: t
            for r, t in self._reported.items()
            if not math.isinf(t) and t > 0
        }
        if len(times) < 2:
            return
        med = statistics.median(times.values())
        if med <= 0:
            return
        ratio = NetworkCheckConstant.STRAGGLER_RATIO
        self._stragglers = {r for r, t in times.items() if t > ratio * med}

    def check_straggler(self) -> List[int]:
        with self._lock:
            return sorted(self._stragglers)

    def reset_check(self):
        with self._lock:
            self._reset_check_locked()

    def _reset_check_locked(self):
        self._check_round = 0
        self._node_status.clear()
        self._node_groups = []
        self._latest_world = {}
        self._stragglers.clear()
        self._reported.clear()
        self._eval_results.clear()


class GroupCheckPhase:
    INTRA = "intra"
    INTRA_DIAG = "intra_diag"
    INTER = "inter"
    INTER_DIAG = "inter_diag"


class GroupNetworkCheckRendezvousManager(NetworkCheckRendezvousManager):
    """Slice-aware network check (reference rdzv_manager.py:876
    GroupNodeNetworkCheckRendezvousManager, re-shaped for TPU blocks).

    Hosts belong to node groups (TPU slices: ICI inside a group, DCN
    across groups). Phases:

    - INTRA: adjacent pairs within each slice probe the ICI path.
      Failures enter INTRA_DIAG (suspect + intra-group healthy pairing,
      bisecting to the faulty host — verdict final).
    - A clean intra pass advances to INTER: same-position hosts of
      adjacent slices pair up to probe DCN. Failures enter INTER_DIAG
      (suspect + healthy-from-another-group pairing — verdict final).

    Without group info every phase falls back to the base pair/bisect
    flow, so ungrouped jobs see identical behavior.
    """

    MAX_PHASES = 4

    def __init__(self):
        super().__init__()
        self._rank_group: Dict[int, int] = {}
        self._phase = GroupCheckPhase.INTRA
        self._concluded = False
        # True only while the CURRENT cycle's world is fully grouped —
        # evaluation, conclusion, and verdict must all branch on the
        # same predicate, or mixed group info (one agent without a
        # group) would leave the check permanently unconcluded.
        self._grouped_mode = False

    def join_rendezvous(
        self,
        node_id: int,
        node_rank: int,
        local_world_size: int,
        node_ip: str = "",
        node_group: int = -1,
    ) -> int:
        with self._lock:
            if node_group >= 0:
                self._rank_group[node_rank] = node_group
        return super().join_rendezvous(
            node_id, node_rank, local_world_size, node_ip, node_group
        )

    # ---- phase machinery ---------------------------------------------------

    def _groups_of(self, world: Dict[int, int]):
        by: Dict[int, List[int]] = {}
        for r in sorted(world):
            g = self._rank_group.get(r, -1)
            if g < 0:
                return None  # mixed/absent group info: fall back
            by.setdefault(g, []).append(r)
        return by if len(by) >= 1 else None

    def _check_concluded(self) -> bool:
        if not self._grouped_mode:
            return super()._check_concluded()
        return self._concluded

    def _reset_check_locked(self):
        super()._reset_check_locked()
        self._phase = GroupCheckPhase.INTRA
        self._concluded = False
        self._grouped_mode = False
        # _rank_group survives: slice membership is a fact about the
        # host, not about one check cycle.

    def _group_nodes(self, check_round, world):
        by = self._groups_of(world)
        self._grouped_mode = by is not None
        if by is None:
            return super()._group_nodes(check_round, world)
        phase = self._phase
        if phase == GroupCheckPhase.INTRA:
            groups = []
            for ranks in by.values():
                groups.extend(self._pair_adjacent(ranks, world))
            return groups
        if phase == GroupCheckPhase.INTRA_DIAG:
            # Bisect within each slice: a cross-slice pairing would
            # probe DCN and prove nothing about the suspect ICI path.
            # A fully-suspect block degenerates to solo host probes —
            # a host fault is isolated directly; a pure ICI-link fault
            # passes solo probes and resurfaces at the next training
            # rendezvous, where the block relaunches whole.
            groups = []
            for ranks in by.values():
                suspects = [
                    r for r in ranks if not self._node_status.get(r, True)
                ]
                healthy = [
                    r for r in ranks if self._node_status.get(r, True)
                ]
                groups.extend(self._pair_suspects(suspects, healthy, world))
            return groups
        if phase == GroupCheckPhase.INTER:
            # Same-position hosts of adjacent slices probe DCN.
            glist = sorted(by)
            groups = []
            for i in range(0, len(glist) - 1, 2):
                a, b = by[glist[i]], by[glist[i + 1]]
                for x, y in zip(a, b):
                    groups.append({x: world[x], y: world[y]})
                for rest in (a[len(b):], b[len(a):]):
                    groups.extend(self._pair_adjacent(rest, world))
            if len(glist) % 2 == 1:
                groups.extend(self._pair_adjacent(by[glist[-1]], world))
            return groups
        # INTER_DIAG: suspect + healthy host from a DIFFERENT slice than
        # the suspect's, so a bad DCN link is bisected to the host.
        suspects = [r for r in sorted(world) if not self._node_status.get(r, True)]
        groups = []
        used = set(suspects)
        for s in suspects:
            partner = next(
                (
                    r
                    for r in sorted(world)
                    if r not in used
                    and self._node_status.get(r, True)
                    and self._rank_group.get(r) != self._rank_group.get(s)
                ),
                None,
            )
            if partner is None:
                groups.append({s: world[s]})
            else:
                used.add(partner)
                groups.append({s: world[s], partner: world[partner]})
        leftovers = [r for r in sorted(world) if r not in used]
        groups.extend(self._pair_adjacent(leftovers, world))
        return groups

    def _maybe_evaluate_round(self):
        expected = set(self._latest_world)
        if not expected or not (set(self._reported) >= expected):
            return
        if not self._grouped_mode:
            super()._maybe_evaluate_round()
            return
        if self._check_round in self._eval_results:
            return
        suspects = sorted(
            r for r, ok in self._node_status.items() if not ok
        )
        self._evaluate_stragglers()
        phase = self._phase

        def advance(next_phase):
            self._eval_results[self._check_round] = []
            self._check_round += 1
            self._phase = next_phase
            self._node_groups = []
            self._reported = {}

        def conclude(faults):
            self._eval_results[self._check_round] = list(faults)
            self._concluded = True
            logger.info(
                "group network check concluded at %s: faults=%s",
                phase,
                faults,
            )

        if phase == GroupCheckPhase.INTRA:
            if suspects:
                logger.info(
                    "intra-slice suspects %s; running intra diagnosis",
                    suspects,
                )
                advance(GroupCheckPhase.INTRA_DIAG)
            else:
                logger.info("intra-slice checks clean; probing DCN")
                advance(GroupCheckPhase.INTER)
        elif phase == GroupCheckPhase.INTRA_DIAG:
            conclude(suspects)
        elif phase == GroupCheckPhase.INTER:
            if suspects:
                logger.info(
                    "inter-slice suspects %s; running inter diagnosis",
                    suspects,
                )
                advance(GroupCheckPhase.INTER_DIAG)
            else:
                conclude([])
        else:
            conclude(suspects)

    def check_fault_node(self) -> Tuple[List[int], int, bool]:
        with self._lock:
            if not self._grouped_mode:
                return super().check_fault_node()
            if not self._eval_results:
                return [], -1, False
            last = max(self._eval_results)
            return (
                list(self._eval_results[last]),
                last,
                not self._concluded,
            )


def create_rdzv_managers() -> Dict[str, RendezvousManager]:
    return {
        RendezvousName.TRAINING: ElasticTrainingRendezvousManager(),
        RendezvousName.NETWORK_CHECK: GroupNetworkCheckRendezvousManager(),
    }
