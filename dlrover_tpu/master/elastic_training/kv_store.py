"""Master-hosted KV store used by workers as a rendezvous store / barrier.

Parity: reference master KV store served via servicer kv_store RPCs and
consumed by elastic_agent/torch/master_kv_store.py. JAX side consumes it
for exit barriers and cross-host handshakes that must not ride collectives.
"""

import threading
import time
from typing import Dict, List

# Blocking-wait audit (ISSUE 5 satellite): ``wait`` is the only
# blocking surface; its default is bounded and every expiry ticks
# ``kv_wait_expired_total`` so a key that never arrives is a metric,
# not a silent hang.
DEFAULT_WAIT_TIMEOUT_S = 300.0


def _kv_metrics():
    from dlrover_tpu.observability.registry import default_registry

    reg = default_registry()
    return (
        reg.counter(
            "kv_wait_expired_total",
            "bounded KV-store waits that expired before all keys arrived",
        ),
        # §32 wait-depth gauge: servicer threads parked inside wait()
        # RIGHT NOW — at fleet scale a stuck producer shows up here
        # before it shows up as thread-pool exhaustion.
        reg.gauge(
            "kv_wait_depth",
            "threads currently blocked in a KV-store wait",
        ),
    )


class KVStoreService:
    def __init__(self):
        self._store: Dict[str, bytes] = {}
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._wait_expired, self._wait_depth = _kv_metrics()

    def set(self, key: str, value: bytes):
        with self._cond:
            self._store[key] = value
            self._cond.notify_all()

    def get(self, key: str) -> bytes:
        with self._lock:
            return self._store.get(key, b"")

    def add(self, key: str, delta: int) -> int:
        with self._cond:
            current = int(self._store.get(key, b"0") or b"0")
            current += delta
            self._store[key] = str(current).encode()
            self._cond.notify_all()
            return current

    def multi_get(self, keys: List[str]) -> Dict[str, bytes]:
        with self._lock:
            return {k: self._store[k] for k in keys if k in self._store}

    def wait(
        self, keys: List[str], timeout: float = DEFAULT_WAIT_TIMEOUT_S
    ) -> bool:
        deadline = time.time() + max(timeout, 0.0)
        self._wait_depth.inc()
        try:
            with self._cond:
                while not all(k in self._store for k in keys):
                    remaining = deadline - time.time()
                    if remaining <= 0:
                        self._wait_expired.inc()
                        return False
                    self._cond.wait(remaining)
                return True
        finally:
            self._wait_depth.dec()

    def size(self) -> int:
        with self._lock:
            return len(self._store)

    def dump(self) -> Dict[str, bytes]:
        """Full copy for journal snapshot compaction (DESIGN.md §37)."""
        with self._lock:
            return dict(self._store)

    def delete(self, key: str):
        with self._lock:
            self._store.pop(key, None)

    def clear(self):
        with self._lock:
            self._store.clear()
