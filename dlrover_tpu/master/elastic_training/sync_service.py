"""Named barriers across workers.

Parity: reference master/elastic_training/sync_service.py:25 (SyncService).
"""

import threading
from typing import Dict, Set


class SyncService:
    def __init__(self):
        self._lock = threading.Lock()
        self._syncs: Dict[str, Set[int]] = {}
        self._finished: Set[str] = set()

    def join_sync(self, sync_name: str, node_rank: int) -> bool:
        with self._lock:
            self._syncs.setdefault(sync_name, set()).add(node_rank)
            return True

    def sync_finished(self, sync_name: str) -> bool:
        with self._lock:
            self._finished.add(sync_name)
            return True

    def query(self, sync_name: str) -> bool:
        with self._lock:
            return sync_name in self._finished

    def members(self, sync_name: str) -> Set[int]:
        with self._lock:
            return set(self._syncs.get(sync_name, set()))

    def notify_finished_if_all(self, sync_name: str, world: Set[int]) -> bool:
        with self._lock:
            if self._syncs.get(sync_name, set()) >= world:
                self._finished.add(sync_name)
                return True
            return False
