"""Named barriers across workers.

Parity: reference master/elastic_training/sync_service.py:25 (SyncService).

Blocking-wait audit (ISSUE 5 satellite): the only blocking surface here
is :meth:`wait_finished`; it is bounded by ``DEFAULT_WAIT_TIMEOUT_S``
(overridable per call, never infinite) and every expiry increments
``sync_wait_expired_total`` so a barrier that silently never completes
is visible on /metrics instead of hanging its callers.
"""

import threading
import time
from typing import Dict, Set

from dlrover_tpu.fault import fault_point

DEFAULT_WAIT_TIMEOUT_S = 300.0


def _sync_metrics():
    from dlrover_tpu.observability.registry import default_registry

    reg = default_registry()
    return (
        reg.counter(
            "sync_wait_expired_total",
            "bounded sync-barrier waits that expired before completion",
        ),
        # §32 wait-depth gauge, same rationale as kv_wait_depth.
        reg.gauge(
            "sync_wait_depth",
            "threads currently blocked in a sync-barrier wait",
        ),
    )


class SyncService:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._syncs: Dict[str, Set[int]] = {}
        self._finished: Set[str] = set()
        self._wait_expired, self._wait_depth = _sync_metrics()

    def join_sync(self, sync_name: str, node_rank: int) -> bool:
        with self._cond:
            self._syncs.setdefault(sync_name, set()).add(node_rank)
            return True

    def sync_finished(self, sync_name: str) -> bool:
        with self._cond:
            self._finished.add(sync_name)
            self._cond.notify_all()
            return True

    def query(self, sync_name: str) -> bool:
        with self._lock:
            return sync_name in self._finished

    def wait_finished(
        self, sync_name: str, timeout: float = DEFAULT_WAIT_TIMEOUT_S
    ) -> bool:
        """Block until ``sync_name`` finishes, at most ``timeout``
        seconds. False (plus a metric tick) on expiry — callers degrade
        gracefully (re-poll, proceed degraded, or surface the stall)
        instead of hanging a servicer thread forever."""
        # AFTER the deadline is fixed, so a delay action eats into the
        # wait budget and can push the barrier into its timeout path.
        deadline = time.time() + max(timeout, 0.0)
        fault_point("sync.wait", sync=sync_name)
        self._wait_depth.inc()
        try:
            with self._cond:
                while sync_name not in self._finished:
                    remaining = deadline - time.time()
                    if remaining <= 0:
                        self._wait_expired.inc()
                        return False
                    self._cond.wait(remaining)
                return True
        finally:
            self._wait_depth.dec()

    def journal_snapshot(self) -> dict:
        """Barrier membership/finish state for journal compaction
        (DESIGN.md §37)."""
        with self._lock:
            return {
                "joins": {
                    name: sorted(ranks)
                    for name, ranks in self._syncs.items()
                },
                "finished": sorted(self._finished),
            }

    def restore_journal_state(self, joins, finished):
        """Rehydrate after a master restart: riders re-polling
        ``wait_finished`` on an already-finished barrier must not hang
        on the new incarnation."""
        with self._cond:
            for name, ranks in (joins or {}).items():
                self._syncs.setdefault(name, set()).update(ranks)
            self._finished.update(finished or ())
            self._cond.notify_all()

    def members(self, sync_name: str) -> Set[int]:
        with self._lock:
            return set(self._syncs.get(sync_name, set()))

    def notify_finished_if_all(self, sync_name: str, world: Set[int]) -> bool:
        with self._cond:
            if self._syncs.get(sync_name, set()) >= world:
                self._finished.add(sync_name)
                self._cond.notify_all()
                return True
            return False
