"""Master-side rescale control loop: versioned plans + bounded barriers.

The live-rescale protocol (docs/DESIGN.md §27) closes the loop between
the subsystems the repo already has — rendezvous legality, shard-lease
recovery, committed-checkpoint tracking, the fault plane — into an
N→M world change that never tears the job down:

1. **Detect.** A node death (agent ``NodeFailureReport`` routed here by
   the servicer, or the process supervisor calling
   :meth:`RescaleCoordinator.note_worker_lost`) or a scale-up join
   (``RescaleJoinReport``) changes the live set.
2. **Plan.** The coordinator picks the largest *legal* world that fits
   the live set (``legal_counts_fn`` — wired to the trainer's batch
   config so ``global_batch % (micro * dp) == 0`` always holds) and
   broadcasts a versioned :class:`RescalePlan`: monotonically increasing
   ``plan_id``, the new world map, and ``restore_step`` = the newest
   checkpoint step reported committed. Plans are pulled by workers
   (``RescalePlanRequest``), so a dropped broadcast costs one poll.
3. **Barrier.** Survivors ack phases ("barrier" → "restored" →
   "resumed"); each phase barrier is a bounded wait. A rank that dies
   mid-barrier makes the barrier EXPIRE, at which point the missing
   ranks are treated as lost and a superseding plan is cut — the
   protocol is self-healing, never wedged.

Every transition lands in the PR-1 metrics registry, so /metrics shows
plans cut, barrier waits, expirations, and the live worker count.
"""

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set

from dlrover_tpu.common.log import logger

def wire_batch_legality(
    rdzv_managers, coordinator, batch_config, local_world_size: int = 1
):
    """Single source of truth for batch-config legality: install the
    same ``legal_counts_fn`` on the training rendezvous AND the rescale
    coordinator, so neither can ever form a world whose dp size doesn't
    divide the global batch."""
    from dlrover_tpu.common.constants import RendezvousName

    legal_fn = batch_config.legal_node_counts_fn(
        local_world_size=local_world_size
    )
    mgr = (rdzv_managers or {}).get(RendezvousName.TRAINING)
    if mgr is not None:
        mgr.set_legal_counts_fn(legal_fn)
    if coordinator is not None:
        coordinator.set_legal_counts_fn(legal_fn)


# Worker phases, in protocol order.
PHASE_BARRIER = "barrier"
PHASE_RESTORED = "restored"
PHASE_RESUMED = "resumed"
PHASES = (PHASE_BARRIER, PHASE_RESTORED, PHASE_RESUMED)


def _metrics():
    from dlrover_tpu.observability.registry import default_registry

    reg = default_registry()
    return {
        "plans": reg.counter(
            "rescale_plans_total",
            "rescale plans cut, by trigger",
            labelnames=("reason",),
        ),
        "live": reg.gauge(
            "rescale_live_workers",
            "workers currently registered with the rescale plane",
        ),
        "barrier_wait": reg.histogram(
            "rescale_barrier_wait_seconds",
            "plan creation to all-acked, per phase",
            labelnames=("phase",),
        ),
        "barrier_expired": reg.counter(
            "rescale_barrier_expired_total",
            "rescale barriers that hit their bounded wait",
        ),
        "evicted": reg.counter(
            "rescale_workers_evicted_total",
            "live workers left out of a plan's world (illegal count)",
        ),
    }


@dataclass
class RescalePlan:
    plan_id: int
    world: Dict[int, int]              # node_rank -> local_world_size
    rank_order: List[int]
    restore_step: int
    reason: str
    created_at: float
    barrier_timeout_s: float
    acks: Dict[str, Set[int]] = field(
        default_factory=lambda: {p: set() for p in PHASES}
    )
    expired: bool = False
    # membership-event sequence at cut time: a rank whose join is newer
    # than this never receives the plan (it is a held-back waiter, not
    # an evictee — absence from ``world`` is only an eviction notice to
    # ranks the plan actually considered)
    cut_seq: int = 0
    # wall time each phase barrier completed (metrics / bench)
    completed_at: Dict[str, float] = field(default_factory=dict)


class RescaleCoordinator:
    """Owns the live worker set and the current plan.

    ``legal_counts_fn(max_nodes, node_unit) -> List[int]`` decides which
    world sizes may form (same contract as
    ``RendezvousManager.set_legal_counts_fn``); ``restore_step_fn`` may
    override the internally tracked committed step (e.g. to read the job
    manager's tracker).
    """

    def __init__(
        self,
        legal_counts_fn: Optional[Callable[[int, int], List[int]]] = None,
        restore_step_fn: Optional[Callable[[], int]] = None,
        barrier_timeout_s: float = 30.0,
        node_unit: int = 1,
        bootstrap_min: int = 1,
        clock: Callable[[], float] = time.time,
    ):
        self._lock = threading.RLock()
        self._legal_counts_fn = legal_counts_fn
        self._restore_step_fn = restore_step_fn
        self._barrier_timeout_s = barrier_timeout_s
        self._node_unit = max(node_unit, 1)
        # No plan is cut before this many workers have joined — keeps a
        # staggered bootstrap from cutting one plan per arriving worker.
        self._bootstrap_min = max(bootstrap_min, 1)
        self._clock = clock
        self._live: Dict[int, int] = {}    # rank -> local_world_size
        self._rank_group: Dict[int, int] = {}  # rank -> TPU slice/block
        self._seq = 0                      # membership-event counter
        self._join_seq: Dict[int, int] = {}  # rank -> seq at (re)join
        self._plan: Optional[RescalePlan] = None
        self._plan_seq = 0
        self._committed_step = -1
        # Master-journal hook (DESIGN.md §37): called with each freshly
        # cut plan so plan_id monotonicity survives a master crash.
        # Invoked under self._lock — the hook must not call back in.
        self.on_plan_cut: Optional[Callable[[RescalePlan], None]] = None
        self._m = _metrics()

    def restore_journal_state(self, plan_seq: int, committed_step: int):
        """Master-journal rehydration: floor the plan_id sequence so a
        restarted master can never re-issue a stale plan_id, and
        re-learn the newest committed checkpoint step."""
        with self._lock:
            self._plan_seq = max(self._plan_seq, int(plan_seq))
            self._committed_step = max(
                self._committed_step, int(committed_step)
            )

    # ---- configuration -----------------------------------------------------

    def set_legal_counts_fn(self, fn: Callable[[int, int], List[int]]):
        with self._lock:
            self._legal_counts_fn = fn

    # ---- membership events -------------------------------------------------

    def note_worker_joined(
        self, rank: int, local_world_size: int = 1, node_group: int = -1
    ):
        """A worker announced itself (bootstrap, scale-up join, or a
        restarted incarnation re-joining)."""
        with self._lock:
            self._seq += 1
            if rank not in self._live:
                self._join_seq[rank] = self._seq
            self._live[rank] = local_world_size
            if node_group >= 0:
                self._rank_group[rank] = node_group
            self._m["live"].set(len(self._live))
            plan = self._plan
            if plan is None:
                # The bootstrap gate ONLY defers the first plan (a
                # staggered start must not cut one plan per arrival).
                # Once any plan exists, a join is a scale-up signal no
                # matter how far below the original node count the live
                # set is — a replacement for a half-dead world must be
                # folded in, not silently evicted.
                if len(self._live) >= self._bootstrap_min:
                    self._make_plan_locked("bootstrap")
                return
            if rank not in plan.world:
                if not plan.expired and len(
                    self._select_world_locked()
                ) <= len(plan.rank_order):
                    # The join adds no capacity — the joiner's slice
                    # block is still incomplete, or the world is already
                    # at the largest legal size (a same-size selection
                    # is a seat SWAP: it would evict a healthy running
                    # rank for zero gain). Cutting a plan here would
                    # roll every healthy survivor back to restore_step
                    # for a no-op membership change (and a relaunch loop
                    # would livelock training). Hold the joiner back as
                    # a WAITER instead: it stays in the live set but
                    # receives no plan (get_plan) until a membership
                    # change cuts one that considers it.
                    logger.info(
                        "rescale: rank %d held as waiter (world "
                        "stays: %s)", rank, plan.rank_order,
                    )
                    return
                # Mid-run join: scale UP. The new plan includes the
                # joiner if the enlarged world is legal.
                self._make_plan_locked("scale_up_join")
            elif plan.expired:
                # The plan wedged on expiry with no legal replacement
                # world at the time — this join may make one legal
                # again; "never wedged" requires re-planning here.
                self._make_plan_locked("rejoin")
            elif rank in plan.acks.get(PHASE_RESTORED, set()) or (
                rank in plan.acks.get(PHASE_RESUMED, set())
            ):
                # This rank already acked 'restored' (or beyond) on the
                # current plan, so the join must be a new incarnation
                # (crashed + restarted in place without a node-loss
                # report) — and because its old ack still counts toward
                # the 'restored' barrier, peers may have passed it and
                # trained ahead. Silently handing it the plan back would
                # let it roll back alone — and, if designated, rewind
                # the live shard cursor — double-consuming shards. A
                # fresh plan rolls the whole world back together. (A
                # rank that had only acked 'barrier' re-adopts safely:
                # the 'restored' barrier cannot complete without its new
                # incarnation, so no peer can be past it.)
                self._make_plan_locked("rejoin")

    def note_worker_lost(self, rank: int):
        """A worker died (agent failure report or supervisor observation).
        Cuts a scale-down plan when the dead rank was part of the active
        world; idempotent for ranks already gone."""
        with self._lock:
            if rank not in self._live:
                return
            del self._live[rank]
            self._rank_group.pop(rank, None)
            self._join_seq.pop(rank, None)
            self._m["live"].set(len(self._live))
            if self._plan is not None and rank in self._plan.world:
                self._make_plan_locked("node_lost")

    def evict_worker(self, rank: int, reason: str = "straggler_evict"
                     ) -> bool:
        """Deliberate eviction (the §30 autoscaler condemning a flagged
        straggler): unlike :meth:`note_worker_lost` the rank is still
        ALIVE — it is removed from the live set and, when it sat in the
        current plan's world, a superseding plan is cut under
        ``reason`` so the survivors re-mesh without it. The evictee
        learns of its eviction from the plan itself (absence from
        ``world`` is the eviction notice) and exits cleanly; its
        replacement re-joins through the normal scale-up path."""
        with self._lock:
            if rank not in self._live:
                return False
            del self._live[rank]
            self._rank_group.pop(rank, None)
            self._join_seq.pop(rank, None)
            self._m["live"].set(len(self._live))
            self._m["evicted"].inc()
            if self._plan is not None and rank in self._plan.world:
                self._make_plan_locked(reason)
            logger.info(
                "rescale: rank %d evicted (%s); %d live workers remain",
                rank, reason, len(self._live),
            )
            return True

    def note_ckpt_step(self, step: int, committed: bool):
        if committed:
            with self._lock:
                self._committed_step = max(self._committed_step, step)

    def committed_step(self) -> int:
        with self._lock:
            if self._restore_step_fn is not None:
                try:
                    step = self._restore_step_fn()
                    if step is not None and step >= 0:
                        return max(step, self._committed_step)
                except Exception:
                    logger.warning(
                        "restore_step_fn failed; using reported steps",
                        exc_info=True,
                    )
            return self._committed_step

    # ---- planning ----------------------------------------------------------

    def _legal_world_size(self, n_live: int) -> int:
        if self._legal_counts_fn is None:
            return n_live
        counts = [
            c
            for c in self._legal_counts_fn(n_live, self._node_unit)
            if c <= n_live
        ]
        return max(counts) if counts else 0

    def _complete_groups_locked(self) -> Optional[List[List[int]]]:
        """Live ranks bucketed into COMPLETE slice blocks, lowest-rank
        block first, or None when grouping doesn't apply. Same rule as
        ``RendezvousManager._select_waiters``: an ICI slice cannot run
        collectives with a missing host, so a plan's world must never
        straddle a broken block."""
        unit = self._node_unit
        if unit <= 1 or not any(
            self._rank_group.get(r, -1) >= 0 for r in self._live
        ):
            return None
        by_group: Dict[int, List[int]] = {}
        for r in sorted(self._live):
            by_group.setdefault(self._rank_group.get(r, -1), []).append(r)
        groups = [m[:unit] for m in by_group.values() if len(m) >= unit]
        groups.sort(key=lambda g: g[0])
        return groups

    def _select_world_locked(self) -> List[int]:
        """The world the next plan would carry: the largest legal rank
        set, built from complete slice blocks when grouping applies."""
        groups = self._complete_groups_locked()
        if groups is None:
            size = self._legal_world_size(len(self._live))
            return sorted(self._live)[:max(size, 0)]
        # legal_counts_fn only emits multiples of node_unit, so a
        # legal size is always fillable with whole blocks.
        eligible = [r for g in groups for r in g]
        size = self._legal_world_size(len(eligible))
        return sorted(eligible[:max(size, 0)])

    def _make_plan_locked(self, reason: str):
        ranks = self._select_world_locked()
        if not ranks:
            logger.warning(
                "rescale: no legal world size fits %d live workers; "
                "holding the previous plan until membership changes",
                len(self._live),
            )
            return
        evicted = [r for r in sorted(self._live) if r not in set(ranks)]
        if evicted:
            # Evicted workers exit cleanly (code 0) when they see the
            # plan, so no failure report will ever remove them — fold
            # them out of the live set NOW or later plans would
            # re-include dead ranks and stall a full barrier timeout.
            self._m["evicted"].inc(len(evicted))
            for rank in evicted:
                del self._live[rank]
                self._rank_group.pop(rank, None)
                self._join_seq.pop(rank, None)
            self._m["live"].set(len(self._live))
        self._plan_seq += 1
        self._plan = RescalePlan(
            plan_id=self._plan_seq,
            world={r: self._live[r] for r in ranks},
            rank_order=list(ranks),
            restore_step=self.committed_step(),
            reason=reason,
            created_at=self._clock(),
            barrier_timeout_s=self._barrier_timeout_s,
            cut_seq=self._seq,
        )
        self._m["plans"].inc(reason=reason)
        if self.on_plan_cut is not None:
            try:
                self.on_plan_cut(self._plan)
            except Exception:
                logger.exception("on_plan_cut hook failed")
        logger.info(
            "rescale plan %d cut (%s): world=%s restore_step=%d",
            self._plan.plan_id,
            reason,
            self._plan.rank_order,
            self._plan.restore_step,
        )

    # ---- worker-facing surface --------------------------------------------

    def get_plan(
        self, node_rank: int, current_plan_id: int = -1
    ) -> Optional[RescalePlan]:
        """The latest plan if newer than ``current_plan_id``, else None.
        Evicted ranks still receive the plan (absence from ``world`` IS
        the eviction notice) — but a HELD-BACK waiter, whose join the
        plan never considered, gets None and keeps waiting: handing it
        the older plan would read as an eviction and make it exit."""
        with self._lock:
            plan = self._plan
            if plan is None or plan.plan_id <= current_plan_id:
                return None
            if (
                node_rank not in plan.world
                and node_rank in self._live
                and self._join_seq.get(node_rank, 0) > plan.cut_seq
            ):
                return None
            return plan

    def current_plan(self) -> Optional[RescalePlan]:
        with self._lock:
            return self._plan

    def ack(self, plan_id: int, node_rank: int, phase: str) -> bool:
        """Record a worker's phase ack. Stale-plan acks are dropped
        (False); re-acks are idempotent."""
        with self._lock:
            plan = self._plan
            if plan is None or plan.plan_id != plan_id:
                return False
            if phase not in plan.acks or node_rank not in plan.world:
                return False
            plan.acks[phase].add(node_rank)
            if (
                plan.acks[phase] >= set(plan.world)
                and phase not in plan.completed_at
            ):
                now = self._clock()
                plan.completed_at[phase] = now
                self._m["barrier_wait"].observe(
                    max(now - plan.created_at, 0.0), phase=phase
                )
                logger.info(
                    "rescale plan %d: phase %r barrier complete (%.2fs)",
                    plan_id,
                    phase,
                    now - plan.created_at,
                )
            return True

    def barrier_state(self, plan_id: int, phase: str):
        """(ready, expired, superseded, missing) for a plan's phase.

        Expiry is evaluated here (the waiters drive the clock): once the
        bounded wait runs out with ranks missing, those ranks are treated
        as lost and a superseding plan is cut — the surviving waiters see
        ``superseded`` on their next poll and pivot to the new plan.

        Each phase's budget restarts at the PREVIOUS phase's completion
        (plan creation for the first): a restore that legitimately takes
        longer than one budget must not eat the 'restored' barrier's
        allowance and evict healthy-but-slow ranks."""
        with self._lock:
            plan = self._plan
            if plan is None:
                return False, False, False, []
            if plan.plan_id != plan_id:
                return False, False, plan.plan_id > plan_id, []
            missing = sorted(set(plan.world) - plan.acks.get(phase, set()))
            if not missing:
                return True, False, False, []
            anchor = plan.created_at
            if phase in PHASES and PHASES.index(phase) > 0:
                prev = PHASES[PHASES.index(phase) - 1]
                anchor = plan.completed_at.get(prev, plan.created_at)
            if self._clock() - anchor > plan.barrier_timeout_s:
                if not plan.expired:
                    plan.expired = True
                    self._m["barrier_expired"].inc()
                    logger.warning(
                        "rescale plan %d: phase %r barrier expired; "
                        "ranks %s treated as lost",
                        plan_id,
                        phase,
                        missing,
                    )
                    for rank in missing:
                        self._live.pop(rank, None)
                        self._rank_group.pop(rank, None)
                        self._join_seq.pop(rank, None)
                    self._m["live"].set(len(self._live))
                    self._make_plan_locked("barrier_expired")
                return False, True, False, missing
            return False, False, False, missing
