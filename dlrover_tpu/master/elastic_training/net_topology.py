"""Network-topology-aware rank ordering.

Parity: reference dlrover/python/master/elastic_training/net_topology.py
:23-56 (DpTopologySorter) — hooks that reorder the rendezvous world so
data-parallel neighbors land close in the physical network. On TPU the
unit is the slice: hosts of one slice share ICI and must be contiguous
in rank space; cross-slice (DCN) hops go between blocks.
"""

import abc
from typing import Dict, List


class TopologyQuerier(abc.ABC):
    """Answers "which physical block is this node in?" (slice id for
    TPU; switch/pod id for generic fabrics)."""

    @abc.abstractmethod
    def block_of(self, node_rank: int, node_ip: str) -> str:
        ...


class SubnetTopologyQuerier(TopologyQuerier):
    """Default heuristic: nodes sharing an IP /24 share a block (GKE
    TPU slices get contiguous pod CIDRs per slice)."""

    def block_of(self, node_rank: int, node_ip: str) -> str:
        if not node_ip or "." not in node_ip:
            return ""
        return node_ip.rsplit(".", 1)[0]


class TopologySorter(abc.ABC):
    @abc.abstractmethod
    def sort(
        self, world: Dict[int, int], node_ips: Dict[int, str]
    ) -> List[int]:
        """Return node ranks in communication-friendly order."""


class DpTopologySorter(TopologySorter):
    """Group ranks by physical block, blocks ordered by their smallest
    member: ring/allreduce neighbors stay intra-block (ICI), and only
    block boundaries cross DCN (reference DpTopologySorter semantics)."""

    def __init__(self, querier: TopologyQuerier = None):
        self._querier = querier or SubnetTopologyQuerier()

    def sort(
        self, world: Dict[int, int], node_ips: Dict[int, str]
    ) -> List[int]:
        blocks: Dict[str, List[int]] = {}
        for rank in sorted(world):
            block = self._querier.block_of(rank, node_ips.get(rank, ""))
            blocks.setdefault(block, []).append(rank)
        ordered: List[int] = []
        for block in sorted(blocks.values(), key=lambda rs: rs[0]):
            ordered.extend(block)
        return ordered
