"""Cluster version tracking for PS-style elasticity.

Parity: reference master/elastic_training/elastic_ps.py (ElasticPsService).
On TPU this tracks "mesh generation" versions: each re-mesh bumps the global
version so stale workers can detect they belong to an old world.
"""

import threading
from typing import Dict


class ClusterVersionService:
    LOCAL = "local"
    GLOBAL = "global"
    RESTORED = "restored"

    def __init__(self):
        self._lock = threading.Lock()
        self._global_version = 0
        self._node_versions: Dict[str, Dict[int, Dict[str, int]]] = {}

    def get_global_version(self) -> int:
        with self._lock:
            return self._global_version

    def inc_global_version(self) -> int:
        with self._lock:
            self._global_version += 1
            return self._global_version

    def update_node_version(
        self, task_type: str, task_id: int, version_type: str, version: int
    ):
        with self._lock:
            self._node_versions.setdefault(task_type, {}).setdefault(
                task_id, {}
            )[version_type] = version

    def get_node_version(
        self, task_type: str, task_id: int, version_type: str
    ) -> int:
        with self._lock:
            return (
                self._node_versions.get(task_type, {})
                .get(task_id, {})
                .get(version_type, 0)
            )
