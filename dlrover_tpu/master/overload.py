"""Master overload accounting and graceful degradation (DESIGN.md §32).

When the control plane saturates — inflight RPC depth climbing, handler
latency EWMA past its band — the master must degrade in a *lawful*
order rather than slow down uniformly:

    **diagnostics before data, data never before leases.**

Concretely, three shed classes:

- ``diagnostic`` — span pushes and diagnosis reports
  (``DiagnosisDataReport``) and resource stats (``ResourceStats``).
  First to go: losing them costs observability detail, never
  correctness.
- ``telemetry`` — step/goodput progress reports (``GlobalStepReport``,
  ``GoodputPhaseReport``). Shed only above the second watermark:
  goodput accounting degrades, training does not.
- ``critical`` — everything else: task leases, rendezvous, KV/sync
  barriers, checkpoint coordination, rescale plans, heartbeats.
  **Never shed.** A master that drops a lease verb under load converts
  an overload into a training stall; the admission governor is
  structurally incapable of it (``admit`` returns before any shed
  logic for critical verbs).

The governor is a small hysteresis state machine over two signals the
servicer feeds it — per-request handler seconds (EWMA'd here) and the
current inflight depth — with injectable clock for tests. Escalation
is immediate (an overloaded master must not debounce its own relief);
de-escalation requires ``calm_hold_s`` of both signals under the low
watermarks (a flapping governor would turn diagnostics into a strobe).

Every shed ticks ``master_load_shed_total{class}``; the servicer
additionally ticks ``master_rpc_dropped_total{verb}``. Live state —
level, EWMA, watermarks, per-class shed totals — is served at
``/api/control_plane`` next to every bounded buffer's occupancy/drop
counters, so "is the master shedding and what is it costing" is one
dashboard fetch.
"""

import threading
import time
from typing import Callable, Dict, Optional

from dlrover_tpu.observability.registry import default_registry

CLASS_DIAGNOSTIC = "diagnostic"
CLASS_TELEMETRY = "telemetry"
CLASS_CRITICAL = "critical"

# Request-type names (the servicer's verb strings) per shed class.
# Anything unlisted is critical — new verbs are protected by default
# and must opt INTO sheddability.
DIAGNOSTIC_VERBS = frozenset({
    "DiagnosisDataReport",
    "ResourceStats",
})
TELEMETRY_VERBS = frozenset({
    "GlobalStepReport",
    "GoodputPhaseReport",
})

# Shed levels: 0 admits everything, 1 sheds diagnostic, 2 sheds
# diagnostic + telemetry. There is deliberately no level 3.
LEVEL_CLASSES = {
    0: frozenset(),
    1: frozenset({CLASS_DIAGNOSTIC}),
    2: frozenset({CLASS_DIAGNOSTIC, CLASS_TELEMETRY}),
}


def classify(verb: str) -> str:
    if verb in DIAGNOSTIC_VERBS:
        return CLASS_DIAGNOSTIC
    if verb in TELEMETRY_VERBS:
        return CLASS_TELEMETRY
    return CLASS_CRITICAL


class OverloadGovernor:
    """Admission governor: watches inflight depth + handler-latency
    EWMA, sheds diagnostic traffic first, never touches critical verbs.

    ``latency_high_s``/``inflight_high`` define the level-1 watermark;
    level 2 engages at ``level2_factor`` times either watermark. Both
    signals must sit under ``low_frac`` of the level-1 watermark for
    ``calm_hold_s`` before the level steps back down (one step per
    calm period).
    """

    def __init__(
        self,
        latency_high_s: float = 0.25,
        inflight_high: int = 64,
        level2_factor: float = 2.0,
        low_frac: float = 0.5,
        calm_hold_s: float = 2.0,
        ewma_alpha: float = 0.2,
        clock: Callable[[], float] = time.monotonic,
    ):
        self._lock = threading.Lock()
        self._clock = clock
        self._latency_high_s = float(latency_high_s)
        self._inflight_high = int(inflight_high)
        self._level2_factor = float(level2_factor)
        self._low_frac = float(low_frac)
        self._calm_hold_s = float(calm_hold_s)
        self._alpha = float(ewma_alpha)
        self._ewma_s: Optional[float] = None
        self._inflight = 0
        self._level = 0
        self._calm_since: Optional[float] = None
        self._last_observe: Optional[float] = None
        self._level_changes = 0
        self._shed_counts: Dict[str, int] = {
            CLASS_DIAGNOSTIC: 0, CLASS_TELEMETRY: 0,
        }
        self._shed_counter = default_registry().counter(
            "master_load_shed_total",
            "RPCs shed by the overload governor per traffic class",
            labelnames=("cls",),
        )

    # ---- operator/harness knobs -------------------------------------------

    def set_thresholds(
        self,
        latency_high_s: Optional[float] = None,
        inflight_high: Optional[int] = None,
    ):
        """Retune watermarks live (dashboard/ops hook; the load harness
        drops them to force the shed path deterministically)."""
        with self._lock:
            if latency_high_s is not None:
                self._latency_high_s = float(latency_high_s)
            if inflight_high is not None:
                self._inflight_high = int(inflight_high)

    # ---- signal feed -------------------------------------------------------

    def observe(self, handler_s: float, inflight: int):
        """Called by the servicer after every dispatched handler."""
        now = self._clock()
        with self._lock:
            self._last_observe = now
            self._inflight = max(int(inflight), 0)
            if self._ewma_s is None:
                self._ewma_s = max(handler_s, 0.0)
            else:
                self._ewma_s = (
                    self._alpha * max(handler_s, 0.0)
                    + (1.0 - self._alpha) * self._ewma_s
                )
            self._step_level(now)

    def _load_factor(self) -> float:
        """max of the two signals, each normalized to its level-1
        watermark: >=1 means level 1 territory, >=level2_factor means
        level 2."""
        lat = (
            (self._ewma_s / self._latency_high_s)
            if (self._ewma_s is not None and self._latency_high_s > 0)
            else 0.0
        )
        depth = (
            self._inflight / self._inflight_high
            if self._inflight_high > 0 else 0.0
        )
        return max(lat, depth)

    def _step_level(self, now: float):
        factor = self._load_factor()
        target = (
            2 if factor >= self._level2_factor
            else 1 if factor >= 1.0
            else 0
        )
        if target > self._level:
            # Escalate immediately — relief must not debounce.
            self._level = target
            self._level_changes += 1
            self._calm_since = None
            return
        if self._level == 0:
            self._calm_since = None
            return
        if factor < self._low_frac:
            if self._calm_since is None:
                self._calm_since = now
            elif now - self._calm_since >= self._calm_hold_s:
                self._level -= 1
                self._level_changes += 1
                self._calm_since = None
        else:
            self._calm_since = None

    def _relax_if_idle_locked(self, now: float):
        """De-escalation must not depend on handled traffic arriving:
        observe() only runs when a handler executes, so a master whose
        remaining traffic is ALL being shed (or none at all) would
        latch its level forever. An idle signal feed is a calm one —
        step down one level per ``calm_hold_s`` of silence."""
        if self._level == 0 or self._last_observe is None:
            return
        idle = now - self._last_observe
        steps = int(idle / self._calm_hold_s) if self._calm_hold_s > 0 \
            else (1 if idle > 0 else 0)
        if steps <= 0:
            return
        new_level = max(self._level - steps, 0)
        if new_level != self._level:
            self._level = new_level
            self._level_changes += 1
            self._calm_since = None
        # Consume the idle time spent stepping so the NEXT step needs
        # another full hold of silence.
        self._last_observe = now

    # ---- admission ---------------------------------------------------------

    def admit(self, verb: str) -> Optional[str]:
        """None = admitted. Otherwise the shed class name — the caller
        answers without running the handler. Critical verbs return
        before any shed logic: the ordering law is structural."""
        cls = classify(verb)
        if cls == CLASS_CRITICAL:
            return None
        with self._lock:
            self._relax_if_idle_locked(self._clock())
            if cls not in LEVEL_CLASSES[self._level]:
                return None
            self._shed_counts[cls] += 1
        self._shed_counter.inc(cls=cls)
        return cls

    # ---- read side ---------------------------------------------------------

    @property
    def level(self) -> int:
        with self._lock:
            self._relax_if_idle_locked(self._clock())
            return self._level

    def state(self) -> Dict:
        with self._lock:
            self._relax_if_idle_locked(self._clock())
            return {
                "level": self._level,
                "level_changes": self._level_changes,
                "handler_ewma_s": (
                    round(self._ewma_s, 6)
                    if self._ewma_s is not None else None
                ),
                "inflight": self._inflight,
                "load_factor": round(self._load_factor(), 4),
                "latency_high_s": self._latency_high_s,
                "inflight_high": self._inflight_high,
                "level2_factor": self._level2_factor,
                "calm_hold_s": self._calm_hold_s,
                "shed_total": dict(self._shed_counts),
                "shed_classes_now": sorted(LEVEL_CLASSES[self._level]),
                "ordering_law": "diagnostics before data, "
                                "data never before leases",
            }
