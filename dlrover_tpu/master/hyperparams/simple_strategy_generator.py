"""Runtime-knob suggestion (micro-batch / grad-accum / mesh / remat).

Parity: reference dlrover/python/master/hyperparams/
simple_strategy_generator.py:179 (SimpleStrategyGenerator producing a
ParallelConfig the agent-side tuner feeds to trainers) — re-pointed at
JAX knobs: the tunables are the per-device micro batch, gradient
accumulation (fixed global batch), the device-mesh shape for the current
world, and the remat (activation checkpointing) policy when host OOMs
are observed.
"""

import threading
from typing import Dict, Optional

from dlrover_tpu.common import comm
from dlrover_tpu.common.constants import NodeExitReason
from dlrover_tpu.common.log import logger


def _balanced_mesh(n_devices: int) -> Dict[str, int]:
    """Factor device count into (dp, fsdp): biggest fsdp power of two
    that divides n, rest dp — the default layout for memory-bound LMs
    (weights sharded, batch replicated across the remainder)."""
    if n_devices <= 1:
        return {"dp": 1}
    fsdp = 1
    while fsdp * 2 <= n_devices and n_devices % (fsdp * 2) == 0:
        fsdp *= 2
    dp = n_devices // fsdp
    if dp == 1:
        return {"fsdp": fsdp}
    return {"dp": dp, "fsdp": fsdp}


class SimpleStrategyGenerator:
    def __init__(
        self,
        job_manager=None,
        global_batch_size: int = 0,
        devices_per_node: int = 4,
    ):
        self._job_manager = job_manager
        self._global_batch_size = global_batch_size
        self._devices_per_node = devices_per_node
        self._version = 0
        self._last: Optional[comm.ParallelConfig] = None
        # generate() mutates suggestion state and is called from every
        # agent tuner's poll through the master's threaded RPC pool —
        # unserialized, two concurrent polls could version-bump twice
        # for identical configs (each bump makes workers rebuild their
        # jitted step: a full XLA recompile).
        self._gen_lock = threading.Lock()

    def generate(self) -> Optional[comm.ParallelConfig]:
        """Suggest knobs for the current world; None if undecidable."""
        if self._job_manager is None:
            return None
        with self._gen_lock:
            return self._generate_locked()

    def _generate_locked(self) -> Optional[comm.ParallelConfig]:
        workers = self._job_manager.worker_manager.running_nodes()
        if not workers:
            return self._last
        # Prefer the declared chips-per-host over the constructor default:
        # mesh suggestions must match the real device count.
        chips = [
            n.config_resource.tpu_chips
            for n in workers
            if n.config_resource.tpu_chips > 0
        ]
        per_node = chips[0] if chips else self._devices_per_node
        n_devices = len(workers) * per_node
        micro = self._suggest_micro_batch(n_devices)
        accum = 1
        if self._global_batch_size > 0 and micro > 0:
            denom = micro * n_devices
            if self._global_batch_size % denom != 0:
                # A fixed global batch must divide exactly — rounding up
                # would silently train on a bigger batch. Leave the
                # batching knobs unset and let the trainer keep its own.
                logger.warning(
                    "global batch %d not divisible by micro(%d) x "
                    "devices(%d); batching suggestion withheld",
                    self._global_batch_size,
                    micro,
                    n_devices,
                )
                micro = 0
                accum = 0
            else:
                accum = self._global_batch_size // denom
        config = comm.ParallelConfig(
            micro_batch_size=micro,
            grad_accum_steps=accum,
            remat_policy=self._suggest_remat(),
            mesh_shape=_balanced_mesh(n_devices),
        )
        if self._last is None or self._changed(config):
            self._version += 1
            config.version = self._version
            self._last = config
            logger.info(
                "parallel config v%d: micro=%d accum=%d mesh=%s remat=%s",
                config.version,
                micro,
                accum,
                config.mesh_shape,
                config.remat_policy,
            )
        else:
            config.version = self._version
        return config

    def _suggest_micro_batch(self, n_devices: int) -> int:
        if self._global_batch_size <= 0:
            return 0
        # Largest power-of-two micro batch that divides the per-device
        # share of the global batch (keeps the MXU batched without
        # breaking fixed-global-batch divisibility).
        share = max(self._global_batch_size // n_devices, 1)
        micro = 1
        while micro * 2 <= share and share % (micro * 2) == 0:
            micro *= 2
        return micro

    def _suggest_remat(self) -> str:
        """Escalate activation rematerialization on OOM evidence: the
        first OOM EPISODE suggests "attn_save" (attention stays
        un-rematted — its re-run dominates the remat bill, see
        models/llama.py remat policies); a REPEATED episode escalates
        to "full". Episode attribution rides the lineage exit history
        (get_relaunch_node shares it across relaunches): one symmetric
        SPMD episode stamps each lineage ONCE no matter how many
        records it marks or how late (heartbeat-timeout) the marks
        land, while a lineage with two OOM exits has provably OOMed
        again after a relaunch — timing-free, so it cannot be confused
        by when records were created or polled."""
        nodes = self._job_manager.worker_manager.nodes.values()
        evidence = [
            n for n in nodes
            if n.exit_reason == NodeExitReason.OOM
            or n.exit_count(NodeExitReason.OOM) > 0
        ]
        if not evidence:
            return ""
        if any(
            n.exit_count(NodeExitReason.OOM) >= 2 for n in evidence
        ):
            return "full"
        return "attn_save"

    def _changed(self, config: comm.ParallelConfig) -> bool:
        last = self._last
        return (
            last.micro_batch_size != config.micro_batch_size
            or last.grad_accum_steps != config.grad_accum_steps
            or last.remat_policy != config.remat_policy
            or last.mesh_shape != config.mesh_shape
        )
