"""Dynamic data sharding: datasets -> shards -> dispatched tasks.

Parity: reference dlrover/python/master/shard/task_manager.py and
batch_dataset_manager.py — TODO/DOING queues, timeout re-queue, shard
checkpoint/restore so a restarted job resumes exactly the unconsumed data.
"""

import json
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from dlrover_tpu.common import comm
from dlrover_tpu.common.constants import TaskType
from dlrover_tpu.common.log import logger
from dlrover_tpu.master.shard.dataset_splitter import (
    DatasetSplitter,
    Shard,
    StreamingDatasetSplitter,
    create_dataset_splitter,
)


@dataclass
class Task:
    task_id: int
    task_type: str
    shard: Shard
    epoch: int = 0

    @classmethod
    def create_invalid_task(cls) -> "Task":
        return cls(-1, TaskType.NONE, Shard("", 0, 0))


@dataclass
class _DoingTask:
    task: Task
    node_id: int
    start_time: float


class BatchDatasetManager:
    """Shard queue of one dataset (reference batch_dataset_manager.py:29)."""

    def __init__(self, task_type: str, splitter: DatasetSplitter):
        self._task_type = task_type
        self._splitter = splitter
        self.todo: List[Task] = []
        self.doing: Dict[int, _DoingTask] = {}
        self._task_id_seq = 0
        self._completed_count = 0
        self._lock = threading.Lock()

    def get_task(self, node_id: int) -> Task:
        with self._lock:
            if not self.todo and not self._splitter.epoch_finished():
                self._create_todo_tasks()
            if not self.todo:
                if self.doing:
                    # Data remains in flight: tell the worker to wait, its
                    # peers' shards may be re-queued on timeout/failure.
                    return Task(-1, TaskType.WAIT, Shard("", 0, 0))
                return Task.create_invalid_task()
            task = self.todo.pop(0)
            self.doing[task.task_id] = _DoingTask(task, node_id, time.time())
            return task

    def _create_todo_tasks(self):
        shards = self._splitter.create_shards()
        epoch = self._splitter.epoch
        for shard in shards:
            self.todo.append(
                Task(self._task_id_seq, self._task_type, shard, epoch)
            )
            self._task_id_seq += 1

    def report_task_done(
        self, task_id: int, node_id: int, success: bool = True
    ) -> bool:
        with self._lock:
            doing = self.doing.pop(task_id, None)
            if doing is None:
                return False
            if not success:
                # The worker explicitly failed the shard: its records
                # were NOT consumed — re-queue, don't count as done.
                logger.warning(
                    "task %d failed on node %d; re-queueing",
                    task_id,
                    node_id,
                )
                self.todo.insert(0, doing.task)
                return False
            self._completed_count += 1
            return True

    def recover_timeout_tasks(self, timeout: float):
        with self._lock:
            now = time.time()
            expired = [
                tid
                for tid, d in self.doing.items()
                if now - d.start_time > timeout
            ]
            for tid in expired:
                doing = self.doing.pop(tid)
                logger.warning(
                    "task %d of node %d timed out; re-queueing",
                    tid,
                    doing.node_id,
                )
                self.todo.insert(0, doing.task)

    def recover_node_tasks(self, node_id: int):
        """Re-queue all in-flight shards of a dead node."""
        with self._lock:
            lost = [
                tid for tid, d in self.doing.items() if d.node_id == node_id
            ]
            for tid in lost:
                self.todo.insert(0, self.doing.pop(tid).task)

    def completed(self) -> bool:
        with self._lock:
            return (
                self._splitter.epoch_finished()
                and not self.todo
                and not self.doing
            )

    # ---- shard checkpoint --------------------------------------------------

    def checkpoint(self) -> dict:
        with self._lock:
            undone = [
                [t.task.shard.start, t.task.shard.end, t.task.shard.record_indices]
                for t in self.doing.values()
            ] + [
                [t.shard.start, t.shard.end, t.shard.record_indices]
                for t in self.todo
            ]
            return {
                "epoch": self._splitter.epoch,
                "undone_shards": undone,
                "completed": self._completed_count,
            }

    def restore(self, state: dict, dataset_name: str):
        with self._lock:
            self.todo.clear()
            self.doing.clear()
            self._splitter.epoch = state.get("epoch", 0)
            self._completed_count = state.get("completed", 0)
            for entry in state.get("undone_shards", []):
                start, end = entry[0], entry[1]
                indices = entry[2] if len(entry) > 2 else None
                self.todo.append(
                    Task(
                        self._task_id_seq,
                        self._task_type,
                        Shard(dataset_name, start, end, indices),
                        self._splitter.epoch,
                    )
                )
                self._task_id_seq += 1


class TaskManager:
    """Owns all dataset managers; periodic timeout recovery thread.

    Parity: reference master/shard/task_manager.py (TaskManager).
    """

    def __init__(self, task_timeout: float = 1800.0, perf_monitor=None):
        self._lock = threading.Lock()
        # BatchDatasetManager or StreamingDatasetManager (duck-typed).
        self._datasets: Dict[str, object] = {}
        self._task_timeout = task_timeout
        self._perf_monitor = perf_monitor
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._recover_loop, daemon=True, name="task-recover"
            )
            self._thread.start()

    def stop(self):
        self._stopped.set()

    def _recover_loop(self):
        while not self._stopped.wait(30):
            with self._lock:
                managers = list(self._datasets.values())
            for m in managers:
                m.recover_timeout_tasks(self._task_timeout)

    # ---- servicer surface --------------------------------------------------

    def new_dataset(self, params: comm.DatasetShardParams):
        with self._lock:
            if params.dataset_name in self._datasets:
                return
            splitter = create_dataset_splitter(
                params.storage_type,
                params.dataset_name,
                params.dataset_size,
                params.shard_size,
                params.num_epochs,
                params.shuffle,
                num_partitions=params.num_partitions,
            )
            if isinstance(splitter, StreamingDatasetSplitter):
                from dlrover_tpu.master.shard.streaming_dataset_manager import (  # noqa: E501
                    StreamingDatasetManager,
                )

                manager = StreamingDatasetManager(params.task_type, splitter)
            else:
                manager = BatchDatasetManager(params.task_type, splitter)
            self._datasets[params.dataset_name] = manager
            logger.info(
                "dataset %s registered (%s): size=%d shard=%d epochs=%d",
                params.dataset_name,
                params.storage_type,
                params.dataset_size,
                params.shard_size,
                params.num_epochs,
            )

    def get_dataset(self, name: str) -> Optional[BatchDatasetManager]:
        with self._lock:
            return self._datasets.get(name)

    def get_task(self, node_id: int, dataset_name: str) -> comm.ShardTask:
        mgr = self.get_dataset(dataset_name)
        if mgr is None:
            return comm.ShardTask()
        task = mgr.get_task(node_id)
        return comm.ShardTask(
            task_id=task.task_id,
            task_type=task.task_type,
            dataset_name=dataset_name,
            start=task.shard.start,
            end=task.shard.end,
            epoch=task.epoch,
            record_indices=task.shard.record_indices,
            partition=task.shard.partition,
        )

    def report_task_done(
        self,
        dataset_name: str,
        task_id: int,
        node_id: int,
        success: bool = True,
    ):
        mgr = self.get_dataset(dataset_name)
        if mgr is not None:
            mgr.report_task_done(task_id, node_id, success)

    def recover_node_tasks(self, node_id: int):
        with self._lock:
            managers = list(self._datasets.values())
        for m in managers:
            m.recover_node_tasks(node_id)

    def finished(self) -> bool:
        with self._lock:
            if not self._datasets:
                return False
            return all(m.completed() for m in self._datasets.values())

    def get_shard_checkpoint(self, dataset_name: str) -> str:
        mgr = self.get_dataset(dataset_name)
        if mgr is None:
            return ""
        return json.dumps(mgr.checkpoint())

    def restore_shard_checkpoint(self, dataset_name: str, checkpoint: str):
        mgr = self.get_dataset(dataset_name)
        if mgr is not None and checkpoint:
            mgr.restore(json.loads(checkpoint), dataset_name)
