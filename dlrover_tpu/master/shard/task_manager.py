"""Dynamic data sharding: datasets -> shards -> dispatched tasks.

Parity: reference dlrover/python/master/shard/task_manager.py and
batch_dataset_manager.py — TODO/DOING queues, timeout re-queue, shard
checkpoint/restore so a restarted job resumes exactly the unconsumed data.
"""

import json
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, List, Optional

from dlrover_tpu.common import comm
from dlrover_tpu.common.constants import TaskType
from dlrover_tpu.common.log import logger
from dlrover_tpu.fault import fault_point
from dlrover_tpu.master.shard.dataset_splitter import (
    DatasetSplitter,
    Shard,
    StreamingDatasetSplitter,
    create_dataset_splitter,
)


@dataclass
class Task:
    task_id: int
    task_type: str
    shard: Shard
    epoch: int = 0
    # Wall time this task (re-)entered the todo queue; 0 = unknown.
    # Dispatch observes now - enqueue_ts as the §32 queue-age
    # histogram — a growing age at constant depth means dispatch is
    # starved, not the dataset.
    enqueue_ts: float = 0.0

    @classmethod
    def create_invalid_task(cls) -> "Task":
        return cls(-1, TaskType.NONE, Shard("", 0, 0))


def _queue_metrics():
    """§32 dispatch self-instrumentation (fresh registry lookup per
    manager instance, same discipline as rdzv_manager's)."""
    from dlrover_tpu.master.rpc_metrics import RPC_BUCKETS
    from dlrover_tpu.observability.registry import default_registry

    reg = default_registry()
    return {
        "dispatch": reg.histogram(
            "shard_dispatch_seconds",
            "time spent inside one get-task(s) dispatch call",
            buckets=RPC_BUCKETS,
        ),
        "queue_age": reg.histogram(
            "shard_task_queue_age_seconds",
            "todo-queue residence time of a lease at dispatch",
        ),
        "todo": reg.gauge(
            "shard_todo_depth", "queued shard leases across datasets"
        ),
        "doing": reg.gauge(
            "shard_doing_depth", "in-flight shard leases across datasets"
        ),
    }


@dataclass
class _DoingTask:
    task: Task
    node_id: int
    start_time: float


def drain_tasks(get_one, node_id: int, count: int) -> List[Task]:
    """THE batched-dispatch sentinel contract, in one place: call
    ``get_one(node_id)`` up to ``count`` times collecting real leases;
    a WAIT/invalid task (negative id) stops the drain and is returned
    alone only when nothing real was collected."""
    out: List[Task] = []
    for _ in range(max(count, 1)):
        task = get_one(node_id)
        if task.task_id < 0:
            if not out:
                out.append(task)
            break
        out.append(task)
    return out


class BatchDatasetManager:
    """Shard queue of one dataset (reference batch_dataset_manager.py:29)."""

    def __init__(self, task_type: str, splitter: DatasetSplitter):
        self._task_type = task_type
        self._splitter = splitter
        # deque, not list: dispatch pops the head and recovery re-queues
        # at the head — O(1) both ways where list.pop(0)/insert(0, ...)
        # were O(n) per task on large shard counts.
        self.todo: Deque[Task] = deque()
        self.doing: Dict[int, _DoingTask] = {}
        self._task_id_seq = 0
        self._completed_count = 0
        self._lock = threading.Lock()
        self._metrics = _queue_metrics()

    def get_task(self, node_id: int) -> Task:
        with self._lock:
            return self._get_task_locked(node_id)

    def _get_task_locked(self, node_id: int) -> Task:
        if not self.todo and not self._splitter.epoch_finished():
            self._create_todo_tasks()
        if not self.todo:
            if self.doing:
                # Data remains in flight: tell the worker to wait, its
                # peers' shards may be re-queued on timeout/failure.
                return Task(-1, TaskType.WAIT, Shard("", 0, 0))
            return Task.create_invalid_task()
        task = self.todo.popleft()
        now = time.time()
        if task.enqueue_ts > 0:
            self._metrics["queue_age"].observe(
                max(now - task.enqueue_ts, 0.0)
            )
        self.doing[task.task_id] = _DoingTask(task, node_id, now)
        return task

    def get_tasks(self, node_id: int, count: int) -> List[Task]:
        """Up to ``count`` leases in one call (the batched-dispatch verb,
        sentinel contract in :func:`drain_tasks`). One lock hold for the
        whole batch — a prefetching worker costs the dispatch path one
        acquisition per batch, not per shard."""
        with self._lock:
            return drain_tasks(self._get_task_locked, node_id, count)

    def _create_todo_tasks(self):
        shards = self._splitter.create_shards()
        epoch = self._splitter.epoch
        now = time.time()
        for shard in shards:
            self.todo.append(
                Task(
                    self._task_id_seq, self._task_type, shard, epoch,
                    enqueue_ts=now,
                )
            )
            self._task_id_seq += 1

    def report_task_done(
        self, task_id: int, node_id: int, success: bool = True
    ) -> bool:
        with self._lock:
            doing = self.doing.pop(task_id, None)
            if doing is None:
                return False
            if not success:
                # The worker explicitly failed the shard: its records
                # were NOT consumed — re-queue, don't count as done.
                logger.warning(
                    "task %d failed on node %d; re-queueing",
                    task_id,
                    node_id,
                )
                doing.task.enqueue_ts = time.time()
                self.todo.appendleft(doing.task)
                return False
            self._completed_count += 1
            return True

    def recover_timeout_tasks(self, timeout: float):
        with self._lock:
            now = time.time()
            expired = [
                tid
                for tid, d in self.doing.items()
                if now - d.start_time > timeout
            ]
            for tid in expired:
                doing = self.doing.pop(tid)
                logger.warning(
                    "task %d of node %d timed out; re-queueing",
                    tid,
                    doing.node_id,
                )
                doing.task.enqueue_ts = now
                self.todo.appendleft(doing.task)

    def recover_node_tasks(self, node_id: int):
        """Re-queue all in-flight shards of a dead node — including
        leases the worker had prefetched but never consumed."""
        with self._lock:
            lost = [
                tid for tid, d in self.doing.items() if d.node_id == node_id
            ]
            now = time.time()
            for tid in lost:
                task = self.doing.pop(tid).task
                task.enqueue_ts = now
                self.todo.appendleft(task)

    def completed(self) -> bool:
        with self._lock:
            return (
                self._splitter.epoch_finished()
                and not self.todo
                and not self.doing
            )

    # ---- shard checkpoint --------------------------------------------------

    def checkpoint(self) -> dict:
        with self._lock:
            undone = [
                [t.task.shard.start, t.task.shard.end, t.task.shard.record_indices]
                for t in self.doing.values()
            ] + [
                [t.shard.start, t.shard.end, t.shard.record_indices]
                for t in self.todo
            ]
            return {
                "epoch": self._splitter.epoch,
                "undone_shards": undone,
                "completed": self._completed_count,
            }

    def restore(self, state: dict, dataset_name: str):
        with self._lock:
            self.todo.clear()
            self.doing.clear()
            self._splitter.epoch = state.get("epoch", 0)
            self._completed_count = state.get("completed", 0)
            now = time.time()
            for entry in state.get("undone_shards", []):
                start, end = entry[0], entry[1]
                indices = entry[2] if len(entry) > 2 else None
                self.todo.append(
                    Task(
                        self._task_id_seq,
                        self._task_type,
                        Shard(dataset_name, start, end, indices),
                        self._splitter.epoch,
                        enqueue_ts=now,
                    )
                )
                self._task_id_seq += 1

    # ---- master-journal crash recovery (docs/DESIGN.md §37) ---------------

    def rehydrate(
        self,
        dataset_name: str,
        epoch: int,
        completed: int,
        todo_shards,
        doing,
        next_task_id: int,
    ):
        """Install journal-replayed state after a master crash. Unlike
        :meth:`restore` (a user-driven shard-checkpoint restore that
        mints fresh ids), crash rehydration must keep outstanding
        leases in ``doing`` under their ORIGINAL task ids so a worker
        that rode through the outage can still report them done —
        re-queueing them with new ids would double-dispatch their data.

        ``todo_shards``: iterable of ``[start, end, indices, partition]``.
        ``doing``: ``tid -> (node_id, epoch, start, end, indices, part)``.
        """
        with self._lock:
            self.todo.clear()
            self.doing.clear()
            self._splitter.epoch = max(epoch, 0)
            self._completed_count = completed
            self._task_id_seq = max(next_task_id, 0)
            now = time.time()
            for entry in todo_shards:
                start, end = entry[0], entry[1]
                indices = entry[2] if len(entry) > 2 else None
                part = entry[3] if len(entry) > 3 else 0
                self.todo.append(
                    Task(
                        self._task_id_seq,
                        self._task_type,
                        Shard(dataset_name, start, end, indices, part),
                        self._splitter.epoch,
                        enqueue_ts=now,
                    )
                )
                self._task_id_seq += 1
            for tid, lease in doing.items():
                node_id, task_epoch, start, end, indices, part = lease
                task = Task(
                    tid,
                    self._task_type,
                    Shard(dataset_name, start, end, indices, part),
                    task_epoch,
                    enqueue_ts=now,
                )
                # start_time = now: a dead worker's rehydrated lease
                # re-queues via the normal timeout path; a live worker
                # pops it with a done-report long before that.
                self.doing[tid] = _DoingTask(task, node_id, now)
                self._task_id_seq = max(self._task_id_seq, tid + 1)

    def journal_snapshot(self) -> dict:
        """Lease-preserving state for journal compaction. Unlike
        :meth:`checkpoint` this does NOT fold ``doing`` into the undone
        list — outstanding leases keep their ids across the snapshot so
        compaction never breaks the exactly-once law above."""
        with self._lock:
            return {
                "epoch": self._splitter.epoch,
                "completed": self._completed_count,
                "todo": [
                    [t.shard.start, t.shard.end, t.shard.record_indices,
                     t.shard.partition]
                    for t in self.todo
                ],
                "doing": {
                    tid: {
                        "node": d.node_id,
                        "epoch": d.task.epoch,
                        "start": d.task.shard.start,
                        "end": d.task.shard.end,
                        "idx": d.task.shard.record_indices,
                        "part": d.task.shard.partition,
                    }
                    for tid, d in self.doing.items()
                },
                "next_tid": self._task_id_seq,
            }


class TaskManager:
    """Owns all dataset managers; periodic timeout recovery thread.

    Parity: reference master/shard/task_manager.py (TaskManager).
    """

    def __init__(self, task_timeout: float = 1800.0, perf_monitor=None):
        self._lock = threading.Lock()
        # BatchDatasetManager or StreamingDatasetManager (duck-typed).
        self._datasets: Dict[str, object] = {}
        self._task_timeout = task_timeout
        self._perf_monitor = perf_monitor
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        from dlrover_tpu.observability.registry import default_registry

        reg = default_registry()
        self._tasks_dispatched = reg.counter(
            "shard_tasks_dispatched_total",
            "shard leases handed to workers",
        )
        self._dispatch_rpcs = reg.counter(
            "shard_dispatch_rpcs_total",
            "get-task RPCs served (single or batched)",
        )
        self._tasks_recovered = reg.counter(
            "shard_tasks_recovered_total",
            "in-flight leases re-queued after timeout/failure/node loss",
        )
        self._metrics = _queue_metrics()

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._recover_loop, daemon=True, name="task-recover"
            )
            self._thread.start()

    def stop(self):
        self._stopped.set()

    def _recover_loop(self):
        while not self._stopped.wait(30):
            with self._lock:
                managers = list(self._datasets.values())
            for m in managers:
                m.recover_timeout_tasks(self._task_timeout)

    # ---- servicer surface --------------------------------------------------

    def new_dataset(self, params: comm.DatasetShardParams):
        with self._lock:
            if params.dataset_name in self._datasets:
                return
            splitter = create_dataset_splitter(
                params.storage_type,
                params.dataset_name,
                params.dataset_size,
                params.shard_size,
                params.num_epochs,
                params.shuffle,
                num_partitions=params.num_partitions,
            )
            if isinstance(splitter, StreamingDatasetSplitter):
                from dlrover_tpu.master.shard.streaming_dataset_manager import (  # noqa: E501
                    StreamingDatasetManager,
                )

                manager = StreamingDatasetManager(params.task_type, splitter)
            else:
                manager = BatchDatasetManager(params.task_type, splitter)
            self._datasets[params.dataset_name] = manager
            logger.info(
                "dataset %s registered (%s): size=%d shard=%d epochs=%d",
                params.dataset_name,
                params.storage_type,
                params.dataset_size,
                params.shard_size,
                params.num_epochs,
            )

    def get_dataset(self, name: str) -> Optional[BatchDatasetManager]:
        with self._lock:
            return self._datasets.get(name)

    @staticmethod
    def _to_shard_task(task: Task, dataset_name: str) -> comm.ShardTask:
        return comm.ShardTask(
            task_id=task.task_id,
            task_type=task.task_type,
            dataset_name=dataset_name,
            start=task.shard.start,
            end=task.shard.end,
            epoch=task.epoch,
            record_indices=task.shard.record_indices,
            partition=task.shard.partition,
        )

    def get_task(self, node_id: int, dataset_name: str) -> comm.ShardTask:
        mgr = self.get_dataset(dataset_name)
        if mgr is None:
            return comm.ShardTask()
        t0 = time.monotonic()
        task = mgr.get_task(node_id)
        self._metrics["dispatch"].observe(time.monotonic() - t0)
        self._dispatch_rpcs.inc()
        if task.task_id >= 0:
            self._tasks_dispatched.inc()
        self._refresh_depth_gauges()
        return self._to_shard_task(task, dataset_name)

    def get_tasks(
        self, node_id: int, dataset_name: str, count: int
    ) -> List[comm.ShardTask]:
        """Batched dispatch: up to ``count`` real leases, or a single
        WAIT/invalid sentinel when none are available right now."""
        fault_point("shard.dispatch", dataset=dataset_name, count=count)
        mgr = self.get_dataset(dataset_name)
        if mgr is None:
            return [comm.ShardTask()]
        t0 = time.monotonic()
        getter = getattr(mgr, "get_tasks", None)
        if getter is not None:
            tasks = getter(node_id, count)
        else:
            # Duck-typed manager without the batched verb: same sentinel
            # contract, one lock acquisition per task.
            tasks = drain_tasks(mgr.get_task, node_id, count)
        self._metrics["dispatch"].observe(time.monotonic() - t0)
        self._dispatch_rpcs.inc()
        self._tasks_dispatched.inc(
            sum(1 for t in tasks if t.task_id >= 0) or 0
        )
        self._refresh_depth_gauges()
        return [self._to_shard_task(t, dataset_name) for t in tasks]

    def report_task_done(
        self,
        dataset_name: str,
        task_id: int,
        node_id: int,
        success: bool = True,
    ):
        mgr = self.get_dataset(dataset_name)
        if mgr is not None:
            mgr.report_task_done(task_id, node_id, success)

    def report_tasks_done(
        self,
        dataset_name: str,
        node_id: int,
        done_ids: List[int],
        failed_ids: Optional[List[int]] = None,
    ):
        """Apply one coalesced done-report batch."""
        mgr = self.get_dataset(dataset_name)
        if mgr is None:
            return
        for tid in done_ids:
            mgr.report_task_done(tid, node_id, True)
        for tid in failed_ids or []:
            mgr.report_task_done(tid, node_id, False)

    def recover_node_tasks(self, node_id: int):
        with self._lock:
            managers = list(self._datasets.values())
        for m in managers:
            before = len(m.doing)
            m.recover_node_tasks(node_id)
            self._tasks_recovered.inc(max(before - len(m.doing), 0))

    def _refresh_depth_gauges(self):
        """Depth gauges after a dispatch; len() per manager under the
        GIL, no manager locks taken — gauges tolerate a ±1 race."""
        with self._lock:
            managers = list(self._datasets.values())
        self._metrics["todo"].set(sum(len(m.todo) for m in managers))
        self._metrics["doing"].set(sum(len(m.doing) for m in managers))

    def queue_stats(self) -> Dict[str, object]:
        """§32 buffer accounting for /api/control_plane: occupancy +
        drops for the lease queues (leases are never dropped — they are
        re-queued, and the recovery counter is the honest analogue)."""
        with self._lock:
            datasets = dict(self._datasets)
        per = {
            name: {"todo": len(m.todo), "doing": len(m.doing)}
            for name, m in datasets.items()
        }
        todo = sum(d["todo"] for d in per.values())
        doing = sum(d["doing"] for d in per.values())
        dispatch = self._metrics["dispatch"]
        return {
            "occupancy": todo + doing,
            "drops": 0,
            "todo": todo,
            "doing": doing,
            "recovered_total": self._tasks_recovered.value(),
            "dispatch_p99_s": dispatch.quantile(0.99),
            "datasets": per,
        }

    def finished(self) -> bool:
        with self._lock:
            if not self._datasets:
                return False
            return all(m.completed() for m in self._datasets.values())

    def journal_snapshots(self) -> Dict[str, dict]:
        """Per-dataset lease-preserving snapshots for journal compaction
        (managers without the surface are skipped)."""
        with self._lock:
            datasets = dict(self._datasets)
        out: Dict[str, dict] = {}
        for name, mgr in datasets.items():
            snap = getattr(mgr, "journal_snapshot", None)
            if snap is not None:
                out[name] = snap()
        return out

    def get_shard_checkpoint(self, dataset_name: str) -> str:
        mgr = self.get_dataset(dataset_name)
        if mgr is None:
            return ""
        return json.dumps(mgr.checkpoint())

    def restore_shard_checkpoint(self, dataset_name: str, checkpoint: str):
        mgr = self.get_dataset(dataset_name)
        if mgr is not None and checkpoint:
            mgr.restore(json.loads(checkpoint), dataset_name)
