"""Streaming dataset manager: dynamic sharding over an unbounded source.

Parity: reference dlrover/python/master/shard/streaming_dataset_manager.py
(StreamingDatasetManager) — tasks are carved on demand from per-partition
offsets, a failed shard is retried up to its budget then dropped (a
poisoned record range must not wedge an infinite stream), completed-step
accounting tracks consumption, and the shard checkpoint captures
partition offsets + undone shards so a restarted job resumes the exact
unconsumed stream positions.

Duck-type compatible with BatchDatasetManager (task_manager.py routes to
either based on the dataset's storage_type).
"""

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, List

from dlrover_tpu.common.constants import TaskType
from dlrover_tpu.common.log import logger
from dlrover_tpu.master.shard.dataset_splitter import (
    Shard,
    StreamingDatasetSplitter,
)
from dlrover_tpu.master.shard.task_manager import (
    Task,
    _DoingTask,
    drain_tasks,
)

_MAX_TASK_RETRIES = 3


@dataclass
class _RetryState:
    count: int = 0


class StreamingDatasetManager:
    def __init__(self, task_type: str, splitter: StreamingDatasetSplitter):
        self._task_type = task_type
        self._splitter = splitter
        self.todo: Deque[Task] = deque()
        self.doing: Dict[int, _DoingTask] = {}
        self._task_id_seq = 0
        self._completed_count = 0
        self._completed_records = 0
        self._retries: Dict[str, _RetryState] = {}
        self._lock = threading.Lock()

    # ---- dispatch ----------------------------------------------------------

    def get_task(self, node_id: int) -> Task:
        with self._lock:
            return self._get_task_locked(node_id)

    def _get_task_locked(self, node_id: int) -> Task:
        if not self.todo and not self._splitter.epoch_finished():
            # Carve the next window of shards from the stream.
            for shard in self._splitter.create_shards():
                self.todo.append(
                    Task(self._task_id_seq, self._task_type, shard)
                )
                self._task_id_seq += 1
        if not self.todo:
            if self.doing:
                return Task(-1, TaskType.WAIT, Shard("", 0, 0))
            return Task.create_invalid_task()
        task = self.todo.popleft()
        self.doing[task.task_id] = _DoingTask(task, node_id, time.time())
        return task

    def get_tasks(self, node_id: int, count: int) -> List[Task]:
        """Batched dispatch (sentinel contract in ``drain_tasks``)."""
        with self._lock:
            return drain_tasks(self._get_task_locked, node_id, count)

    # ---- completion & recovery --------------------------------------------

    def report_task_done(
        self, task_id: int, node_id: int, success: bool = True
    ) -> bool:
        with self._lock:
            doing = self.doing.pop(task_id, None)
            if doing is None:
                return False
            if success:
                self._completed_count += 1
                shard = doing.task.shard
                self._completed_records += shard.end - shard.start
                self._retries.pop(self._shard_key(shard), None)
                return True
            self._recover_locked(doing.task, "reported failed")
            return False

    def recover_timeout_tasks(self, timeout: float):
        with self._lock:
            now = time.time()
            expired = [
                tid
                for tid, d in self.doing.items()
                if now - d.start_time > timeout
            ]
            for tid in expired:
                doing = self.doing.pop(tid)
                self._recover_locked(doing.task, "timed out")

    def recover_node_tasks(self, node_id: int):
        with self._lock:
            lost = [
                tid for tid, d in self.doing.items() if d.node_id == node_id
            ]
            for tid in lost:
                self._recover_locked(self.doing.pop(tid).task, "node lost")

    def _shard_key(self, shard: Shard) -> str:
        return f"{shard.partition}:{shard.start}:{shard.end}"

    def _recover_locked(self, task: Task, why: str):
        state = self._retries.setdefault(
            self._shard_key(task.shard), _RetryState()
        )
        state.count += 1
        if state.count > _MAX_TASK_RETRIES:
            # A poisoned range must not wedge the stream forever.
            logger.error(
                "streaming shard %s %s %d times; dropping it",
                self._shard_key(task.shard),
                why,
                state.count,
            )
            return
        logger.warning(
            "streaming shard %s %s; re-queueing (retry %d/%d)",
            self._shard_key(task.shard),
            why,
            state.count,
            _MAX_TASK_RETRIES,
        )
        self.todo.appendleft(task)

    # ---- progress ----------------------------------------------------------

    def completed(self) -> bool:
        with self._lock:
            return (
                self._splitter.epoch_finished()
                and not self.todo
                and not self.doing
            )

    def completed_records(self) -> int:
        with self._lock:
            return self._completed_records

    # ---- shard checkpoint --------------------------------------------------

    def checkpoint(self) -> dict:
        with self._lock:
            undone = [
                [t.task.shard.partition, t.task.shard.start, t.task.shard.end]
                for t in self.doing.values()
            ] + [
                [t.shard.partition, t.shard.start, t.shard.end]
                for t in self.todo
            ]
            return {
                "streaming": True,
                "splitter": self._splitter.to_checkpoint(),
                "undone_shards": undone,
                "completed": self._completed_count,
                "completed_records": self._completed_records,
            }

    def restore(self, state: dict, dataset_name: str):
        with self._lock:
            self.todo.clear()
            self.doing.clear()
            self._splitter.restore_checkpoint(state["splitter"])
            self._completed_count = state.get("completed", 0)
            self._completed_records = state.get("completed_records", 0)
            for part, start, end in state.get("undone_shards", []):
                self.todo.append(
                    Task(
                        self._task_id_seq,
                        self._task_type,
                        Shard(dataset_name, start, end, partition=part),
                    )
                )
                self._task_id_seq += 1

    # ---- master-journal crash recovery (docs/DESIGN.md §37) ---------------

    def rehydrate(
        self,
        dataset_name: str,
        epoch: int,
        completed: int,
        todo_shards,
        doing,
        next_task_id: int,
        splitter_ckpt: dict = None,
    ):
        """Install journal-replayed state after a master crash: splitter
        offsets advance past every journaled carve, outstanding leases
        keep their ORIGINAL task ids (same exactly-once law as
        ``BatchDatasetManager.rehydrate``). ``epoch`` is ignored —
        per-partition offsets, not epochs, are streaming progress."""
        with self._lock:
            self.todo.clear()
            self.doing.clear()
            if splitter_ckpt:
                self._splitter.restore_checkpoint(splitter_ckpt)
            self._completed_count = completed
            self._task_id_seq = max(next_task_id, 0)
            for entry in todo_shards:
                start, end = entry[0], entry[1]
                part = entry[3] if len(entry) > 3 else 0
                self.todo.append(
                    Task(
                        self._task_id_seq,
                        self._task_type,
                        Shard(dataset_name, start, end, partition=part),
                    )
                )
                self._task_id_seq += 1
            now = time.time()
            for tid, lease in doing.items():
                node_id, task_epoch, start, end, _indices, part = lease
                task = Task(
                    tid,
                    self._task_type,
                    Shard(dataset_name, start, end, partition=part),
                    task_epoch,
                )
                self.doing[tid] = _DoingTask(task, node_id, now)
                self._task_id_seq = max(self._task_id_seq, tid + 1)

    def journal_snapshot(self) -> dict:
        """Lease-preserving state for journal compaction (ids survive,
        unlike :meth:`checkpoint` which folds doing into undone)."""
        with self._lock:
            return {
                "epoch": 0,
                "completed": self._completed_count,
                "splitter": self._splitter.to_checkpoint(),
                "todo": [
                    [t.shard.start, t.shard.end, None, t.shard.partition]
                    for t in self.todo
                ],
                "doing": {
                    tid: {
                        "node": d.node_id,
                        "epoch": d.task.epoch,
                        "start": d.task.shard.start,
                        "end": d.task.shard.end,
                        "idx": None,
                        "part": d.task.shard.partition,
                    }
                    for tid, d in self.doing.items()
                },
                "next_tid": self._task_id_seq,
            }
