"""Split datasets into shards for dynamic dispatch.

Parity: reference dlrover/python/master/shard/dataset_splitter.py
(DatasetSplitter:92, TableDatasetSplitter:146, TextDatasetSplitter:259).
A shard is a [start, end) record range; workers fetch shards as tasks so a
slow/dead worker's pending shards get re-dispatched (data elasticity).
"""

import random
from abc import ABC, abstractmethod
from dataclasses import dataclass
from typing import List, Optional


@dataclass
class Shard:
    name: str
    start: int
    end: int
    record_indices: Optional[List[int]] = None


class DatasetSplitter(ABC):
    def __init__(
        self,
        dataset_name: str,
        dataset_size: int,
        shard_size: int,
        num_epochs: int = 1,
    ):
        self.dataset_name = dataset_name
        self.dataset_size = dataset_size
        self.shard_size = max(shard_size, 1)
        self._num_epochs = max(num_epochs, 1)
        self.epoch = 0

    @abstractmethod
    def create_shards(self) -> List[Shard]:
        ...

    def epoch_finished(self) -> bool:
        return self.epoch >= self._num_epochs


class TableDatasetSplitter(DatasetSplitter):
    """Contiguous range shards over an indexed (table-like) dataset."""

    def __init__(
        self,
        dataset_name: str,
        dataset_size: int,
        shard_size: int,
        num_epochs: int = 1,
        shuffle: bool = False,
        max_shard_count: int = 50000,
    ):
        super().__init__(dataset_name, dataset_size, shard_size, num_epochs)
        self._shuffle = shuffle
        self._max_shard_count = max_shard_count

    def create_shards(self) -> List[Shard]:
        self.epoch += 1
        shards = [
            Shard(
                name=self.dataset_name,
                start=start,
                end=min(start + self.shard_size, self.dataset_size),
            )
            for start in range(0, self.dataset_size, self.shard_size)
        ][: self._max_shard_count]
        if self._shuffle:
            random.shuffle(shards)
        return shards


class TextDatasetSplitter(DatasetSplitter):
    """Shards carrying explicit (possibly shuffled) record indices, for
    line-oriented datasets where a worker reads specific rows."""

    def __init__(
        self,
        dataset_name: str,
        dataset_size: int,
        shard_size: int,
        num_epochs: int = 1,
        shuffle: bool = False,
    ):
        super().__init__(dataset_name, dataset_size, shard_size, num_epochs)
        self._shuffle = shuffle

    def create_shards(self) -> List[Shard]:
        self.epoch += 1
        indices = list(range(self.dataset_size))
        if self._shuffle:
            random.shuffle(indices)
        shards = []
        for start in range(0, self.dataset_size, self.shard_size):
            end = min(start + self.shard_size, self.dataset_size)
            shards.append(
                Shard(
                    name=self.dataset_name,
                    start=start,
                    end=end,
                    record_indices=indices[start:end],
                )
            )
        return shards


def create_dataset_splitter(
    storage_type: str,
    dataset_name: str,
    dataset_size: int,
    shard_size: int,
    num_epochs: int = 1,
    shuffle: bool = False,
) -> DatasetSplitter:
    if storage_type == "text":
        return TextDatasetSplitter(
            dataset_name, dataset_size, shard_size, num_epochs, shuffle
        )
    return TableDatasetSplitter(
        dataset_name, dataset_size, shard_size, num_epochs, shuffle
    )
