"""Split datasets into shards for dynamic dispatch.

Parity: reference dlrover/python/master/shard/dataset_splitter.py
(DatasetSplitter:92, TableDatasetSplitter:146, TextDatasetSplitter:259).
A shard is a [start, end) record range; workers fetch shards as tasks so a
slow/dead worker's pending shards get re-dispatched (data elasticity).
"""

import random
from abc import ABC, abstractmethod
from dataclasses import dataclass
from typing import List, Optional


@dataclass
class Shard:
    name: str
    start: int
    end: int
    record_indices: Optional[List[int]] = None
    # Source partition for streaming datasets ([start, end) offsets are
    # per-partition in a message queue / log store).
    partition: int = 0


class DatasetSplitter(ABC):
    def __init__(
        self,
        dataset_name: str,
        dataset_size: int,
        shard_size: int,
        num_epochs: int = 1,
    ):
        self.dataset_name = dataset_name
        self.dataset_size = dataset_size
        self.shard_size = max(shard_size, 1)
        self._num_epochs = max(num_epochs, 1)
        self.epoch = 0

    @abstractmethod
    def create_shards(self) -> List[Shard]:
        ...

    def epoch_finished(self) -> bool:
        return self.epoch >= self._num_epochs


class TableDatasetSplitter(DatasetSplitter):
    """Contiguous range shards over an indexed (table-like) dataset."""

    def __init__(
        self,
        dataset_name: str,
        dataset_size: int,
        shard_size: int,
        num_epochs: int = 1,
        shuffle: bool = False,
        max_shard_count: int = 50000,
    ):
        super().__init__(dataset_name, dataset_size, shard_size, num_epochs)
        self._shuffle = shuffle
        self._max_shard_count = max_shard_count

    def create_shards(self) -> List[Shard]:
        self.epoch += 1
        shards = [
            Shard(
                name=self.dataset_name,
                start=start,
                end=min(start + self.shard_size, self.dataset_size),
            )
            for start in range(0, self.dataset_size, self.shard_size)
        ][: self._max_shard_count]
        if self._shuffle:
            random.shuffle(shards)
        return shards


class TextDatasetSplitter(DatasetSplitter):
    """Shards carrying explicit (possibly shuffled) record indices, for
    line-oriented datasets where a worker reads specific rows."""

    def __init__(
        self,
        dataset_name: str,
        dataset_size: int,
        shard_size: int,
        num_epochs: int = 1,
        shuffle: bool = False,
    ):
        super().__init__(dataset_name, dataset_size, shard_size, num_epochs)
        self._shuffle = shuffle

    def create_shards(self) -> List[Shard]:
        self.epoch += 1
        indices = list(range(self.dataset_size))
        if self._shuffle:
            random.shuffle(indices)
        shards = []
        for start in range(0, self.dataset_size, self.shard_size):
            end = min(start + self.shard_size, self.dataset_size)
            shards.append(
                Shard(
                    name=self.dataset_name,
                    start=start,
                    end=end,
                    record_indices=indices[start:end],
                )
            )
        return shards


class StreamingDatasetSplitter(DatasetSplitter):
    """Carve shards incrementally from an unbounded, partitioned source
    (message queue / log store read by record offset).

    Parity: reference master/shard/dataset_splitter.py:361
    (StreamingDatasetSplitter) — ``dataset_size=-1`` means infinite;
    each ``create_shards`` call carves at most ``fetch_shards`` new
    shards, round-robin over partitions, advancing per-partition
    offsets. The offsets (not epochs) are the progress state, so the
    shard checkpoint captures them exactly.
    """

    def __init__(
        self,
        dataset_name: str,
        shard_size: int,
        num_partitions: int = 1,
        dataset_size: int = -1,
        partition_offsets: Optional[dict] = None,
        fetch_shards: int = 16,
    ):
        super().__init__(
            dataset_name, dataset_size, shard_size, num_epochs=1
        )
        self.partition_offsets = dict(
            partition_offsets
            if partition_offsets is not None
            else {p: 0 for p in range(max(num_partitions, 1))}
        )
        self._fetch_shards = fetch_shards
        # Remaining records (-1 = unbounded); counts down for bounded
        # streams so the tail shard is exact.
        self.remaining = dataset_size if dataset_size >= 0 else -1
        self._next_partition = 0

    def create_shards(self) -> List[Shard]:
        shards: List[Shard] = []
        parts = sorted(self.partition_offsets)
        for _ in range(self._fetch_shards):
            if self.remaining == 0:
                break
            p = parts[self._next_partition % len(parts)]
            self._next_partition += 1
            start = self.partition_offsets[p]
            take = self.shard_size
            if self.remaining > 0:
                take = min(take, self.remaining)
                self.remaining -= take
            self.partition_offsets[p] = start + take
            shards.append(
                Shard(
                    name=self.dataset_name,
                    start=start,
                    end=start + take,
                    partition=p,
                )
            )
        return shards

    def epoch_finished(self) -> bool:
        # An unbounded stream never finishes; a bounded one finishes when
        # every record has been carved into a shard.
        return self.remaining == 0

    def to_checkpoint(self) -> dict:
        return {
            "partition_offsets": {
                str(p): o for p, o in self.partition_offsets.items()
            },
            "remaining": self.remaining,
            "shard_size": self.shard_size,
        }

    def restore_checkpoint(self, state: dict):
        self.partition_offsets = {
            int(p): o for p, o in state["partition_offsets"].items()
        }
        self.remaining = state["remaining"]
        self.shard_size = state.get("shard_size", self.shard_size)


def create_dataset_splitter(
    storage_type: str,
    dataset_name: str,
    dataset_size: int,
    shard_size: int,
    num_epochs: int = 1,
    shuffle: bool = False,
    num_partitions: int = 1,
) -> DatasetSplitter:
    if storage_type in ("stream", "streaming", "kafka", "sls"):
        return StreamingDatasetSplitter(
            dataset_name,
            shard_size,
            num_partitions=num_partitions,
            dataset_size=dataset_size,
        )
    if storage_type == "text":
        return TextDatasetSplitter(
            dataset_name, dataset_size, shard_size, num_epochs, shuffle
        )
    return TableDatasetSplitter(
        dataset_name, dataset_size, shard_size, num_epochs, shuffle
    )
