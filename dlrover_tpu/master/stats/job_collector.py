"""Job runtime stats collection and reporting.

Parity: reference dlrover/python/master/stats/ (JobMetricCollector,
reporter.py:233, training_metrics.py) — samples node resource usage,
training throughput, and goodput into typed records and hands them to a
pluggable reporter (in-memory locally; a cluster brain service can
implement StatsReporter to receive them instead).
"""

import abc
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List

from dlrover_tpu.common.log import logger


@dataclass
class RuntimeMetricSample:
    timestamp: float
    global_step: int
    speed: float  # steps/s
    goodput: float  # percent
    worker_count: int
    node_usage: Dict[int, Dict[str, float]] = field(default_factory=dict)


@dataclass
class JobCompletionRecord:
    job_name: str
    success: bool
    exit_reason: str
    duration_s: float
    failure_count: int


class StatsReporter(abc.ABC):
    @abc.abstractmethod
    def report_runtime_sample(self, sample: RuntimeMetricSample):
        ...

    @abc.abstractmethod
    def report_job_completion(self, record: JobCompletionRecord):
        ...


class LocalStatsReporter(StatsReporter):
    """Keeps a bounded in-memory history (the standalone 'brain')."""

    def __init__(self, max_samples: int = 2048):
        self._max = max_samples
        self.samples: List[RuntimeMetricSample] = []
        self.completions: List[JobCompletionRecord] = []

    def report_runtime_sample(self, sample: RuntimeMetricSample):
        self.samples.append(sample)
        del self.samples[: -self._max]

    def report_job_completion(self, record: JobCompletionRecord):
        self.completions.append(record)


class JobMetricCollector:
    def __init__(
        self,
        job_name: str,
        job_manager,
        perf_monitor,
        reporter: StatsReporter = None,
        interval_s: float = 30.0,
    ):
        self._job_name = job_name
        self._job_manager = job_manager
        self._perf_monitor = perf_monitor
        self.reporter = reporter or LocalStatsReporter()
        self._interval_s = interval_s
        self._started_at = time.time()
        self._stopped = threading.Event()
        self._thread = None

    def collect_once(self) -> RuntimeMetricSample:
        usage = {}
        for node in self._job_manager.worker_manager.nodes.values():
            usage[node.id] = {
                "cpu": node.used_resource.cpu,
                "memory_mb": node.used_resource.memory_mb,
            }
        sample = RuntimeMetricSample(
            timestamp=time.time(),
            global_step=self._perf_monitor.global_step,
            speed=self._perf_monitor.running_speed(),
            goodput=self._perf_monitor.goodput(),
            worker_count=len(self._job_manager.worker_manager.alive_nodes()),
            node_usage=usage,
        )
        self.reporter.report_runtime_sample(sample)
        return sample

    def report_completion(self, success: bool, exit_reason: str,
                          failure_count: int):
        self.reporter.report_job_completion(
            JobCompletionRecord(
                job_name=self._job_name,
                success=success,
                exit_reason=exit_reason,
                duration_s=time.time() - self._started_at,
                failure_count=failure_count,
            )
        )

    def start(self):
        self._stopped.clear()
        self._thread = threading.Thread(
            target=self._loop, name="job-metric-collector", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stopped.set()

    def _loop(self):
        while not self._stopped.wait(self._interval_s):
            try:
                self.collect_once()
            except Exception:
                logger.exception("job metric collection failed")
