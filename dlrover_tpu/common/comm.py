"""Control-plane message vocabulary.

The master<->agent protocol is two RPCs — ``get(request) -> response`` and
``report(request) -> ack`` — carrying typed dataclasses (reference:
dlrover/python/proto/elastic_training.proto:26-29 and
dlrover/python/common/comm.py:105-560). Dataclasses here are re-designed
around JAX's coordination model: rendezvous produces the
(coordinator_address, num_processes, process_id) triple plus a mesh-shape
hint instead of a torch process-group world.
"""

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from dlrover_tpu.common.serialize import PickleSerializable


@dataclass
class Message(PickleSerializable):
    """Envelope for both directions of the get/report protocol."""

    node_id: int = -1
    node_type: str = ""
    data: bytes = b""
    # Distributed-trace context ({"trace_id", "span_id"}) stamped by the
    # client when tracing is armed, so the servicer's server span joins
    # the caller's tree (docs/DESIGN.md §29). None when disarmed — and
    # readers use getattr(): envelopes pickled by older builds carry no
    # attribute at all.
    trace: Optional[Dict[str, str]] = None


@dataclass
class BaseRequest(PickleSerializable):
    pass


@dataclass
class BaseResponse(PickleSerializable):
    success: bool = True
    reason: str = ""
    # Monotone incarnation of the master that produced this response,
    # stamped by the servicer when a durable journal is armed (-1 = no
    # journal / pre-journal build). Workers fence on a CHANGE in this
    # value to detect a restarted master and re-register/flush
    # (docs/DESIGN.md §37). Readers use getattr(): responses pickled by
    # older builds carry no attribute at all.
    master_epoch: int = -1


# --------------------------------------------------------------------------
# Rendezvous
# --------------------------------------------------------------------------


@dataclass
class JoinRendezvousRequest(BaseRequest):
    node_id: int = 0
    node_rank: int = 0
    local_world_size: int = 1  # JAX processes per host (usually 1 on TPU)
    rdzv_name: str = ""
    node_unit: int = 1  # hosts per slice: node count must be a multiple
    node_ip: str = ""
    # TPU slice/block index of this host (-1 = ungrouped). Drives
    # complete-group rendezvous, group-aware network check phases, and
    # group-level relaunch.
    node_group: int = -1


@dataclass
class JoinRendezvousResponse(BaseResponse):
    round: int = 0


@dataclass
class CommWorldRequest(BaseRequest):
    node_id: int = 0
    rdzv_name: str = ""


@dataclass
class CommWorld(BaseResponse):
    """A completed rendezvous round.

    ``world`` maps node_rank -> local_world_size for every participant;
    ``group`` partitions nodes during network check (reference
    rdzv_manager.py:_get_comm_world). The agent derives
    ``jax.distributed.initialize`` args from it.
    """

    round: int = 0
    group: int = 0
    world: Dict[int, int] = field(default_factory=dict)
    coordinator_rank: int = -1  # node chosen to host the JAX coordinator
    # Explicit rank ordering (master's topology-aware choice). Process-id
    # assignment MUST follow this list, not the world dict's insertion
    # order — dict order surviving the transport is an artifact of the
    # pickle wire format, and a future proto/JSON transport would
    # silently desynchronize ranks across nodes without this field.
    rank_order: List[int] = field(default_factory=list)
    # node_rank -> slice/node-group id (-1 = ungrouped). Lets workers
    # size the dcn mesh axis even when groups came from explicit
    # DLROVER_TPU_NODE_GROUP env rather than node_unit arithmetic.
    node_groups: Dict[int, int] = field(default_factory=dict)


@dataclass
class RendezvousState(BaseResponse):
    waiting_num: int = 0
    completed: bool = False
    round: int = 0


@dataclass
class NumNodesWaitingRequest(BaseRequest):
    rdzv_name: str = ""


@dataclass
class NumNodesWaitingResponse(BaseResponse):
    waiting_num: int = 0


# --------------------------------------------------------------------------
# Node / network check
# --------------------------------------------------------------------------


@dataclass
class NetworkReadyRequest(BaseRequest):
    pass


@dataclass
class NetworkCheckResultReport(BaseRequest):
    node_id: int = 0
    node_rank: int = 0
    result: float = 0.0  # elapsed seconds of the probe; inf on failure
    succeeded: bool = True


@dataclass
class FaultNodeRequest(BaseRequest):
    pass


@dataclass
class FaultNodeResponse(BaseResponse):
    # Verdict of the last fully-reported check round; -1 while none has
    # concluded (an empty fault list is only meaningful when
    # evaluated_round >= 0). needs_round2 tells agents a suspect-bisection
    # round is pending and they should rejoin the check rendezvous.
    fault_nodes: List[int] = field(default_factory=list)
    evaluated_round: int = -1
    needs_round2: bool = False


@dataclass
class StragglerRequest(BaseRequest):
    pass


@dataclass
class StragglerResponse(BaseResponse):
    stragglers: List[int] = field(default_factory=list)


# --------------------------------------------------------------------------
# Heartbeat & diagnosis
# --------------------------------------------------------------------------


@dataclass
class HeartbeatReport(BaseRequest):
    node_id: int = 0
    timestamp: float = 0.0


@dataclass
class HeartbeatResponse(BaseResponse):
    # Serialized DiagnosisAction instances for the agent to execute.
    actions: List[Any] = field(default_factory=list)


@dataclass
class DiagnosisDataReport(BaseRequest):
    """Generic diagnosis payload (metrics scrape, log tail, chip events)."""

    node_id: int = 0
    data_type: str = ""
    payload: Dict[str, Any] = field(default_factory=dict)
    timestamp: float = 0.0


@dataclass
class NodeFailureReport(BaseRequest):
    node_id: int = 0
    node_rank: int = 0
    error_data: str = ""
    level: str = ""
    restart_count: int = 0
    exit_code: int = 0


@dataclass
class SucceededRequest(BaseRequest):
    node_id: int = 0
    node_type: str = ""


@dataclass
class NodeEventReport(BaseRequest):
    node_id: int = 0
    event_type: str = ""
    reason: str = ""
    message: str = ""


# --------------------------------------------------------------------------
# Resources & performance
# --------------------------------------------------------------------------


@dataclass
class ResourceStats(BaseRequest):
    node_id: int = 0
    cpu_percent: float = 0.0
    memory_mb: float = 0.0
    tpu_duty_cycle: float = 0.0  # chip busy-%
    hbm_used_mb: float = 0.0


@dataclass
class GlobalStepReport(BaseRequest):
    node_id: int = 0
    step: int = 0
    timestamp: float = 0.0
    elapsed_train_secs: float = 0.0  # productive train time since last report
    # This rank's recent per-step wall seconds (0 = not measured): the
    # master's straggler score is per-rank step-time skew, and this
    # piggyback keeps it one existing RPC, not a new verb.
    step_time_s: float = 0.0


@dataclass
class GoodputPhaseReport(BaseRequest):
    """Attributes a span of wall time to a goodput phase (train/ckpt/
    restart/rendezvous), the basis of the goodput metric."""

    node_id: int = 0
    phase: str = ""
    start: float = 0.0
    end: float = 0.0


# --------------------------------------------------------------------------
# KV-store (rendezvous store / barriers for workers)
# --------------------------------------------------------------------------


@dataclass
class KVStoreSetRequest(BaseRequest):
    key: str = ""
    value: bytes = b""


@dataclass
class KVStoreGetRequest(BaseRequest):
    key: str = ""


@dataclass
class KVStoreGetResponse(BaseResponse):
    value: bytes = b""


@dataclass
class KVStoreAddRequest(BaseRequest):
    key: str = ""
    delta: int = 1


@dataclass
class KVStoreAddResponse(BaseResponse):
    value: int = 0


@dataclass
class KVStoreMultiGetRequest(BaseRequest):
    keys: List[str] = field(default_factory=list)


@dataclass
class KVStoreMultiGetResponse(BaseResponse):
    values: Dict[str, bytes] = field(default_factory=dict)


# --------------------------------------------------------------------------
# Sync service (named barriers)
# --------------------------------------------------------------------------


@dataclass
class SyncJoinRequest(BaseRequest):
    sync_name: str = ""
    node_id: int = 0
    node_rank: int = 0


@dataclass
class SyncFinishRequest(BaseRequest):
    sync_name: str = ""


@dataclass
class SyncQueryRequest(BaseRequest):
    sync_name: str = ""


@dataclass
class SyncQueryResponse(BaseResponse):
    done: bool = False


# --------------------------------------------------------------------------
# Dynamic data sharding
# --------------------------------------------------------------------------


@dataclass
class DatasetShardParams(BaseRequest):
    dataset_name: str = ""
    dataset_size: int = 0  # -1 with a streaming storage_type = unbounded
    shard_size: int = 0  # records per task/shard
    num_epochs: int = 1
    shuffle: bool = False
    storage_type: str = "text"  # "table" | "text" | "stream"
    task_type: str = "training"
    # Streaming sources (message queues / log stores) are partitioned;
    # shards carry the partition they were carved from.
    num_partitions: int = 1


@dataclass
class TaskRequest(BaseRequest):
    dataset_name: str = ""
    node_id: int = 0


@dataclass
class ShardTask(BaseResponse):
    task_id: int = -1
    task_type: str = "none"
    dataset_name: str = ""
    start: int = 0
    end: int = 0
    epoch: int = 0
    # Explicit (possibly shuffled) record indices for text datasets; None
    # means the contiguous [start, end) range.
    record_indices: Optional[List[int]] = None
    # Source partition of a streaming shard ([start, end) offsets are
    # per-partition for message-queue/log-store datasets).
    partition: int = 0


@dataclass
class TaskDoneReport(BaseRequest):
    dataset_name: str = ""
    task_id: int = -1
    node_id: int = 0
    # False re-queues the shard (streaming sources retry a failed shard
    # up to its retry budget before dropping it).
    success: bool = True


@dataclass
class MultiTaskRequest(BaseRequest):
    """Batched lease request: up to ``count`` shards in one round trip.

    The prefetcher's verb — a worker keeping N shards in flight pays one
    RPC per batch instead of one per shard boundary."""

    dataset_name: str = ""
    node_id: int = 0
    count: int = 1


@dataclass
class MultiTaskResponse(BaseResponse):
    """``tasks`` holds real shard leases only. An empty list with
    ``wait=True`` means peers hold the remaining shards in flight (the
    single-task WAIT sentinel, batched); empty with ``wait=False`` means
    the dataset is exhausted."""

    tasks: List["ShardTask"] = field(default_factory=list)
    wait: bool = False


@dataclass
class TaskDoneBatchReport(BaseRequest):
    """Coalesced done-reports: every shard id in ``done_ids`` completed
    successfully, every id in ``failed_ids`` must be re-queued."""

    dataset_name: str = ""
    node_id: int = 0
    done_ids: List[int] = field(default_factory=list)
    failed_ids: List[int] = field(default_factory=list)


@dataclass
class ShardCheckpointRequest(BaseRequest):
    dataset_name: str = ""


@dataclass
class ShardCheckpointResponse(BaseResponse):
    checkpoint: str = ""  # JSON blob of undone shards


@dataclass
class ShardCheckpointRestoreRequest(BaseRequest):
    dataset_name: str = ""
    checkpoint: str = ""


# --------------------------------------------------------------------------
# Live elastic rescale (plan broadcast + barrier; docs/DESIGN.md §27)
# --------------------------------------------------------------------------


@dataclass
class RescaleJoinReport(BaseRequest):
    """A worker announcing itself to the rescale plane — at process start
    (bootstrap / scale-up join) the coordinator folds it into the live
    set and, mid-run, a join triggers a scale-up plan."""

    node_id: int = 0
    node_rank: int = 0
    local_world_size: int = 1
    # TPU slice/block index (-1 = ungrouped) — lets the coordinator form
    # worlds from complete blocks only, like rendezvous does.
    node_group: int = -1


@dataclass
class RescalePlanRequest(BaseRequest):
    """Poll for a rescale plan newer than ``current_plan_id`` (-1 = any).
    Pull-based broadcast: the versioned plan is fetched, not pushed, so a
    dropped reply costs one poll interval, never a lost plan."""

    node_id: int = 0
    node_rank: int = 0
    current_plan_id: int = -1


@dataclass
class RescalePlanResponse(BaseResponse):
    """A versioned rescale plan. ``plan_id`` is -1 when no newer plan
    exists. ``world`` maps node_rank -> local_world_size for the NEW
    world; a polling rank absent from ``world`` has been evicted.
    ``restore_step`` is the last committed checkpoint step every
    survivor must restore (-1 = fresh/bootstrap)."""

    plan_id: int = -1
    world: Dict[int, int] = field(default_factory=dict)
    rank_order: List[int] = field(default_factory=list)
    restore_step: int = -1
    reason: str = ""
    created_at: float = 0.0
    barrier_timeout_s: float = 30.0


@dataclass
class RescaleAckReport(BaseRequest):
    """Worker progress through a plan's phases ("barrier": data path
    torn down, done-reports flushed; "restored": state + shard cursor
    restored at the plan step; "resumed": first post-rescale step about
    to run). Idempotent — safe under RPC retry."""

    node_id: int = 0
    node_rank: int = 0
    plan_id: int = -1
    phase: str = "barrier"


@dataclass
class RescaleBarrierRequest(BaseRequest):
    node_id: int = 0
    node_rank: int = 0
    plan_id: int = -1
    phase: str = "barrier"


@dataclass
class RescaleBarrierResponse(BaseResponse):
    """``ready``: every rank of the plan's world acked ``phase``.
    ``superseded``: a newer plan exists — abandon this barrier and poll
    the plan verb again. ``expired``: the bounded wait ran out; the
    coordinator has already re-planned around the missing ranks."""

    ready: bool = False
    expired: bool = False
    superseded: bool = False
    missing: List[int] = field(default_factory=list)


# --------------------------------------------------------------------------
# Checkpoint coordination
# --------------------------------------------------------------------------


@dataclass
class CkptStepReport(BaseRequest):
    node_id: int = 0
    step: int = 0
    committed: bool = False


@dataclass
class CkptLatestStepRequest(BaseRequest):
    pass


@dataclass
class CkptLatestStepResponse(BaseResponse):
    step: int = -1


# --------------------------------------------------------------------------
# Pre-check, config, job control
# --------------------------------------------------------------------------


@dataclass
class PreCheckRequest(BaseRequest):
    node_id: int = 0


@dataclass
class PreCheckResponse(BaseResponse):
    status: str = "PASS"


@dataclass
class ParallelConfigRequest(BaseRequest):
    node_id: int = 0


@dataclass
class ParallelConfig(BaseResponse):
    """Master-suggested runtime knobs (reference ParallelConfig /
    hyperparams/simple_strategy_generator.py), re-pointed at JAX knobs."""

    micro_batch_size: int = 0
    grad_accum_steps: int = 0
    remat_policy: str = ""
    mesh_shape: Dict[str, int] = field(default_factory=dict)
    version: int = 0


@dataclass
class ElasticRunConfigRequest(BaseRequest):
    pass


@dataclass
class ElasticRunConfigResponse(BaseResponse):
    configs: Dict[str, str] = field(default_factory=dict)


@dataclass
class JobDetailRequest(BaseRequest):
    pass


@dataclass
class JobDetailResponse(BaseResponse):
    job_name: str = ""
    stage: str = ""
    nodes: Dict[int, Dict[str, Any]] = field(default_factory=dict)


# --------------------------------------------------------------------------
# Cluster version tracking (PS-style elasticity parity; reference
# master/elastic_training/elastic_ps.py)
# --------------------------------------------------------------------------


@dataclass
class ClusterVersionRequest(BaseRequest):
    task_type: str = ""
    task_id: int = 0
    version_type: str = ""


@dataclass
class ClusterVersionResponse(BaseResponse):
    version: int = 0


@dataclass
class ClusterVersionReport(BaseRequest):
    task_type: str = ""
    task_id: int = 0
    version_type: str = ""
    version: int = 0


def now() -> float:
    return time.time()
