"""Shared constants for the control plane.

Parity map: reference dlrover/python/common/constants.py (NodeType,
NodeStatus, RendezvousName, JobExitReason, ...) — re-derived for TPU
terminology (hosts in a slice, ICI/DCN, JAX processes) rather than copied.
"""


class NodeType:
    """Roles a node (one TPU host / one process group member) can play."""

    WORKER = "worker"
    MASTER = "master"
    # Parameter-server era roles kept for API parity with PS-style jobs
    # (reference common/constants.py NodeType); unused in pure SPMD jobs.
    PS = "ps"
    CHIEF = "chief"
    EVALUATOR = "evaluator"


class NodeStatus:
    """Lifecycle states of a supervised node.

    Mirrors the legal-transition vocabulary of the reference
    (master/node/status_flow.py) with k8s Pod phases generalized to
    "scheduled process units".
    """

    INITIAL = "Initial"
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    DELETED = "Deleted"
    BREAKDOWN = "Breakdown"  # machine-level fault (node check failed)
    UNKNOWN = "Unknown"

    @classmethod
    def end_states(cls):
        return {cls.SUCCEEDED, cls.FAILED, cls.DELETED, cls.BREAKDOWN}


class NodeEventType:
    ADDED = "ADDED"
    MODIFIED = "MODIFIED"
    DELETED = "DELETED"
    # Health-related event types surfaced by diagnosis.
    NODE_CHECK_FAILED = "NODE_CHECK_FAILED"
    STRAGGLER = "STRAGGLER"


class NodeExitReason:
    """Why a worker process/pod exited; drives relaunch policy
    (reference master/node/dist_job_manager.py:_should_relaunch)."""

    SUCCEEDED = "Succeeded"
    KILLED = "Killed"
    OOM = "OOMKilled"
    FATAL_ERROR = "FatalError"  # unrecoverable: never relaunch
    SOFTWARE_ERROR = "SoftwareError"  # app crash: bounded relaunch
    HARDWARE_ERROR = "HardwareError"  # relaunch on a new machine
    PREEMPTED = "Preempted"  # cloud preemption: always relaunch
    UNKNOWN = "Unknown"


# Relaunch budget per exit reason, as a multiple of a node's
# max_relaunch_count (reference dist_job_manager.py:996 differentiates
# reasons when deciding relaunch; the factors bound each failure mode
# separately so a preemption storm can't be starved by one OOM and a
# crash loop can't relaunch forever).
# Worker-log markers shared by the agent's failure diagnosis and the
# master's exit classifier — one source so the two sides never disagree.
# OOM covers host RAM (MemoryError, oom-killer) and device HBM (XLA
# RESOURCE_EXHAUSTED); hardware covers TPU/runtime init faults.
OOM_LOG_MARKERS = (
    r"resource_exhausted",
    r"out of memory",
    r"memoryerror",
    r"oom[- _]?kill",
    r"hbm.*exceed",
)
HARDWARE_LOG_MARKERS = (
    r"tpu.*(unavailable|unhealthy|not found)",
    r"libtpu.*(fail|error)",
    r"pjrt.*init.*fail",
    r"device or resource busy",
    r"uncorrectable ecc",
)

RELAUNCH_BUDGET_FACTOR = {
    NodeExitReason.PREEMPTED: 10.0,
    NodeExitReason.KILLED: 2.0,
    NodeExitReason.OOM: 1.0,
    NodeExitReason.HARDWARE_ERROR: 1.0,
    NodeExitReason.SOFTWARE_ERROR: 1.0,
    NodeExitReason.UNKNOWN: 1.0,
    NodeExitReason.FATAL_ERROR: 0.0,
}


class ExitCode:
    """Process exit codes with special relaunch semantics."""

    SUCCESS = 0
    KILLED = 137  # 128 + SIGKILL
    TERMED = 143  # 128 + SIGTERM
    FATAL = 1
    SCRIPT_ERROR = 2
    # Agent-chosen codes:
    NODE_CHECK_FAILED = 3
    GPU_DRIVER_ERROR = 201
    HARDWARE_ERROR = 202


class JobStage:
    INIT = "INIT"
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUSPENDED = "SUSPENDED"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPING = "STOPPING"


class JobExitReason:
    SUCCEEDED = "Succeeded"
    CODE_ERROR = "CodeError"
    WORKER_OOM = "WorkerOOM"
    WORKER_ERROR = "WorkerError"
    HANG_ERROR = "HangError"
    UNKNOWN = "Unknown"


class RendezvousName:
    """The two rendezvous domains (reference
    master/elastic_training/rdzv_manager.py)."""

    TRAINING = "training"
    NETWORK_CHECK = "network-check"


class RendezvousConstant:
    MAX_WAIT_SECS = 600
    PEND_TIMEOUT_SECS = 600
    JOIN_TIMEOUT_SECS = 600


class TrainingExceptionLevel:
    PROCESS_ERROR = "process"
    NODE_ERROR = "node"
    RDZV_ERROR = "rdzv"
    WARNING = "warning"
    INFO = "info"


class PlatformType:
    LOCAL = "local"
    KUBERNETES = "k8s"
    RAY = "ray"
    GKE_TPU = "gke_tpu"


class CommunicationType:
    COMM_SERVICE_GRPC = "grpc"
    COMM_SERVICE_HTTP = "http"


class NodeEnv:
    """Environment variables of the control-plane protocol between master,
    agent and worker processes (reference common/constants.py NodeEnv)."""

    MASTER_ADDR = "DLROVER_TPU_MASTER_ADDR"
    JOB_NAME = "DLROVER_TPU_JOB_NAME"
    NODE_ID = "DLROVER_TPU_NODE_ID"
    NODE_RANK = "DLROVER_TPU_NODE_RANK"
    NODE_NUM = "DLROVER_TPU_NODE_NUM"
    JOB_UUID = "DLROVER_TPU_JOB_UUID"
    # Flag telling the worker process which UDS root dir the agent shared
    # objects (queues/locks/shm metadata) live under.
    SHARED_DIR = "DLROVER_TPU_SHARED_DIR"
    # Restart bookkeeping
    RESTART_COUNT = "DLROVER_TPU_RESTART_COUNT"
    # Monitoring switch
    MONITOR_ENABLED = "DLROVER_TPU_MONITOR_ENABLED"
    AUTO_CKPT = "DLROVER_TPU_AUTO_CKPT"


class WorkerEnv:
    """Env vars injected into each JAX worker process by the agent.

    These replace torchrun's WORLD_SIZE/RANK vocabulary with the triple
    ``jax.distributed.initialize`` needs, plus local process coords.
    """

    COORDINATOR_ADDRESS = "DLROVER_TPU_COORDINATOR"
    NUM_PROCESSES = "DLROVER_TPU_NUM_PROCESSES"
    PROCESS_ID = "DLROVER_TPU_PROCESS_ID"
    LOCAL_RANK = "DLROVER_TPU_LOCAL_RANK"
    LOCAL_WORLD_SIZE = "DLROVER_TPU_LOCAL_WORLD_SIZE"
    RESTART_COUNT = "DLROVER_TPU_RESTART_COUNT"
    RDZV_ROUND = "DLROVER_TPU_RDZV_ROUND"
    # Comma-separated node ranks of the current world (commit protocol
    # needs the ACTUAL membership, not arithmetic over process counts).
    NODE_RANKS = "DLROVER_TPU_NODE_RANKS"
    # Node groups (TPU slices) in the world: with the group-major rank
    # order, a dcn mesh axis of this size maps one group per slice row —
    # what a worker needs to build a multi-slice mesh.
    NUM_SLICES = "DLROVER_TPU_NUM_SLICES"


class JobConstant:
    RDZV_JOIN_TIMEOUT_DEFAULT = 600
    MASTER_CLIENT_TIMEOUT_DEFAULT = 10
    MASTER_CLIENT_DEFAULT_RETRY = 3
    TRAINING_AGENT_LOOP_INTERVAL = 2
    MASTER_RUN_LOOP_INTERVAL = 5
    NODE_HEARTBEAT_INTERVAL = 15
    HEARTBEAT_TIMEOUT_SECS = 600
    # Interval the perf monitor uses to compute throughput
    PERF_SAMPLE_INTERVAL = 10


class CheckpointConstant:
    """Flash checkpoint naming (reference
    dlrover/python/common/constants.py CheckpointConstant)."""

    TRACKER_FILE = "latest_checkpointed_iteration.txt"
    STEP_DIR_PREFIX = "checkpoint-"
    DONE_DIR = "._dlrover_ckpt_done"
    STAGE_DIR = "._dlrover_ckpt_stage"
    MODEL_STATES_NAME = "model_states"
    SAVE_TIMEOUT = 600
    # KV-store key under which the master publishes the per-job replica
    # auth token (seeded in servicer, consumed by flash_ckpt/replica.py).
    REPLICA_TOKEN_KEY = "ckpt-replica/token"


class NetworkCheckConstant:
    MATMUL_SIZE = 1024  # per-chip MXU probe GEMM dimension
    MATMUL_ROUNDS = 30
    ALLREDUCE_MB = 64
    STRAGGLER_RATIO = 2.0  # slower than 2x median => straggler
    CHECK_TIMEOUT = 300


class PreCheckStatus:
    CHECKING = "CHECKING"
    PASS = "PASS"
    FAIL = "FAIL"
    DISABLED = "DISABLED"


class DiagnosisConstant:
    MASTER_INSTANCE = -1
    ANY_INSTANCE = -2
    ACTION_EXPIRED_SECS = 600
    MASTER_OBSERVE_INTERVAL = 60
    AGENT_PERIODICAL_REPORT_INTERVAL = 60


class DiagnosisActionType:
    NONE = "no_action"
    EVENT = "event"
    RESTART_WORKER = "restart_worker"  # soft: restart processes in place
    RELAUNCH_WORKER = "relaunch_worker"  # hard: replace the node
    JOB_RESTART = "job_restart"
    JOB_ABORT = "job_abort"


class TaskType:
    """Dynamic data sharding task types (reference master/shard)."""

    TRAINING = "training"
    EVALUATION = "evaluation"
    PREDICTION = "prediction"
    WAIT = "wait"
    NONE = "none"


class DatasetType:
    TEXT = "text"
    TABLE = "table"


class GoodputPhase:
    """Phases used by the perf monitor to attribute wall time."""

    INIT = "init"
    TRAIN = "train"
    CKPT = "ckpt"
    RESTART = "restart"
    RENDEZVOUS = "rendezvous"


class EventReportConstants:
    TYPE_INFO = "info"
    TYPE_WARN = "warn"
    TYPE_ERROR = "error"
    ACTION_STOP = "stop"
    ACTION_START = "start"
