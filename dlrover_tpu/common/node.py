"""Node domain model held by the job master.

Parity: reference dlrover/python/common/node.py:44-460 (Node, NodeResource,
NodeGroupResource, NodeEvent). A "node" here is one TPU host (one JAX
process slot) inside a slice, or a CPU worker in local mode.
"""

import copy
import time
from dataclasses import dataclass, field
from typing import Optional

from dlrover_tpu.common.constants import (
    RELAUNCH_BUDGET_FACTOR,
    NodeEventType,
    NodeExitReason,
    NodeStatus,
    NodeType,
)


@dataclass
class NodeResource:
    """Requested/used resources of one node."""

    cpu: float = 0.0
    memory_mb: float = 0.0
    tpu_chips: int = 0
    tpu_type: str = ""  # e.g. "v5litepod"
    priority: str = ""

    def is_empty(self) -> bool:
        return (
            self.cpu <= 0
            and self.memory_mb <= 0
            and self.tpu_chips <= 0
            and not self.tpu_type
        )

    @classmethod
    def resource_str_to_node_resource(cls, resource: str) -> "NodeResource":
        """Parse "cpu=4,memory=8192Mi,tpu=4" style strings."""
        res = cls()
        if not resource:
            return res
        for kv in resource.split(","):
            if "=" not in kv:
                continue
            k, v = kv.split("=", 1)
            k = k.strip().lower()
            v = v.strip()
            if k == "cpu":
                res.cpu = float(v)
            elif k == "memory":
                res.memory_mb = float(v.rstrip("Mi").rstrip("mi"))
            elif k in ("tpu", "tpu_chips"):
                res.tpu_chips = int(v)
        return res


@dataclass
class NodeGroupResource:
    """Resource template for one role group (count x per-node resource)."""

    count: int = 0
    node_resource: NodeResource = field(default_factory=NodeResource)


class Node:
    """Mutable per-node record tracked by the master's job manager."""

    def __init__(
        self,
        node_type: str = NodeType.WORKER,
        node_id: int = 0,
        rank_index: Optional[int] = None,
        name: str = "",
        host_name: str = "",
        host_ip: str = "",
        status: str = NodeStatus.INITIAL,
        config_resource: Optional[NodeResource] = None,
        max_relaunch_count: int = 3,
    ):
        self.type = node_type
        self.id = node_id
        self.rank_index = rank_index if rank_index is not None else node_id
        self.name = name or f"{node_type}-{node_id}"
        self.host_name = host_name
        self.host_ip = host_ip
        self.status = status
        self.config_resource = config_resource or NodeResource()
        self.used_resource = NodeResource()
        self.max_relaunch_count = max_relaunch_count

        self.relaunch_count = 0
        self.relaunchable = True
        self.is_released = False
        self.exit_reason = ""
        # Every classified exit of this rank's lineage (survives
        # relaunches): drives the per-reason relaunch budgets
        # (master/node/exit_reason.py).
        self.exit_history: list = []
        # When the master asked the backend for this node; pending-timeout
        # is measured from here.
        self.create_time: Optional[float] = time.time()
        self.start_time: Optional[float] = None
        self.finish_time: Optional[float] = None
        self.heartbeat_time: float = 0.0
        self.restart_training = False
        self.critical = False
        # TPU slice/block index (-1 = ungrouped); a hardware fault on
        # one member relaunches the whole block together (ICI needs the
        # full slice) while other blocks keep running.
        self.node_group = -1
        self.migrated = False
        self.paral_config_version = -1
        self.reported_status: str = ""
        # (ts, status) transitions — the dashboard's node-detail
        # timeline; bounded so a crash-looping node can't grow it.
        self.status_history: list = [(time.time(), status)]

    # ---- status transitions -------------------------------------------------

    def update_status(self, status: str) -> bool:
        from dlrover_tpu.master.node.status_flow import NodeStateFlow

        allowed = NodeStateFlow.transition_allowed(self.status, status)
        if allowed:
            if (
                status == NodeStatus.RUNNING
                and self.status != NodeStatus.RUNNING
            ):
                self.start_time = time.time()
            if status in NodeStatus.end_states():
                self.finish_time = time.time()
            self.status = status
            self.status_history.append((time.time(), status))
            del self.status_history[:-50]
        return allowed

    def is_end(self) -> bool:
        return self.status in NodeStatus.end_states()

    def is_unrecoverable_failure(self) -> str:
        """Return a non-empty reason if this node must not be relaunched.

        With a classified exit history, each reason spends its own
        budget (RELAUNCH_BUDGET_FACTOR x max_relaunch_count): ten
        preemptions must not be blocked by the generic cap, while a
        crash loop exhausts its smaller budget quickly. Without history
        (legacy callers), the flat relaunch_count cap applies.
        """
        if not self.relaunchable:
            return "node not relaunchable"
        if self.exit_reason == NodeExitReason.FATAL_ERROR:
            return "fatal software error"
        if self.exit_history:
            reason = self.exit_reason or NodeExitReason.UNKNOWN
            budget = int(
                self.max_relaunch_count
                * RELAUNCH_BUDGET_FACTOR.get(reason, 1.0)
            )
            count = self.exit_count(reason)
            if count > budget:
                return (
                    f"{reason} exits {count} > budget {budget} "
                    f"(max_relaunch {self.max_relaunch_count})"
                )
            return ""
        if self.relaunch_count >= self.max_relaunch_count:
            return (
                f"relaunch count {self.relaunch_count} >= "
                f"max {self.max_relaunch_count}"
            )
        return ""

    def inc_relaunch_count(self):
        self.relaunch_count += 1

    def record_exit(self, reason: str):
        self.exit_history.append(reason)

    def exit_count(self, reason: str) -> int:
        return self.exit_history.count(reason)

    def update_from_resource_stats(self, cpu: float, memory_mb: float):
        self.used_resource.cpu = cpu
        self.used_resource.memory_mb = memory_mb

    def get_relaunch_node(self, new_id: int) -> "Node":
        """Build the replacement node record after a relaunch decision."""
        new_node = copy.copy(self)
        new_node.id = new_id
        new_node.name = f"{self.type}-{new_id}"
        new_node.status = NodeStatus.INITIAL
        new_node.create_time = time.time()
        new_node.start_time = None
        new_node.finish_time = None
        new_node.is_released = False
        new_node.exit_reason = ""
        new_node.relaunch_count = self.relaunch_count + 1
        # The lineage's exit history rides along (shared list: past
        # exits are immutable facts about the rank, not the pod).
        new_node.exit_history = self.exit_history
        # Fresh timeline: the POD's life starts now (copy.copy would
        # share the predecessor's list — appends from either object
        # would cross-pollute both dashboards' timelines).
        new_node.status_history = [(time.time(), NodeStatus.INITIAL)]
        new_node.used_resource = NodeResource()
        new_node.heartbeat_time = 0
        return new_node

    def __repr__(self):
        return (
            f"Node(type={self.type}, id={self.id}, rank={self.rank_index}, "
            f"status={self.status}, relaunches={self.relaunch_count})"
        )


@dataclass
class NodeEvent:
    """An observed change of a node, produced by watchers or the agent."""

    event_type: str = NodeEventType.MODIFIED
    node: Optional[Node] = None

    def is_node_check_failed(self) -> bool:
        return self.event_type == NodeEventType.NODE_CHECK_FAILED
