"""Environment-variable helpers for the master/agent/worker protocol."""

import os
import socket

from dlrover_tpu.common.constants import NodeEnv, WorkerEnv


def get_env_int(name: str, default: int = 0) -> int:
    try:
        return int(os.getenv(name, default))
    except (TypeError, ValueError):
        return default


TRUTHY = ("1", "true", "yes", "on")


def env_bool(mapping, name: str, default: bool = False) -> bool:
    """Truthy test over any mapping (os.environ or a merged env dict) —
    the ONE definition of the vocabulary; hand-rolled tuples drift."""
    value = mapping.get(name, "")
    if not value:
        return default
    return value.strip().lower() in TRUTHY


def get_env_bool(name: str, default: bool = False) -> bool:
    return env_bool(os.environ, name, default)


_WARNED_CHOICES: set = set()


def resolve_env_choice(name: str, allowed, default: str) -> str:
    """Env knob constrained to ``allowed`` values, warning ONCE per
    unrecognized value and falling back to ``default`` — a typo in a
    kernel A/B knob must be LOUD, or the experiment silently measures
    the wrong path. The one definition of the pattern (kv dtype, MoE
    dispatch, decode attention all use it)."""
    raw = os.environ.get(name, default).lower()
    if raw in allowed:
        return raw
    if (name, raw) not in _WARNED_CHOICES:
        _WARNED_CHOICES.add((name, raw))
        import logging

        logging.getLogger(__name__).warning(
            "%s=%r is not one of %s; falling back to %r",
            name, raw, tuple(allowed), default,
        )
    return default


def get_env_str(name: str, default: str = "") -> str:
    return os.getenv(name, default)


def get_node_id() -> int:
    return get_env_int(NodeEnv.NODE_ID, 0)


def get_node_rank() -> int:
    return get_env_int(NodeEnv.NODE_RANK, get_node_id())


def get_node_num() -> int:
    return get_env_int(NodeEnv.NODE_NUM, 1)


def get_master_addr() -> str:
    return get_env_str(NodeEnv.MASTER_ADDR, "")


def get_hostname_ip():
    hostname = socket.gethostname()
    try:
        ip = socket.gethostbyname(hostname)
    except socket.gaierror:
        ip = "127.0.0.1"
    return hostname, ip


def find_free_port(start: int = 0) -> int:
    """Ask the OS for a free TCP port (bind to 0) or probe from ``start``."""
    if start == 0:
        with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
            s.bind(("", 0))
            return s.getsockname()[1]
    for port in range(start, start + 1000):
        with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
            try:
                s.bind(("", port))
                return port
            except OSError:
                continue
    raise RuntimeError("no free port found")


def worker_env(
    coordinator: str,
    num_processes: int,
    process_id: int,
    local_rank: int = 0,
    local_world_size: int = 1,
    restart_count: int = 0,
    rdzv_round: int = 0,
    node_ranks=None,
    num_slices: int = 1,
) -> dict:
    """Build the env block the agent injects into each JAX worker."""
    env = {
        WorkerEnv.COORDINATOR_ADDRESS: coordinator,
        WorkerEnv.NUM_PROCESSES: str(num_processes),
        WorkerEnv.PROCESS_ID: str(process_id),
        WorkerEnv.LOCAL_RANK: str(local_rank),
        WorkerEnv.LOCAL_WORLD_SIZE: str(local_world_size),
        WorkerEnv.RESTART_COUNT: str(restart_count),
        WorkerEnv.RDZV_ROUND: str(rdzv_round),
        WorkerEnv.NUM_SLICES: str(num_slices),
    }
    if node_ranks:
        env[WorkerEnv.NODE_RANKS] = ",".join(str(r) for r in node_ranks)
    return env
