"""Message (de)serialization for control-plane RPC.

The reference ships pickled dataclasses inside a 2-method gRPC service
(dlrover/python/common/comm.py:105-560, serialize.py). We keep the same
wire shape — a class-name tag + pickled payload — because control-plane
messages are small and trusted (master and agents are the same codebase in
the same security domain), but restrict unpickling to registered message
classes to avoid arbitrary-code deserialization.
"""

import importlib
import io
import pickle
from typing import Any

_ALLOWED_MODULE_PREFIXES = (
    "dlrover_tpu.",
    "collections",
    "numpy",
    "datetime",
)

# ``builtins`` must NOT be allowed wholesale: builtins.eval/exec/getattr are
# classic pickle RCE gadgets. Only value constructors that real messages use.
_ALLOWED_BUILTINS = frozenset(
    {
        "bool", "int", "float", "complex", "str", "bytes", "bytearray",
        "list", "tuple", "dict", "set", "frozenset", "slice", "range",
        "object", "NoneType", "Exception",
    }
)

# Extra names needed to unpickle a jax pytree structure — used by the
# flash-checkpoint shm/storage metadata loader, never by the control-plane
# RPC path. PyTreeDef's reducer references the jaxlib extension class and
# the default registry; the exact module path moved across jaxlib
# versions, so match by name under jax/jaxlib prefixes.
_PYTREE_NAMES = frozenset({"PyTreeDef", "default_registry", "pytree"})

# Module prefixes whose *classes* may appear as custom pytree node types
# in a real training state: optimizer states are optax NamedTuples, train
# states are flax struct dataclasses, etc. Users register their own node
# modules via DLROVER_TPU_PYTREE_MODULES (comma-separated prefixes).
_PYTREE_NODE_PREFIXES = (
    "jax",
    "jaxlib",
    "optax",
    "flax",
    "chex",
    "haiku",
    "ml_dtypes",
)


def _extra_pytree_prefixes():
    import os

    raw = os.getenv("DLROVER_TPU_PYTREE_MODULES", "")
    return tuple(p.strip() for p in raw.split(",") if p.strip())


class _RestrictedUnpickler(pickle.Unpickler):
    allow_pytree = False

    def find_class(self, module: str, name: str):
        if module == "builtins":
            if name in _ALLOWED_BUILTINS:
                return getattr(importlib.import_module(module), name)
        elif any(
            module == p.rstrip(".") or module.startswith(p)
            for p in _ALLOWED_MODULE_PREFIXES
        ):
            return getattr(importlib.import_module(module), name)
        elif self.allow_pytree:
            root = module.split(".", 1)[0]
            if root in _PYTREE_NODE_PREFIXES or any(
                module == p or module.startswith(p + ".") or root == p
                for p in _extra_pytree_prefixes()
            ):
                obj = getattr(importlib.import_module(module), name)
                # Admit classes (pytree node types: NamedTuples, struct
                # dataclasses) and the known jax registry singletons, but
                # never plain functions — REDUCE on an arbitrary callable
                # is the code-execution gadget this loader exists to block.
                if isinstance(obj, type) or name in _PYTREE_NAMES:
                    return obj
        raise pickle.UnpicklingError(
            f"blocked unpickle of {module}.{name}: not a control-plane type"
        )


class _PytreeUnpickler(_RestrictedUnpickler):
    allow_pytree = True


def dumps(obj: Any) -> bytes:
    return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)


def loads(data: bytes) -> Any:
    return _RestrictedUnpickler(io.BytesIO(data)).load()


def loads_pytree(data: bytes) -> Any:
    """Restricted unpickle that additionally admits jax PyTreeDef.

    For checkpoint metadata (shm images, storage shard meta) which embeds
    pickled tree structures; everything else stays locked down, so a
    hostile payload reaching a checkpoint port cannot execute code.
    """
    return _PytreeUnpickler(io.BytesIO(data)).load()


class PickleSerializable:
    """Mixin for messages; kept trivially small so dataclasses stay plain."""

    def serialize(self) -> bytes:
        return dumps(self)

    @classmethod
    def deserialize(cls, data: bytes):
        return loads(data)
