"""Message (de)serialization for control-plane RPC.

The reference ships pickled dataclasses inside a 2-method gRPC service
(dlrover/python/common/comm.py:105-560, serialize.py). We keep the same
wire shape — a class-name tag + pickled payload — because control-plane
messages are small and trusted (master and agents are the same codebase in
the same security domain), but restrict unpickling to registered message
classes to avoid arbitrary-code deserialization.
"""

import importlib
import io
import pickle
from typing import Any

_ALLOWED_MODULE_PREFIXES = (
    "dlrover_tpu.",
    "builtins",
    "collections",
    "numpy",
    "datetime",
)


class _RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module: str, name: str):
        if module == "builtins" or any(
            module == p.rstrip(".") or module.startswith(p)
            for p in _ALLOWED_MODULE_PREFIXES
        ):
            return getattr(importlib.import_module(module), name)
        raise pickle.UnpicklingError(
            f"blocked unpickle of {module}.{name}: not a control-plane type"
        )


def dumps(obj: Any) -> bytes:
    return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)


def loads(data: bytes) -> Any:
    return _RestrictedUnpickler(io.BytesIO(data)).load()


class PickleSerializable:
    """Mixin for messages; kept trivially small so dataclasses stay plain."""

    def serialize(self) -> bytes:
        return dumps(self)

    @classmethod
    def deserialize(cls, data: bytes):
        return loads(data)
