"""Master-side cluster accelerator-metric monitor.

Parity: reference dlrover/python/common/metric/monitor.py:43-503 +
metric_context.py — a job-level monitor that scrapes an EXTERNAL
GPU/NPU metrics API on an interval into a windowed per-node metric
context, which diagnosis then consults (frozen step counters, idle
accelerators) independently of the workers' own reporting path.

TPU-shaped: there is no vendor metrics API to scrape — the external
source is the per-node tpu_timer daemons' Prometheus endpoints (the
native runtime every worker already carries, serving /metrics), so the
master needs no third-party metrics stack, and any other Prometheus
exporter (a cluster DCGM-style TPU exporter, node-exporter) works
through the same scraper. Two layers:

- :class:`JobMetricContext` — bounded, windowed history per
  (node, metric) with job-level aggregate queries. Pure data; the
  diagnosis masters read it.
- :class:`JobMetricMonitor` — the scrape loop over ``{node_id:
  "host:port"}`` endpoints, with per-node unreachable accounting (a
  node whose daemon stops answering is itself a diagnosis signal —
  the reference treats scrape failure the same way).

The out-of-band property is the point: these metrics come from the
NATIVE daemon thread, so a worker wedged inside libtpu/XLA (Python
frozen, heartbeats possibly still flowing from other threads) shows a
frozen ``tpu_timer_counter/steps`` here even though it answers nothing
else. ``steps_frozen`` is therefore hang corroboration that needs no
cooperation from the training loop.
"""

import collections
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from dlrover_tpu.common.log import logger

# Metric keys as the tpu_timer daemon exposes them
# (diagnosis/collectors.parse_prometheus_text flattening).
STEP_COUNTER = "tpu_timer_counter/steps"


class JobMetricContext:
    """Windowed per-(node, metric) samples + job-level queries."""

    def __init__(self, max_samples_per_series: int = 360):
        self._max = max_samples_per_series
        self._lock = threading.Lock()
        self._series: Dict[
            Tuple[int, str], "collections.deque[Tuple[float, float]]"
        ] = {}
        self._last_scrape: Dict[int, float] = {}
        self._unreachable: Dict[int, int] = collections.Counter()

    def record(
        self, node_id: int, metrics: Dict[str, float],
        ts: Optional[float] = None,
    ):
        ts = time.time() if ts is None else ts
        with self._lock:
            self._last_scrape[node_id] = ts
            self._unreachable.pop(node_id, None)
            for key, value in metrics.items():
                series = self._series.setdefault(
                    (node_id, key),
                    collections.deque(maxlen=self._max),
                )
                series.append((ts, float(value)))

    def record_unreachable(self, node_id: int):
        with self._lock:
            self._unreachable[node_id] += 1

    def unreachable_count(self, node_id: int) -> int:
        with self._lock:
            return self._unreachable.get(node_id, 0)

    def latest(self, node_id: int, key: str) -> Optional[float]:
        with self._lock:
            series = self._series.get((node_id, key))
            return series[-1][1] if series else None

    def window(
        self, node_id: int, key: str, span_s: float
    ) -> List[Tuple[float, float]]:
        """(ts, value) samples within the last ``span_s`` seconds."""
        cutoff = time.time() - span_s
        with self._lock:
            series = self._series.get((node_id, key)) or ()
            return [(ts, v) for ts, v in series if ts >= cutoff]

    def nodes(self) -> List[int]:
        with self._lock:
            return sorted(
                {n for n, _ in self._series} | set(self._unreachable)
            )

    def job_gauge_mean(self, key: str) -> Optional[float]:
        vals = [
            v for n in self.nodes()
            for v in [self.latest(n, key)] if v is not None
        ]
        return sum(vals) / len(vals) if vals else None

    def steps_frozen(
        self, span_s: float, min_samples: int = 2
    ) -> bool:
        """True when EVERY reporting node's native step counter is flat
        across the window — the out-of-band hang corroboration (one
        healthy node advancing means the job is not globally hung, it
        is waiting on a straggler; per-node attribution then comes from
        the per-node windows)."""
        nodes = self.nodes()
        if not nodes:
            return False
        saw_series = False
        for node in nodes:
            window = self.window(node, STEP_COUNTER, span_s)
            if len(window) < min_samples:
                continue
            saw_series = True
            values = [v for _, v in window]
            if max(values) > min(values):
                return False
        return saw_series

    def summary(self) -> Dict:
        """Dashboard/admin view: latest value per (node, metric)."""
        with self._lock:
            out: Dict[int, Dict[str, float]] = {}
            for (node, key), series in self._series.items():
                if series:
                    out.setdefault(node, {})[key] = series[-1][1]
            for node, count in self._unreachable.items():
                out.setdefault(node, {})["unreachable_scrapes"] = count
            return out


def _default_fetch(addr: str, timeout: float) -> str:
    import http.client

    host, port = addr.rsplit(":", 1)
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    try:
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        if resp.status != 200:
            raise OSError(f"GET /metrics -> {resp.status}")
        return resp.read().decode()
    finally:
        conn.close()


class JobMetricMonitor:
    """Scrape loop over the job's metric endpoints into a context.

    ``endpoints`` maps node_id -> "host:port" (static clusters) or is a
    zero-arg callable returning that mapping (elastic clusters: the
    master re-resolves live nodes each round). ``fetch`` is injectable
    for tests/alternative transports."""

    def __init__(
        self,
        endpoints,
        context: Optional[JobMetricContext] = None,
        interval_s: float = 15.0,
        timeout_s: float = 5.0,
        fetch: Callable[[str, float], str] = _default_fetch,
    ):
        self._endpoints = (
            endpoints if callable(endpoints) else (lambda: endpoints)
        )
        self.context = context or JobMetricContext()
        self._interval_s = interval_s
        self._timeout_s = timeout_s
        self._fetch = fetch
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def scrape_once(self) -> int:
        """One scrape round; returns how many nodes answered."""
        from dlrover_tpu.diagnosis.collectors import (
            parse_prometheus_text,
        )

        ok = 0
        for node_id, addr in dict(self._endpoints()).items():
            try:
                text = self._fetch(addr, self._timeout_s)
                self.context.record(
                    node_id, parse_prometheus_text(text)
                )
                ok += 1
            except OSError as e:
                self.context.record_unreachable(node_id)
                logger.debug(
                    "metric scrape %s (%s) failed: %s", node_id, addr, e
                )
        return ok

    def start(self):
        if self._thread is not None:
            return
        self._stopped.clear()
        self._thread = threading.Thread(
            target=self._loop, name="job-metric-monitor", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stopped.set()
        if self._thread is not None:
            self._thread.join(timeout=self._timeout_s + 1.0)
            self._thread = None

    def _loop(self):
        while not self._stopped.wait(self._interval_s):
            try:
                self.scrape_once()
            except Exception:  # noqa: BLE001 - monitor must survive
                logger.exception("metric scrape round failed")
