"""Master-wide configuration singleton.

Parity: reference dlrover/python/common/global_context.py:89 (Context).
Values are defaults overridable from CLI args or env.
"""

import os
import threading
from typing import Optional


class Context:
    _instance: Optional["Context"] = None
    _lock = threading.Lock()

    def __init__(self):
        # master loop / supervision
        self.master_port: int = 0
        self.job_name: str = "dlrover-tpu-job"
        self.master_run_interval: int = 5
        self.seconds_to_wait_failed_node: int = 120
        self.hb_timeout_secs: int = 600
        self.relaunch_always: bool = False
        self.max_relaunch_count: int = 3
        # rendezvous
        self.rdzv_join_timeout: int = 600
        self.rdzv_pend_timeout: int = 600
        self.min_nodes: int = 1
        self.max_nodes: int = 1
        self.node_unit: int = 1
        # network check
        self.network_check_enabled: bool = False
        self.straggler_ratio: float = 2.0
        # pre-check
        self.pre_check_enabled: bool = True
        self.pre_check_ops: list = []
        # diagnosis
        self.hang_detect_enabled: bool = True
        self.hang_downtime_secs: int = 1800
        # data sharding
        self.task_process_timeout: int = 1800
        # auto scaling
        self.auto_scaling_enabled: bool = False
        # reporting
        self.dashboard_enabled: bool = False

    @classmethod
    def singleton_instance(cls) -> "Context":
        if cls._instance is None:
            with cls._lock:
                if cls._instance is None:
                    cls._instance = cls()
        return cls._instance

    def from_env(self):
        self.hang_downtime_secs = int(
            os.getenv("DLROVER_TPU_HANG_DOWNTIME", self.hang_downtime_secs)
        )
        self.network_check_enabled = os.getenv(
            "DLROVER_TPU_NETWORK_CHECK", ""
        ).lower() in ("1", "true")
        return self


def get_context() -> Context:
    return Context.singleton_instance()
