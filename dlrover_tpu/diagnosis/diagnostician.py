"""Diagnostician pattern: observe a problem, resolve it to an action.

Parity: reference dlrover/python/diagnosis/common/diagnostician.py:95
(Diagnostician.observe/resolve/diagnose) — each diagnostician watches one
failure mode; the DiagnosisManager runs them periodically and feeds the
resulting actions into the JobContext queues.
"""

import abc
from dataclasses import dataclass, field
from typing import Dict

from dlrover_tpu.common.log import logger
from dlrover_tpu.diagnosis.actions import DiagnosisAction, NoAction


@dataclass
class Observation:
    """What a diagnostician saw; empty observation == healthy."""

    observation: str = ""
    extra: Dict[str, str] = field(default_factory=dict)

    def has_problem(self) -> bool:
        return bool(self.observation)


class Diagnostician(abc.ABC):
    """One failure mode: observe() detects it, resolve() picks the cure."""

    # How often the manager should run observe(), in seconds.
    observe_interval_s: float = 30.0

    @property
    def name(self) -> str:
        return type(self).__name__

    @abc.abstractmethod
    def observe(self, **kwargs) -> Observation:
        ...

    @abc.abstractmethod
    def resolve(self, observation: Observation, **kwargs) -> DiagnosisAction:
        ...

    def diagnose(self, **kwargs) -> DiagnosisAction:
        try:
            ob = self.observe(**kwargs)
            if not ob.has_problem():
                return NoAction()
            logger.warning("%s observed: %s", self.name, ob.observation)
            return self.resolve(ob, **kwargs)
        except Exception:
            logger.exception("diagnostician %s crashed", self.name)
            return NoAction()
