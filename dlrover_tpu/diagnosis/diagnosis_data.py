"""Diagnosis data reported from agents to the master.

Parity: reference dlrover/python/diagnosis/common/diagnosis_data.py
(DiagnosisData base, WorkerTrainingMetric, TrainingLog). Carried inside
``comm.DiagnosisDataReport`` and stored per-node by the DiagnosisMaster.
"""

import time
from dataclasses import dataclass, field
from typing import Dict, List

from dlrover_tpu.common.serialize import PickleSerializable


class DiagnosisDataType:
    TRAINING_LOG = "training_log"
    TRAINING_METRIC = "training_metric"
    RESOURCE = "resource"
    XPU_TIMER_METRIC = "xpu_timer_metric"
    FLIGHT_RECORDER = "flight_recorder"
    # All-thread sys._current_frames() captures from the worker-side
    # hang watchdog / SIGUSR1 on-demand dump (observability §29).
    STACK_DUMP = "stack_dump"
    # Finished distributed-trace spans pushed by workers, routed to the
    # master's TraceAggregator behind /api/traces.
    TRACE_SPANS = "trace_spans"


@dataclass
class DiagnosisData(PickleSerializable):
    data_type: str = ""
    node_id: int = -1
    node_rank: int = -1
    timestamp: float = field(default_factory=time.time)


@dataclass
class TrainingLog(DiagnosisData):
    """Tail of the worker log, pre-filtered to error-ish lines."""

    data_type: str = DiagnosisDataType.TRAINING_LOG
    logs: List[str] = field(default_factory=list)


@dataclass
class WorkerTrainingMetric(DiagnosisData):
    """Step progress as seen by one worker."""

    data_type: str = DiagnosisDataType.TRAINING_METRIC
    global_step: int = 0
    step_time_s: float = 0.0
    throughput: float = 0.0


@dataclass
class NodeResourceData(DiagnosisData):
    data_type: str = DiagnosisDataType.RESOURCE
    cpu_percent: float = 0.0
    memory_mb: float = 0.0
    tpu_duty_cycle: float = 0.0


@dataclass
class XpuTimerMetric(DiagnosisData):
    """Scraped gauges from the native profiler daemon (tpu_timer)."""

    data_type: str = DiagnosisDataType.XPU_TIMER_METRIC
    metrics: Dict[str, float] = field(default_factory=dict)


@dataclass
class FlightRecord(DiagnosisData):
    """The last N step records of a dead worker, fetched by the agent
    from the flight recorder's crash dump."""

    data_type: str = DiagnosisDataType.FLIGHT_RECORDER
    local_rank: int = -1
    steps: List[Dict] = field(default_factory=list)


@dataclass
class StackDump(DiagnosisData):
    """All-thread sys._current_frames() capture from a worker's hang
    watchdog / SIGUSR1 dump, relayed by the agent — the evidence the
    hang diagnostician folds into its escalation."""

    data_type: str = DiagnosisDataType.STACK_DUMP
    reason: str = ""
    meta: Dict = field(default_factory=dict)
    stacks: Dict[str, List[str]] = field(default_factory=dict)
    hang_for_s: float = 0.0


@dataclass
class TraceSpans(DiagnosisData):
    """A batch of finished distributed-trace spans pushed by a worker
    (the /api/traces feed; the servicer ALSO routes these straight to
    its TraceAggregator — this record keeps the generic per-node
    diagnosis ring consistent)."""

    data_type: str = DiagnosisDataType.TRACE_SPANS
    spans: List[Dict] = field(default_factory=list)


def build_diagnosis_data(data_type, node_id, payload, timestamp=0.0):
    """Reconstruct a DiagnosisData from the generic RPC report
    (comm.DiagnosisDataReport: data_type + free-form payload dict)."""
    classes = {
        DiagnosisDataType.TRAINING_LOG: TrainingLog,
        DiagnosisDataType.TRAINING_METRIC: WorkerTrainingMetric,
        DiagnosisDataType.RESOURCE: NodeResourceData,
        DiagnosisDataType.XPU_TIMER_METRIC: XpuTimerMetric,
        DiagnosisDataType.FLIGHT_RECORDER: FlightRecord,
        DiagnosisDataType.STACK_DUMP: StackDump,
        DiagnosisDataType.TRACE_SPANS: TraceSpans,
    }
    cls = classes.get(data_type)
    if cls is None:
        return None
    fields = set(cls.__dataclass_fields__) - {
        "node_id",
        "data_type",
        "timestamp",
    }
    kwargs = {k: v for k, v in (payload or {}).items() if k in fields}
    data = cls(node_id=node_id, **kwargs)
    if timestamp:
        data.timestamp = timestamp
    return data
