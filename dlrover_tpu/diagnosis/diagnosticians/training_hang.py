"""Training-hang diagnostician.

Parity: reference dlrover/python/diagnosis/diagnostician/training_hang.py
:61-339 (TrainingHangDiagnostician) — detects a hung job from global-step
stagnation while all nodes still heartbeat (the XLA-collective-deadlock
signature: processes alive, no step progress), escalating from an
observability event to a job-level restart.

TPU note: without per-kernel NCCL introspection the primary hang signal
is step stagnation from the PerfMonitor plus (when the native profiler is
running) a frozen executable-launch counter from tpu_timer metrics.
"""

import time

from dlrover_tpu.diagnosis.actions import (
    DiagnosisAction,
    EventAction,
    JobRestartAction,
)
from dlrover_tpu.diagnosis.diagnostician import Diagnostician, Observation

_HANG_OBSERVATION = "training-hang"


class TrainingHangDiagnostician(Diagnostician):
    observe_interval_s = 30.0

    def __init__(
        self,
        perf_monitor,
        job_manager=None,
        hang_timeout_s: float = 600.0,
        restart_after_s: float = 1800.0,
        metric_context=None,
        clock=time.time,
        stack_dump_provider=None,
    ):
        self._perf_monitor = perf_monitor
        self._job_manager = job_manager
        self._hang_timeout_s = hang_timeout_s
        self._restart_after_s = restart_after_s
        self._hang_since = 0.0
        # Callable returning recent worker stack dumps (the
        # hang_watchdog's sys._current_frames() captures, reported as
        # "stack_dump" diagnosis data): lets the escalation name the
        # blocked frame instead of just "no step progress".
        self._stack_dump_provider = stack_dump_provider
        # Injectable clock: escalation thresholds are minutes-scale in
        # production, and the tests must drive stagnation -> EventAction
        # -> JobRestartAction without real sleeps.
        self._clock = clock
        # Optional out-of-band corroboration (common/metric.py): the
        # native daemons' step counters come from a C++ thread, so a
        # worker wedged inside libtpu still reports — a frozen counter
        # there is independent evidence the in-band RPC path can't give
        # (and an advancing one vetoes a false hang from lost reports).
        self._metric_context = metric_context

    def observe(self, **kwargs) -> Observation:
        started = self._perf_monitor.global_step > 0
        stagnated = started and self._perf_monitor.step_stagnated(
            self._hang_timeout_s
        )
        if started and self._metric_context is not None:
            from dlrover_tpu.common.metric import STEP_COUNTER

            def advancing(node):
                window = self._metric_context.window(
                    node, STEP_COUNTER, self._hang_timeout_s
                )
                values = [v for _, v in window]
                return len(values) >= 2 and max(values) > min(values)

            oob_frozen = self._metric_context.steps_frozen(
                self._hang_timeout_s
            )
            if stagnated and not oob_frozen and any(
                advancing(n) for n in self._metric_context.nodes()
            ):
                # In-band reports stalled but a native counter is
                # demonstrably ADVANCING: the reporting path is the
                # problem, not the training. (Mere sample existence is
                # not evidence — daemons that answered once then died
                # must not veto a real hang.)
                stagnated = False
            elif not stagnated and oob_frozen:
                stagnated = True
        nodes_alive = True
        if self._job_manager is not None and hasattr(
            self._job_manager, "all_running_node_hanged"
        ):
            # If nodes stopped heartbeating this is a failure, not a hang;
            # the heartbeat monitor handles it.
            nodes_alive = not self._job_manager.all_running_node_hanged()
        if stagnated and nodes_alive:
            if self._hang_since == 0.0:
                self._hang_since = self._clock()
            return Observation(
                observation=_HANG_OBSERVATION,
                extra={
                    "step": str(self._perf_monitor.global_step),
                    "hang_for_s": f"{self._clock() - self._hang_since:.0f}",
                },
            )
        self._hang_since = 0.0
        return Observation()

    def _stack_evidence(self) -> str:
        """Blocked-frame summary from worker stack dumps, '' when none:
        "rank 3 blocked in psum_wait (foo.py:42)". The provider is the
        worker-side hang watchdog's capture, relayed over the diagnosis
        verb — evidence, not a trigger, so failures stay silent."""
        if self._stack_dump_provider is None:
            return ""
        try:
            dumps = self._stack_dump_provider() or []
        except Exception:  # noqa: BLE001 — evidence is best-effort
            return ""
        parts = []
        for dump in dumps[:4]:
            if not isinstance(dump, dict):
                continue
            meta = dump.get("meta", {})
            rank = meta.get("node_rank", dump.get("node_rank", "?"))
            stacks = dump.get("stacks", {})
            # The innermost frame of the main thread (or any thread
            # when unnamed) is where the worker actually sits.
            frames = (
                stacks.get(next(
                    (k for k in stacks if k.startswith("MainThread")),
                    "",
                )) or next(iter(stacks.values()), [])
            )
            if frames:
                top = frames[-1]
                parts.append(f"rank {rank} blocked in {top}")
        return "; ".join(parts)

    def resolve(self, ob: Observation, **kwargs) -> DiagnosisAction:
        hang_for = self._clock() - self._hang_since
        evidence = self._stack_evidence()
        suffix = f" ({evidence})" if evidence else ""
        if hang_for >= self._restart_after_s:
            self._hang_since = 0.0
            return JobRestartAction(
                reason=(
                    f"no step progress for {hang_for:.0f}s at step "
                    f"{ob.extra.get('step')}{suffix}"
                )
            )
        return EventAction(
            event_type="warning",
            event_msg=(
                f"training hang suspected: step {ob.extra.get('step')} "
                f"stalled for {ob.extra.get('hang_for_s')}s{suffix}"
            ),
            reason=_HANG_OBSERVATION,
        )
