"""Training-hang diagnostician.

Parity: reference dlrover/python/diagnosis/diagnostician/training_hang.py
:61-339 (TrainingHangDiagnostician) — detects a hung job from global-step
stagnation while all nodes still heartbeat (the XLA-collective-deadlock
signature: processes alive, no step progress), escalating from an
observability event to a job-level restart.

TPU note: without per-kernel NCCL introspection the primary hang signal
is step stagnation from the PerfMonitor plus (when the native profiler is
running) a frozen executable-launch counter from tpu_timer metrics.
"""

import time

from dlrover_tpu.diagnosis.actions import (
    DiagnosisAction,
    EventAction,
    JobRestartAction,
)
from dlrover_tpu.diagnosis.diagnostician import Diagnostician, Observation

_HANG_OBSERVATION = "training-hang"


class TrainingHangDiagnostician(Diagnostician):
    observe_interval_s = 30.0

    def __init__(
        self,
        perf_monitor,
        job_manager=None,
        hang_timeout_s: float = 600.0,
        restart_after_s: float = 1800.0,
    ):
        self._perf_monitor = perf_monitor
        self._job_manager = job_manager
        self._hang_timeout_s = hang_timeout_s
        self._restart_after_s = restart_after_s
        self._hang_since = 0.0

    def observe(self, **kwargs) -> Observation:
        started = self._perf_monitor.global_step > 0
        stagnated = started and self._perf_monitor.step_stagnated(
            self._hang_timeout_s
        )
        nodes_alive = True
        if self._job_manager is not None and hasattr(
            self._job_manager, "all_running_node_hanged"
        ):
            # If nodes stopped heartbeating this is a failure, not a hang;
            # the heartbeat monitor handles it.
            nodes_alive = not self._job_manager.all_running_node_hanged()
        if stagnated and nodes_alive:
            if self._hang_since == 0.0:
                self._hang_since = time.time()
            return Observation(
                observation=_HANG_OBSERVATION,
                extra={
                    "step": str(self._perf_monitor.global_step),
                    "hang_for_s": f"{time.time() - self._hang_since:.0f}",
                },
            )
        self._hang_since = 0.0
        return Observation()

    def resolve(self, ob: Observation, **kwargs) -> DiagnosisAction:
        hang_for = time.time() - self._hang_since
        if hang_for >= self._restart_after_s:
            self._hang_since = 0.0
            return JobRestartAction(
                reason=(
                    f"no step progress for {hang_for:.0f}s at step "
                    f"{ob.extra.get('step')}"
                )
            )
        return EventAction(
            event_type="warning",
            event_msg=(
                f"training hang suspected: step {ob.extra.get('step')} "
                f"stalled for {ob.extra.get('hang_for_s')}s"
            ),
            reason=_HANG_OBSERVATION,
        )
