"""Node-failure diagnosticians.

Parity: reference dlrover/python/diagnosis/diagnostician/node_failure.py:79
(repeated failures -> abort) and node_inconsistency.py:105 (nodes whose
reported state disagrees with the master record).
"""

from dlrover_tpu.common.constants import NodeStatus
from dlrover_tpu.diagnosis.actions import (
    DiagnosisAction,
    EventAction,
    JobAbortionAction,
)
from dlrover_tpu.diagnosis.diagnostician import Diagnostician, Observation
from dlrover_tpu.master.node.job_context import get_job_context


class NodeFailureDiagnostician(Diagnostician):
    """Aborts the job when the cluster keeps killing whatever we launch —
    the failure budget is global, not per-node."""

    observe_interval_s = 30.0

    def __init__(self, max_total_failures: int = 20):
        self._max_total_failures = max_total_failures

    def observe(self, **kwargs) -> Observation:
        count = get_job_context().failure_count
        if count >= self._max_total_failures:
            return Observation(
                observation="excessive-node-failures",
                extra={"failures": str(count)},
            )
        return Observation()

    def resolve(self, ob: Observation, **kwargs) -> DiagnosisAction:
        return JobAbortionAction(
            reason=(
                f"{ob.extra.get('failures')} node failures exceed the "
                f"budget of {self._max_total_failures}"
            )
        )


class NodeInconsistencyDiagnostician(Diagnostician):
    """Flags nodes the master believes RUNNING that reported SUCCEEDED
    (reference node_inconsistency.py): usually a missed watch event."""

    observe_interval_s = 60.0

    def observe(self, **kwargs) -> Observation:
        stale = []
        for node in get_job_context().get_nodes().values():
            if (
                node.status == NodeStatus.RUNNING
                and node.reported_status == NodeStatus.SUCCEEDED
            ):
                stale.append(node.name)
        if stale:
            return Observation(
                observation="node-state-inconsistency",
                extra={"nodes": ",".join(stale)},
            )
        return Observation()

    def resolve(self, ob: Observation, **kwargs) -> DiagnosisAction:
        return EventAction(
            event_type="warning",
            event_msg=f"inconsistent node states: {ob.extra.get('nodes')}",
            reason=ob.observation,
        )
