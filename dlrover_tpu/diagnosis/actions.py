"""Diagnosis action hierarchy.

Parity: reference dlrover/python/diagnosis/common/diagnosis_action.py
(NoAction/EventAction/NodeAction/JobRestartAction/JobAbortionAction).
Actions are produced by diagnosticians on the master and piggy-backed on
heartbeat responses for the agent to execute (reference
servicer.py:_report_heartbeat, elastic_agent training.py:1489).
"""

import time
from dataclasses import dataclass, field
from typing import Dict

from dlrover_tpu.common.constants import (
    DiagnosisActionType,
    DiagnosisConstant,
)
from dlrover_tpu.common.serialize import PickleSerializable


@dataclass
class DiagnosisAction(PickleSerializable):
    action_type: str = DiagnosisActionType.NONE
    instance: int = DiagnosisConstant.MASTER_INSTANCE
    reason: str = ""
    timestamp: float = field(default_factory=time.time)
    expired_secs: float = DiagnosisConstant.ACTION_EXPIRED_SECS

    def is_expired(self) -> bool:
        return time.time() - self.timestamp > self.expired_secs

    def is_needed(self) -> bool:
        return (
            self.action_type != DiagnosisActionType.NONE
            and not self.is_expired()
        )


@dataclass
class NoAction(DiagnosisAction):
    action_type: str = DiagnosisActionType.NONE


@dataclass
class EventAction(DiagnosisAction):
    """Surface an observability event (no behavior change)."""

    action_type: str = DiagnosisActionType.EVENT
    event_type: str = "info"
    event_msg: str = ""
    labels: Dict[str, str] = field(default_factory=dict)


@dataclass
class NodeAction(DiagnosisAction):
    """Restart worker processes in place, or relaunch the node."""

    action_type: str = DiagnosisActionType.RESTART_WORKER
    node_id: int = -1
    node_status: str = ""


@dataclass
class JobRestartAction(DiagnosisAction):
    action_type: str = DiagnosisActionType.JOB_RESTART


@dataclass
class JobAbortionAction(DiagnosisAction):
    action_type: str = DiagnosisActionType.JOB_ABORT
