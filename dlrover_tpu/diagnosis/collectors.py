"""Agent-side diagnosis data collectors.

Parity: reference dlrover/python/diagnosis/datacollector/
xpu_timer_metric_collector.py:28-75 (Prometheus scrape -> master) and
training_log_collector.py. The tpu_timer collector scrapes the native
daemon's /metrics endpoint and forwards the parsed gauges to the master's
DiagnosisMaster, where the hang diagnostician can see a frozen step
counter even if the Python worker is wedged.
"""

import http.client
import re
import threading
from typing import Dict, Optional

from dlrover_tpu.common.log import logger
from dlrover_tpu.diagnosis.diagnosis_data import DiagnosisDataType

# The labels group admits '}' INSIDE quoted values (kernel names are
# arbitrary strings): any run of non-quote/non-brace chars or a full
# quoted string, repeated.
_METRIC_LINE = re.compile(
    r'^(?P<metric>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>(?:[^"{}]|"(?:[^"\\]|\\.)*")*)\})?'
    r'\s+(?P<value>[-+0-9.eE]+|NaN|[+-]?Inf)\s*$'
)
_LABEL_PAIR = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_prometheus_text(text: str) -> Dict[str, float]:
    """Flatten Prometheus exposition into {metric[/labels]: value}.

    A bare metric keeps its name; the single-label ``{name="X"}``
    convention every in-repo exporter uses (tpu_timer daemon, the
    master's /metrics) flattens to ``metric/X`` — unchanged from the
    original parser; any other label set flattens to
    ``metric/k1=v1,k2=v2`` in exposition order (histogram ``le``
    buckets and multi-label families survive the round trip).
    """
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _METRIC_LINE.match(line)
        if not m:
            continue
        key = m.group("metric")
        raw_labels = m.group("labels")
        if raw_labels:
            pairs = [
                (k, v.replace('\\"', '"').replace("\\\\", "\\"))
                for k, v in _LABEL_PAIR.findall(raw_labels)
            ]
            if len(pairs) == 1 and pairs[0][0] == "name":
                key = f"{key}/{pairs[0][1]}"
            elif pairs:
                flat = ",".join(f"{k}={v}" for k, v in pairs)
                key = f"{key}/{flat}"
        try:
            out[key] = float(m.group("value"))
        except ValueError:
            continue
    return out


class TpuTimerMetricCollector:
    """Scrapes the local tpu_timer daemon and reports to the master."""

    def __init__(
        self,
        master_client=None,
        node_id: int = 0,
        port: int = 0,
        port_file: str = "",
        interval_s: float = 30.0,
    ):
        """``port_file``, when given, is re-read before each scrape: the
        worker publishes its actually-bound daemon port there (the fixed
        base port can be taken by a stale process)."""
        self._client = master_client
        self._node_id = node_id
        self.port = port
        self._port_file = port_file
        self._interval_s = interval_s
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _resolve_port(self) -> int:
        if self._port_file:
            try:
                with open(self._port_file) as f:
                    return int(f.read().strip())
            except (OSError, ValueError):
                pass
        return self.port

    def scrape(self) -> Optional[Dict[str, float]]:
        port = self._resolve_port()
        if not port:
            return None
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
            conn.request("GET", "/metrics")
            resp = conn.getresponse()
            text = resp.read().decode() if resp.status == 200 else ""
            conn.close()
        except Exception:
            # Daemon restarting / truncated response: skip this round,
            # never kill the collector thread.
            return None
        if not text:
            return None
        return parse_prometheus_text(text)

    def collect_once(self) -> bool:
        metrics = self.scrape()
        if not metrics or self._client is None:
            return False
        try:
            self._client.report_diagnosis_data(
                DiagnosisDataType.XPU_TIMER_METRIC,
                {"metrics": metrics, "node_rank": self._node_id},
            )
            return True
        except Exception:
            logger.warning("tpu_timer metric report failed", exc_info=True)
            return False

    def start(self):
        self._stopped.clear()
        self._thread = threading.Thread(
            target=self._loop, name="tpu-timer-collector", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stopped.set()

    def _loop(self):
        while not self._stopped.wait(self._interval_s):
            try:
                self.collect_once()
            except Exception:
                logger.warning("metric collection failed", exc_info=True)
