"""Periodic diagnostician scheduler.

Parity: reference dlrover/python/diagnosis/common/diagnosis_manager.py:226
— registers diagnosticians, runs each at its own cadence on one thread,
and enqueues non-trivial actions into the JobContext for the master
diagnose loop / agent heartbeats to consume.
"""

import threading
import time
from typing import Dict, List

from dlrover_tpu.common.log import logger
from dlrover_tpu.diagnosis.diagnostician import Diagnostician
from dlrover_tpu.master.node.job_context import get_job_context


class DiagnosisManager:
    def __init__(self, tick_s: float = 1.0):
        self._diagnosticians: List[Diagnostician] = []
        self._next_run: Dict[str, float] = {}
        self._tick_s = tick_s
        self._stopped = threading.Event()
        self._thread = None

    def register(self, diagnostician: Diagnostician):
        self._diagnosticians.append(diagnostician)
        self._next_run[diagnostician.name] = 0.0

    def start(self):
        self._stopped.clear()
        self._thread = threading.Thread(
            target=self._run, name="diagnosis-manager", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stopped.set()

    def diagnose_once(self):
        """Run every diagnostician immediately (testing / pre-stop sweep)."""
        for d in self._diagnosticians:
            self._dispatch(d)

    def _run(self):
        while not self._stopped.is_set():
            time.sleep(self._tick_s)
            now = time.time()
            for d in self._diagnosticians:
                if now >= self._next_run[d.name]:
                    self._next_run[d.name] = now + d.observe_interval_s
                    self._dispatch(d)

    def _dispatch(self, diagnostician: Diagnostician):
        action = diagnostician.diagnose()
        if action.is_needed():
            logger.info(
                "diagnosis action from %s: %s (%s)",
                diagnostician.name,
                action.action_type,
                action.reason,
            )
            get_job_context().enqueue_action(action)
