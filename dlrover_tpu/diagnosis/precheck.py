"""Pre-check operators: gate training start on cluster health.

Parity: reference dlrover/python/master/diagnosis/precheck_operator.py
(PreCheckOperator base :91, SchedulingPreCheckOperator,
ConnectionPreCheckOperator :352). The DiagnosisMaster runs each operator
with retries before the servicer reports PASS to waiting agents
(reference trainer elastic_run.py:295 wait_pre_check).
"""

import abc
import time
from dataclasses import dataclass, field
from typing import List

from dlrover_tpu.common.constants import NodeStatus
from dlrover_tpu.common.log import logger
from dlrover_tpu.master.node.job_context import get_job_context


@dataclass
class PreCheckResult:
    passed: bool = True
    reason: str = ""
    abnormal_nodes: List[int] = field(default_factory=list)


class PreCheckOperator(abc.ABC):
    """One pre-flight condition; retried until timeout."""

    retry_interval_s: float = 5.0
    timeout_s: float = 300.0

    @property
    def name(self) -> str:
        return type(self).__name__

    @abc.abstractmethod
    def check(self) -> PreCheckResult:
        ...

    def run_with_retries(self) -> PreCheckResult:
        deadline = time.time() + self.timeout_s
        result = self.check()
        while not result.passed and time.time() < deadline:
            logger.info(
                "pre-check %s not passing yet: %s", self.name, result.reason
            )
            time.sleep(self.retry_interval_s)
            result = self.check()
        return result


class SchedulingPreCheckOperator(PreCheckOperator):
    """All requested nodes left Pending (reference
    precheck_operator.py SchedulingPreCheckOperator): a cluster that can't
    schedule the job should fail fast, before agents wait on rendezvous."""

    def __init__(self, job_manager, timeout_s: float = 300.0):
        self._job_manager = job_manager
        self.timeout_s = timeout_s

    def check(self) -> PreCheckResult:
        pending = self._job_manager.worker_manager.pending_nodes()
        if pending:
            return PreCheckResult(
                passed=False,
                reason=f"{len(pending)} workers still pending",
                abnormal_nodes=[n.id for n in pending],
            )
        return PreCheckResult(passed=True)


class ConnectionPreCheckOperator(PreCheckOperator):
    """All scheduled nodes made at least one RPC to the master within the
    window (reference ConnectionPreCheckOperator :352). Any RPC counts —
    agents poll wait_pre_check before their first heartbeat, so requiring
    heartbeats here would deadlock the gate against the agents it gates.

    ``contact_provider`` returns {node_id: last_contact_wall_time}; wired
    to MasterServicer.node_last_contact.
    """

    def __init__(
        self,
        contact_provider,
        timeout_s: float = 300.0,
        window_s: float = 120.0,
    ):
        self._contact_provider = contact_provider
        self.timeout_s = timeout_s
        self._window_s = window_s

    def check(self) -> PreCheckResult:
        contacts = self._contact_provider() or {}
        silent = []
        now = time.time()
        for node in get_job_context().get_nodes().values():
            if node.status != NodeStatus.RUNNING:
                continue
            # Agents self-report node_id == their rank (run CLI), which
            # survives relaunches; master-internal record ids do not.
            last = contacts.get(node.rank_index, node.heartbeat_time)
            if last <= 0 or (now - last > self._window_s):
                silent.append(node.id)
        if silent:
            return PreCheckResult(
                passed=False,
                reason=f"nodes {silent} have not connected to the master",
                abnormal_nodes=silent,
            )
        return PreCheckResult(passed=True)
