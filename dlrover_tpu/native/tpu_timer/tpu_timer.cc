// tpu_timer implementation. See tpu_timer.h for the design note.

#include "tpu_timer.h"

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cmath>
#include <condition_variable>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr int kNameCap = 64;
constexpr int kRingCap = 1 << 16;  // ~4.7MB trace ring
constexpr int kMaxInflight = 1024;

int64_t NowNs() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return int64_t(ts.tv_sec) * 1000000000LL + ts.tv_nsec;
}

struct Event {
  // Seqlock: odd while a writer is mid-update; readers retry/skip.
  std::atomic<uint64_t> seq{0};
  char name[kNameCap];
  int64_t start_ns;
  int64_t dur_ns;
  double flops;
  int32_t kind;
  int32_t tid;
};

// Span names come from Python and end up inside JSON strings and
// Prometheus label values: restrict to a safe charset at record time.
void SanitizeName(char* dst, const char* src) {
  int i = 0;
  for (; src && src[i] && i < kNameCap - 1; i++) {
    char c = src[i];
    bool ok = (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
              (c >= '0' && c <= '9') || c == '_' || c == '-' || c == '.' ||
              c == '/' || c == ':' || c == ' ';
    dst[i] = ok ? c : '_';
  }
  dst[i] = 0;
}

// Latency histogram with exponential buckets: 1us..~137s (2^0..2^27 us).
struct Histogram {
  static constexpr int kBuckets = 28;
  uint64_t counts[kBuckets] = {0};
  uint64_t total = 0;
  double sum_us = 0;
  double flops_sum = 0;

  void Add(double us, double flops) {
    int b = 0;
    double v = us;
    while (v >= 1.0 && b < kBuckets - 1) {
      v /= 2.0;
      b++;
    }
    counts[b]++;
    total++;
    sum_us += us;
    flops_sum += flops;
  }

  double Quantile(double q) const {
    if (total == 0) return 0;
    uint64_t target = uint64_t(q * double(total));
    uint64_t seen = 0;
    for (int b = 0; b < kBuckets; b++) {
      seen += counts[b];
      if (seen > target) return std::pow(2.0, b);  // bucket upper bound, us
    }
    return std::pow(2.0, kBuckets - 1);
  }
};

struct Inflight {
  std::atomic<int64_t> start_ns{0};  // 0 = free slot
  char name[kNameCap];
  int32_t kind;
  int32_t tid;
};

class Manager {
 public:
  static Manager& Get() {
    static Manager* m = new Manager();
    return *m;
  }

  void Init(int64_t hang_timeout_ms) {
    std::lock_guard<std::mutex> g(mu_);
    hang_timeout_ns_ = hang_timeout_ms * 1000000LL;
    if (!watchdog_running_) {
      watchdog_running_ = true;
      watchdog_ = std::thread([this] { WatchdogLoop(); });
      watchdog_.detach();
    }
  }

  int64_t Begin(const char* name, int kind) {
    for (int i = 0; i < kMaxInflight; i++) {
      int64_t expected = 0;
      if (inflight_[i].start_ns.compare_exchange_strong(
              expected, NowNs(), std::memory_order_acq_rel)) {
        SanitizeName(inflight_[i].name, name ? name : "?");
        inflight_[i].kind = kind;
        inflight_[i].tid = int32_t(::gettid());
        return i;
      }
    }
    return -1;  // saturated: drop (never block the hot path)
  }

  void End(int64_t id, double flops) {
    if (id < 0 || id >= kMaxInflight) return;
    int64_t start = inflight_[id].start_ns.load(std::memory_order_acquire);
    if (start == 0) return;
    int64_t dur = NowNs() - start;
    Record(inflight_[id].name, inflight_[id].kind, start, dur, flops,
           inflight_[id].tid);
    inflight_[id].start_ns.store(0, std::memory_order_release);
  }

  void Record(const char* name, int kind, int64_t start_ns, int64_t dur_ns,
              double flops, int32_t tid) {
    uint64_t slot = ring_head_.fetch_add(1, std::memory_order_relaxed);
    Event& e = ring_[slot % kRingCap];
    e.seq.fetch_add(1, std::memory_order_acq_rel);  // odd: write in flight
    SanitizeName(e.name, name ? name : "?");
    e.start_ns = start_ns;
    e.dur_ns = dur_ns;
    e.flops = flops;
    e.kind = kind;
    e.tid = tid ? tid : int32_t(::gettid());
    e.seq.fetch_add(1, std::memory_order_acq_rel);  // even: committed
    {
      std::lock_guard<std::mutex> g(mu_);
      hist_[std::string(e.name)].Add(double(dur_ns) / 1000.0, flops);
    }
  }

  void SetGauge(const char* name, double value) {
    std::lock_guard<std::mutex> g(mu_);
    gauges_[name] = value;
  }

  void CounterAdd(const char* name, double delta) {
    std::lock_guard<std::mutex> g(mu_);
    counters_[name] += delta;
  }

  int HangCount() {
    if (hang_timeout_ns_ <= 0) return 0;
    int64_t now = NowNs();
    int hung = 0;
    for (int i = 0; i < kMaxInflight; i++) {
      int64_t start = inflight_[i].start_ns.load(std::memory_order_acquire);
      if (start != 0 && now - start > hang_timeout_ns_) hung++;
    }
    return hung;
  }

  std::string MetricsText() {
    std::lock_guard<std::mutex> g(mu_);
    std::string out;
    out.reserve(4096);
    char line[512];
    for (auto& kv : hist_) {
      const std::string& n = kv.first;
      const Histogram& h = kv.second;
      double avg = h.total ? h.sum_us / double(h.total) : 0;
      snprintf(line, sizeof(line),
               "tpu_timer_span_count{name=\"%s\"} %llu\n"
               "tpu_timer_span_avg_us{name=\"%s\"} %.3f\n"
               "tpu_timer_span_p99_us{name=\"%s\"} %.1f\n",
               n.c_str(), (unsigned long long)h.total, n.c_str(), avg,
               n.c_str(), h.Quantile(0.99));
      out += line;
      if (h.flops_sum > 0 && h.sum_us > 0) {
        // TFLOPS = flops / seconds / 1e12
        double tflops = h.flops_sum / (h.sum_us / 1e6) / 1e12;
        snprintf(line, sizeof(line),
                 "tpu_timer_tflops{name=\"%s\"} %.3f\n", n.c_str(), tflops);
        out += line;
      }
    }
    for (auto& kv : gauges_) {
      snprintf(line, sizeof(line), "tpu_timer_gauge{name=\"%s\"} %.6f\n",
               kv.first.c_str(), kv.second);
      out += line;
    }
    for (auto& kv : counters_) {
      snprintf(line, sizeof(line), "tpu_timer_counter{name=\"%s\"} %.6f\n",
               kv.first.c_str(), kv.second);
      out += line;
    }
    char hang[96];
    // HangCount takes no lock, safe under mu_.
    snprintf(hang, sizeof(hang), "tpu_timer_hang_spans %d\n", HangCount());
    out += hang;
    return out;
  }

  int DumpTimeline(const char* path) {
    FILE* f = fopen(path, "w");
    if (!f) return -1;
    fputs("{\"traceEvents\":[", f);
    uint64_t head = ring_head_.load(std::memory_order_relaxed);
    uint64_t count = head < kRingCap ? head : kRingCap;
    uint64_t start = head - count;
    bool first = true;
    for (uint64_t i = start; i < head; i++) {
      Event& e = ring_[i % kRingCap];
      // Seqlock read: copy, then verify no writer touched the slot.
      uint64_t s1 = e.seq.load(std::memory_order_acquire);
      if (s1 & 1) continue;  // write in flight
      Event copy;
      SanitizeName(copy.name, e.name);
      copy.start_ns = e.start_ns;
      copy.dur_ns = e.dur_ns;
      copy.flops = e.flops;
      copy.kind = e.kind;
      copy.tid = e.tid;
      if (e.seq.load(std::memory_order_acquire) != s1) continue;  // torn
      if (copy.dur_ns == 0 && copy.start_ns == 0) continue;
      if (!first) fputc(',', f);
      first = false;
      fprintf(f,
              "{\"name\":\"%s\",\"ph\":\"X\",\"ts\":%.3f,\"dur\":%.3f,"
              "\"pid\":%d,\"tid\":%d,\"args\":{\"kind\":%d,\"flops\":%.0f}}",
              copy.name, double(copy.start_ns) / 1000.0,
              double(copy.dur_ns) / 1000.0, int(getpid()), copy.tid,
              copy.kind, copy.flops);
    }
    fputs("]}", f);
    fclose(f);
    return 0;
  }

  // ---- HTTP daemon ---------------------------------------------------------

  int StartServer(int port) {
    std::lock_guard<std::mutex> g(mu_);
    if (server_fd_ >= 0) return server_port_;
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return 0;
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(uint16_t(port));
    if (bind(fd, (sockaddr*)&addr, sizeof(addr)) != 0 ||
        listen(fd, 16) != 0) {
      close(fd);
      return 0;
    }
    socklen_t len = sizeof(addr);
    getsockname(fd, (sockaddr*)&addr, &len);
    server_fd_ = fd;
    server_port_ = ntohs(addr.sin_port);
    server_thread_ = std::thread([this] { ServeLoop(); });
    server_thread_.detach();
    return server_port_;
  }

  void Shutdown() {
    std::lock_guard<std::mutex> g(mu_);
    watchdog_running_ = false;
    if (server_fd_ >= 0) {
      shutdown(server_fd_, SHUT_RDWR);
      close(server_fd_);
      server_fd_ = -1;
    }
  }

 private:
  Manager() : ring_(kRingCap), inflight_(kMaxInflight) {}

  void WatchdogLoop() {
    while (watchdog_running_) {
      std::this_thread::sleep_for(std::chrono::milliseconds(500));
      int hung = HangCount();
      if (hung > 0) {
        SetGauge("hang_detected", 1.0);
      } else {
        SetGauge("hang_detected", 0.0);
      }
    }
  }

  void ServeLoop() {
    while (true) {
      int cfd = accept(server_fd_, nullptr, nullptr);
      if (cfd < 0) return;  // server closed
      std::thread([this, cfd] { HandleConn(cfd); }).detach();
    }
  }

  void HandleConn(int cfd) {
    char req[1024];
    ssize_t n = read(cfd, req, sizeof(req) - 1);
    if (n <= 0) {
      close(cfd);
      return;
    }
    req[n] = 0;
    std::string body;
    const char* ctype = "text/plain; version=0.0.4";
    if (strncmp(req, "GET /metrics", 12) == 0) {
      body = MetricsText();
    } else if (strncmp(req, "GET /healthz", 12) == 0) {
      body = "ok\n";
    } else if (strncmp(req, "GET /timeline", 13) == 0) {
      char path[] = "/tmp/tpu_timer_timeline_XXXXXX";
      int tfd = mkstemp(path);
      if (tfd >= 0) {
        close(tfd);
        DumpTimeline(path);
        FILE* f = fopen(path, "r");
        if (f) {
          char buf[8192];
          size_t r;
          while ((r = fread(buf, 1, sizeof(buf), f)) > 0)
            body.append(buf, r);
          fclose(f);
        }
        unlink(path);
        ctype = "application/json";
      }
    } else {
      const char* resp = "HTTP/1.1 404 Not Found\r\nContent-Length: 0\r\n\r\n";
      (void)!write(cfd, resp, strlen(resp));
      close(cfd);
      return;
    }
    char hdr[256];
    snprintf(hdr, sizeof(hdr),
             "HTTP/1.1 200 OK\r\nContent-Type: %s\r\n"
             "Content-Length: %zu\r\nConnection: close\r\n\r\n",
             ctype, body.size());
    (void)!write(cfd, hdr, strlen(hdr));
    (void)!write(cfd, body.data(), body.size());
    close(cfd);
  }

  std::mutex mu_;
  std::vector<Event> ring_;
  std::atomic<uint64_t> ring_head_{0};
  std::vector<Inflight> inflight_;
  std::map<std::string, Histogram> hist_;
  std::map<std::string, double> gauges_;
  std::map<std::string, double> counters_;
  int64_t hang_timeout_ns_ = 0;
  std::atomic<bool> watchdog_running_{false};
  std::thread watchdog_;
  std::thread server_thread_;
  int server_fd_ = -1;
  int server_port_ = 0;
};

}  // namespace

extern "C" {

int tt_init(int64_t hang_timeout_ms) {
  Manager::Get().Init(hang_timeout_ms);
  return 0;
}

int tt_start_server(int port) { return Manager::Get().StartServer(port); }

int64_t tt_begin(const char* name, int kind) {
  return Manager::Get().Begin(name, kind);
}

void tt_end(int64_t span_id, double flops) {
  Manager::Get().End(span_id, flops);
}

void tt_record(const char* name, int kind, int64_t start_ns, int64_t dur_ns,
               double flops) {
  Manager::Get().Record(name, kind, start_ns, dur_ns, flops, 0);
}

void tt_set_gauge(const char* name, double value) {
  Manager::Get().SetGauge(name, value);
}

void tt_counter_add(const char* name, double delta) {
  Manager::Get().CounterAdd(name, delta);
}

int tt_hang_count() { return Manager::Get().HangCount(); }

int64_t tt_now_ns() { return NowNs(); }

int tt_dump_timeline(const char* path) {
  return Manager::Get().DumpTimeline(path);
}

int tt_metrics_text(char* buf, int cap) {
  std::string text = Manager::Get().MetricsText();
  if (int(text.size()) + 1 > cap) return -int(text.size()) - 1;
  memcpy(buf, text.data(), text.size());
  buf[text.size()] = 0;
  return int(text.size());
}

void tt_shutdown() { Manager::Get().Shutdown(); }

}  // extern "C"
