// tpu_timer: native profiling/hang-detection runtime for TPU training.
//
// Parity: reference xpu_timer/common/manager.h (GpuTimerManager:106,
// KernelTraceManager:50) and server/hosting_service_server_client.h —
// re-designed for TPU: instead of dlsym-intercepting libcudart, timings
// arrive through an explicit C ABI fed by the Python bridge (step spans,
// XLA compile spans, checkpoint phases, collective probes). The native
// layer owns what must not depend on a (possibly hung) Python runtime:
// the lock-light trace ring, metric aggregation, the Prometheus/timeline
// HTTP daemon, and the hang watchdog.
//
// C ABI (stable, used via ctypes):
//   tt_init(hang_timeout_ms)        -> 0 ok
//   tt_start_server(port)           -> bound port (0 on failure)
//   tt_begin(name, kind)            -> span id (thread-safe)
//   tt_end(span_id, flops)          -> records duration + flops
//   tt_record(name, kind, start_ns, dur_ns, flops) -> out-of-band event
//   tt_set_gauge(name, value)
//   tt_counter_add(name, delta)
//   tt_hang_count()                 -> spans currently over the timeout
//   tt_dump_timeline(path)          -> chrome-trace JSON (perfetto-loadable)
//   tt_metrics_text(buf, cap)       -> Prometheus text exposition
//   tt_shutdown()

#ifndef DLROVER_TPU_TIMER_H_
#define DLROVER_TPU_TIMER_H_

#include <cstdint>

extern "C" {

int tt_init(int64_t hang_timeout_ms);
int tt_start_server(int port);
int64_t tt_begin(const char* name, int kind);
void tt_end(int64_t span_id, double flops);
void tt_record(const char* name, int kind, int64_t start_ns, int64_t dur_ns,
               double flops);
void tt_set_gauge(const char* name, double value);
void tt_counter_add(const char* name, double delta);
int tt_hang_count();
int64_t tt_now_ns();
int tt_dump_timeline(const char* path);
// Returns bytes written (excluding NUL); negative if cap too small.
int tt_metrics_text(char* buf, int cap);
void tt_shutdown();

}  // extern "C"

#endif  // DLROVER_TPU_TIMER_H_
