// stack_sampler: out-of-process NATIVE stack capture for hung workers.
//
// Parity: reference xpu_timer orchestrates gdb/py-spy dumps of arbitrary
// training processes from its per-node daemon
// (xpu_timer/server/hosting_service_server_client.cc, RPC surface
// xpu_timer/protos/hosting_service.proto:14-250). This image ships
// neither gdb nor py-spy, so the capability is built directly:
// ptrace-attach to every thread of the target and unwind its USER-SPACE
// stack with libunwind-ptrace — the C/C++ frames a faulthandler dump
// cannot see (a worker wedged inside libtpu/XLA shows Python blocked in
// one opaque line; the interesting frames are native — VERDICT r4 #4).
//
// The distro ships libunwind runtime libraries but no headers, so the
// small, ABI-stable slice of the API used here is declared locally and
// resolved with dlopen/dlsym at runtime (x86_64 symbol prefix
// _Ux86_64_). Usage:
//
//     stack_sampler <pid> [max_frames]
//
// Output (one block per thread, faulthandler-adjacent format so the
// analysis tool folds it into the same histograms):
//
//     Native thread <tid> (most recent call first):
//       #0 0x00007f... clock_nanosleep+0x47
//       ...
//
// Exit code 0 if at least one thread unwound, 1 otherwise. The target
// keeps running: each thread is attached, walked, detached (SIGSTOP /
// SIGCONT window of a few ms per thread — the same disturbance py-spy
// imposes).

#include <cxxabi.h>
#include <dirent.h>
#include <dlfcn.h>
#include <errno.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/ptrace.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

#include <string>
#include <vector>

namespace {

// ---- libunwind ABI slice (no headers in the image) ----
using unw_word = unsigned long;
struct UnwCursor {
  // Real unw_cursor_t is 127 words; oversize for safety.
  unw_word opaque[512];
};
using unw_addr_space_t = void*;
constexpr int kUnwRegIp = 16;  // UNW_X86_64_RIP == UNW_REG_IP on x86_64

using create_addr_space_fn = unw_addr_space_t (*)(void* accessors,
                                                  int byteorder);
using destroy_addr_space_fn = void (*)(unw_addr_space_t);
using init_remote_fn = int (*)(UnwCursor*, unw_addr_space_t, void*);
using step_fn = int (*)(UnwCursor*);
using get_reg_fn = int (*)(UnwCursor*, int, unw_word*);
using get_proc_name_fn = int (*)(UnwCursor*, char*, size_t, unw_word*);
using upt_create_fn = void* (*)(pid_t);
using upt_destroy_fn = void (*)(void*);

struct Unwind {
  create_addr_space_fn create_addr_space;
  destroy_addr_space_fn destroy_addr_space;
  init_remote_fn init_remote;
  step_fn step;
  get_reg_fn get_reg;
  get_proc_name_fn get_proc_name;
  void* upt_accessors;
  upt_create_fn upt_create;
  upt_destroy_fn upt_destroy;
};

bool load_unwind(Unwind* u) {
  // libunwind-ptrace links against libunwind-generic; load the arch
  // library RTLD_GLOBAL first so _UPT symbols resolve.
  void* arch = dlopen("libunwind-x86_64.so.8", RTLD_NOW | RTLD_GLOBAL);
  if (!arch) {
    fprintf(stderr, "stack_sampler: %s\n", dlerror());
    return false;
  }
  void* upt = dlopen("libunwind-ptrace.so.0", RTLD_NOW | RTLD_GLOBAL);
  if (!upt) {
    fprintf(stderr, "stack_sampler: %s\n", dlerror());
    return false;
  }
  u->create_addr_space = reinterpret_cast<create_addr_space_fn>(
      dlsym(arch, "_Ux86_64_create_addr_space"));
  u->destroy_addr_space = reinterpret_cast<destroy_addr_space_fn>(
      dlsym(arch, "_Ux86_64_destroy_addr_space"));
  u->init_remote = reinterpret_cast<init_remote_fn>(
      dlsym(arch, "_Ux86_64_init_remote"));
  u->step = reinterpret_cast<step_fn>(dlsym(arch, "_Ux86_64_step"));
  u->get_reg = reinterpret_cast<get_reg_fn>(
      dlsym(arch, "_Ux86_64_get_reg"));
  u->get_proc_name = reinterpret_cast<get_proc_name_fn>(
      dlsym(arch, "_Ux86_64_get_proc_name"));
  u->upt_accessors = dlsym(upt, "_UPT_accessors");
  u->upt_create =
      reinterpret_cast<upt_create_fn>(dlsym(upt, "_UPT_create"));
  u->upt_destroy =
      reinterpret_cast<upt_destroy_fn>(dlsym(upt, "_UPT_destroy"));
  if (!u->create_addr_space || !u->init_remote || !u->step ||
      !u->get_reg || !u->get_proc_name || !u->upt_accessors ||
      !u->upt_create || !u->upt_destroy) {
    fprintf(stderr, "stack_sampler: missing libunwind symbols\n");
    return false;
  }
  return true;
}

std::string demangle(const char* name) {
  int status = 0;
  char* out = abi::__cxa_demangle(name, nullptr, nullptr, &status);
  if (status == 0 && out) {
    std::string s(out);
    free(out);
    return s;
  }
  return name;
}

std::vector<pid_t> list_tids(pid_t pid) {
  std::vector<pid_t> tids;
  char path[64];
  snprintf(path, sizeof(path), "/proc/%d/task", pid);
  DIR* dir = opendir(path);
  if (!dir) return tids;
  while (dirent* ent = readdir(dir)) {
    if (ent->d_name[0] == '.') continue;
    tids.push_back(static_cast<pid_t>(atol(ent->d_name)));
  }
  closedir(dir);
  return tids;
}

// Attach and wait for the stop; __WALL covers clone threads.
bool attach(pid_t tid) {
  if (ptrace(PTRACE_ATTACH, tid, nullptr, nullptr) != 0) return false;
  int status = 0;
  for (int i = 0; i < 1000; ++i) {
    pid_t r = waitpid(tid, &status, __WALL);
    if (r == tid && WIFSTOPPED(status)) return true;
    if (r < 0 && errno != EINTR) break;
  }
  ptrace(PTRACE_DETACH, tid, nullptr, nullptr);
  return false;
}

int walk_thread(const Unwind& u, pid_t tid, int max_frames) {
  if (!attach(tid)) {
    fprintf(stderr, "stack_sampler: attach %d failed: %s\n", tid,
            strerror(errno));
    return 0;
  }
  int frames = 0;
  unw_addr_space_t as = u.create_addr_space(u.upt_accessors, 0);
  void* ui = as ? u.upt_create(tid) : nullptr;
  if (ui) {
    UnwCursor cursor;
    memset(&cursor, 0, sizeof(cursor));
    if (u.init_remote(&cursor, as, ui) == 0) {
      printf("Native thread %d (most recent call first):\n", tid);
      do {
        unw_word ip = 0;
        if (u.get_reg(&cursor, kUnwRegIp, &ip) != 0) break;
        char name[512];
        unw_word off = 0;
        if (u.get_proc_name(&cursor, name, sizeof(name), &off) == 0) {
          printf("  #%d 0x%016lx %s+0x%lx\n", frames, ip,
                 demangle(name).c_str(), off);
        } else {
          printf("  #%d 0x%016lx ??\n", frames, ip);
        }
        ++frames;
      } while (frames < max_frames && u.step(&cursor) > 0);
      printf("\n");
    }
    u.upt_destroy(ui);
  }
  if (as) u.destroy_addr_space(as);
  ptrace(PTRACE_DETACH, tid, nullptr, nullptr);
  return frames;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s <pid> [max_frames]\n", argv[0]);
    return 2;
  }
  pid_t pid = static_cast<pid_t>(atol(argv[1]));
  int max_frames = argc > 2 ? atoi(argv[2]) : 64;
  Unwind u;
  if (!load_unwind(&u)) return 1;
  int total = 0;
  for (pid_t tid : list_tids(pid)) {
    total += walk_thread(u, tid, max_frames);
  }
  fflush(stdout);
  return total > 0 ? 0 : 1;
}
