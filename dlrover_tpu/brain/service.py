"""Brain service: cluster-level stats store + resource optimization.

Parity: reference dlrover/go/brain (gRPC ``optimize`` /
``persist_metrics``, MySQL datastore, optimizer plugins) — re-scoped to
a lightweight HTTP service with a JSON-file datastore: masters report
runtime samples and job completions; ``optimize`` answers with a worker
count learned from completed jobs of the same job name (the cross-job
memory a single-job local optimizer cannot have).

The optimizer is PLUGGABLE (the reference's processor/evaluator plugin
architecture, scaled down): built-ins are selected with ``--optimizer``
(``speedup`` — best cost-adjusted throughput; ``marginal-gain`` —
largest worker count still scaling efficiently), and external
algorithms load from a ``pkg.module:factory`` dotted path. The JSONL
store self-compacts (record-count and age retention) so it no longer
grows without bound.

Run: ``python -m dlrover_tpu.brain.service --port 8600 --data_dir /var/brain``
"""

import argparse
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from dlrover_tpu.common.log import logger


class BrainStore:
    """JSON-lines store of job samples and completions, with retention:
    every ``compact_every`` appends (and at startup) each file is
    rewritten keeping the newest ``max_records`` that are younger than
    ``max_age_s`` — a brain that only ever grows eventually optimizes
    from dead history and fills the disk."""

    def __init__(
        self,
        data_dir: str,
        max_records: int = 10_000,
        max_age_s: float = 30 * 24 * 3600.0,
        compact_every: int = 500,
    ):
        self._dir = data_dir
        os.makedirs(data_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._max_records = max_records
        self._max_age_s = max_age_s
        self._compact_every = max(compact_every, 1)
        self._appends: Dict[str, int] = {}
        for kind in ("runtime", "completion"):
            self.compact(kind)

    def compact(self, kind: str):
        """Rewrite the file applying retention (atomic replace)."""

        def ts_of(record) -> float:
            # Same junk tolerance as load(): a foreign writer's bad ts
            # must not brick service start (compact runs in __init__).
            try:
                return float(record.get("ts", 0))
            except (TypeError, ValueError):
                return 0.0

        with self._lock:
            records = self._load_unlocked(kind)
            cutoff = time.time() - self._max_age_s
            fresh = [r for r in records if ts_of(r) >= cutoff]
            # max_records <= 0 means NO count cap (age still applies) —
            # the naive [-0:] slice would keep everything, while [] here
            # would irreversibly wipe the store at startup.
            kept = (
                fresh[-self._max_records:]
                if self._max_records > 0
                else fresh
            )
            if len(kept) == len(records):
                return
            path = self._path(kind)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                for r in kept:
                    f.write(json.dumps(r) + "\n")
            os.replace(tmp, path)
            logger.info(
                "brain store %s compacted: %d -> %d records",
                kind, len(records), len(kept),
            )

    def _path(self, kind: str) -> str:
        return os.path.join(self._dir, f"{kind}.jsonl")

    def append(self, kind: str, record: Dict):
        record = dict(record)
        record["ts"] = time.time()
        path = self._path(kind)
        with self._lock:
            # A crash mid-append can leave a torn final line; appending
            # straight after it would merge (and lose) this record too.
            needs_newline = False
            try:
                with open(path, "rb") as f:
                    f.seek(0, os.SEEK_END)
                    if f.tell() > 0:
                        f.seek(-1, os.SEEK_END)
                        needs_newline = f.read(1) != b"\n"
            except OSError:
                pass
            with open(path, "a") as f:
                if needs_newline:
                    f.write("\n")
                f.write(json.dumps(record) + "\n")
            self._appends[kind] = self._appends.get(kind, 0) + 1
            due = self._appends[kind] % self._compact_every == 0
        if due:
            self.compact(kind)

    def load(self, kind: str) -> List[Dict]:
        with self._lock:
            return self._load_unlocked(kind)

    def _load_unlocked(self, kind: str) -> List[Dict]:
        records = []
        try:
            with open(self._path(kind)) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        record = json.loads(line)
                    except ValueError:
                        continue  # torn line from a crash mid-append
                    if isinstance(record, dict):
                        records.append(record)
        except OSError:
            pass
        return records


def _job_samples(store: BrainStore, job_name: str):
    samples = []
    for s in store.load("runtime"):
        if s.get("job_name") != job_name:
            continue
        try:
            speed = float(s.get("speed", 0))
            count = int(s.get("worker_count", 0))
        except (TypeError, ValueError):
            continue  # records are caller-supplied; skip junk
        if speed > 0 and count > 0:
            samples.append((count, speed))
    return samples


def _mean_speed_by_count(samples) -> Dict[int, float]:
    by_count: Dict[int, List[float]] = {}
    for count, speed in samples:
        by_count.setdefault(count, []).append(speed)
    return {c: sum(v) / len(v) for c, v in by_count.items()}


class SpeedupOptimizer:
    """Cross-job heuristic: among past runs of this job name, prefer the
    worker count with the best observed speed-per-worker (cost-adjusted
    throughput)."""

    def __init__(self, store: BrainStore):
        self._store = store

    def optimize(self, job_name: str) -> Optional[Dict]:
        samples = _job_samples(self._store, job_name)
        if not samples:
            return None
        best_count, best_value = 0, -1.0
        for count, mean in _mean_speed_by_count(samples).items():
            value = mean / count
            if value > best_value:
                best_count, best_value = count, value
        if best_count <= 0:
            return None
        return {
            "worker_count": best_count,
            "evidence_samples": len(samples),
            "optimizer": "speedup",
        }


class MarginalGainOptimizer:
    """Scaling-efficiency heuristic: walk observed worker counts in
    ascending order and keep growing while each scale-up still delivered
    at least ``efficiency`` of its proportional throughput gain —
    answers "how far did this job USEFULLY scale", where speedup answers
    "where was it cheapest"."""

    def __init__(self, store: BrainStore, efficiency: float = 0.7):
        self._store = store
        self._efficiency = efficiency

    def optimize(self, job_name: str) -> Optional[Dict]:
        samples = _job_samples(self._store, job_name)
        if not samples:
            return None
        means = sorted(_mean_speed_by_count(samples).items())
        best_count = means[0][0]
        prev_count, prev_speed = means[0]
        for count, speed in means[1:]:
            ideal = prev_speed * count / prev_count
            if speed >= self._efficiency * ideal:
                best_count = count
                prev_count, prev_speed = count, speed
            else:
                break
        return {
            "worker_count": best_count,
            "evidence_samples": len(samples),
            "optimizer": "marginal-gain",
        }


# Back-compat alias: the original single algorithm.
BrainOptimizer = SpeedupOptimizer

OPTIMIZERS = {
    "speedup": SpeedupOptimizer,
    "marginal-gain": MarginalGainOptimizer,
}


def create_optimizer(name: str, store: BrainStore):
    """Resolve an optimizer: a registry name or an external
    ``pkg.module:factory`` dotted path (the plugin contract — factory
    takes the store, returns an object with ``optimize(job_name)``)."""
    if name in OPTIMIZERS:
        return OPTIMIZERS[name](store)
    if ":" in name:
        import importlib

        module, attr = name.split(":", 1)
        try:
            factory = getattr(importlib.import_module(module), attr)
        except (ImportError, AttributeError, ValueError) as e:
            raise ValueError(
                f"optimizer plugin {name!r} failed to load ({e}); "
                f"expected pkg.module:factory, or a registry name from "
                f"{sorted(OPTIMIZERS)}"
            ) from e
        return factory(store)
    raise ValueError(
        f"unknown optimizer {name!r}; registry: {sorted(OPTIMIZERS)} "
        f"or a pkg.module:factory path"
    )


class BrainService:
    def __init__(
        self,
        port: int = 0,
        data_dir: str = "/tmp/dlrover_brain",
        optimizer: str = "speedup",
        max_records: int = 10_000,
        max_age_s: float = 30 * 24 * 3600.0,
    ):
        self.store = BrainStore(
            data_dir, max_records=max_records, max_age_s=max_age_s
        )
        self.optimizer = create_optimizer(optimizer, self.store)
        self._server = ThreadingHTTPServer(
            ("0.0.0.0", port), self._make_handler()
        )
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def _make_handler(self):
        service = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _json(self, code: int, payload):
                data = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_POST(self):
                length = int(self.headers.get("Content-Length", "0"))
                try:
                    body = json.loads(self.rfile.read(length) or b"{}")
                except ValueError:
                    self._json(400, {"error": "bad json"})
                    return
                if self.path == "/persist_metrics":
                    kind = body.get("kind", "runtime")
                    if kind not in ("runtime", "completion"):
                        self._json(400, {"error": f"bad kind {kind}"})
                        return
                    service.store.append(kind, body.get("record", {}))
                    self._json(200, {"ok": True})
                elif self.path == "/optimize":
                    plan = service.optimizer.optimize(
                        body.get("job_name", "")
                    )
                    self._json(200, {"plan": plan})
                else:
                    self._json(404, {"error": "not found"})

        return Handler

    def start(self):
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="brain", daemon=True
        )
        self._thread.start()
        logger.info("brain service on port %d", self.port)

    def stop(self):
        if self._thread is not None:
            self._server.shutdown()
        self._server.server_close()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="dlrover-tpu brain")
    parser.add_argument("--port", type=int, default=8600)
    parser.add_argument("--data_dir", type=str, default="/tmp/dlrover_brain")
    parser.add_argument(
        "--optimizer", type=str,
        default=os.getenv("DLROVER_TPU_BRAIN_OPTIMIZER", "speedup"),
        help="registry name (speedup, marginal-gain) or pkg.module:factory",
    )
    parser.add_argument("--max_records", type=int, default=10_000)
    parser.add_argument(
        "--max_age_days", type=float, default=30.0,
        help="retention window for the JSONL store",
    )
    args = parser.parse_args(argv)
    service = BrainService(
        args.port,
        args.data_dir,
        optimizer=args.optimizer,
        max_records=args.max_records,
        max_age_s=args.max_age_days * 24 * 3600.0,
    )
    service.start()
    try:
        while True:
            time.sleep(60)
    except KeyboardInterrupt:
        service.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
