"""Brain service: cluster-level stats store + resource optimization.

Parity: reference dlrover/go/brain (gRPC ``optimize`` /
``persist_metrics``, MySQL datastore, optimizer plugins) — re-scoped to
a lightweight HTTP service with a JSON-file datastore: masters report
runtime samples and job completions; ``optimize`` answers with a worker
count learned from completed jobs of the same job name (the cross-job
memory a single-job local optimizer cannot have).

The reference's admin/processor/evaluator architecture is implemented,
scaled to single-service size:

- **datastore**: ``--store jsonl`` (self-compacting JSON-lines) or
  ``--store sqlite`` (persistent DB with indexed job/time filtering —
  the MySQL analogue). Both apply record-count and age retention.
- **optimizer plugins**: ``--optimizer`` picks ``speedup`` (best
  cost-adjusted throughput), ``marginal-gain`` (largest worker count
  still scaling efficiently), or an external ``pkg.module:factory``.
- **evaluators** (brain/evaluators.py): throughput-trend, straggler
  dispersion, and OOM-risk assessments run by the OptimizeProcessor on
  every ``/optimize`` and returned alongside the plan; pluggable the
  same way via ``--evaluators``.
- **admin**: GET ``/admin/jobs`` (known jobs + record counts),
  ``/admin/store`` (backend + retention stats), ``/admin/evaluators``.

Run: ``python -m dlrover_tpu.brain.service --port 8600 --data_dir /var/brain``
"""

import argparse
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from dlrover_tpu.common.log import logger


class BrainStore:
    """JSON-lines store of job samples and completions, with retention:
    every ``compact_every`` appends (and at startup) each file is
    rewritten keeping the newest ``max_records`` that are younger than
    ``max_age_s`` — a brain that only ever grows eventually optimizes
    from dead history and fills the disk."""

    def __init__(
        self,
        data_dir: str,
        max_records: int = 10_000,
        max_age_s: float = 30 * 24 * 3600.0,
        compact_every: int = 500,
    ):
        self._dir = data_dir
        os.makedirs(data_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._max_records = max_records
        self._max_age_s = max_age_s
        self._compact_every = max(compact_every, 1)
        self._appends: Dict[str, int] = {}
        for kind in ("runtime", "completion"):
            self.compact(kind)

    def compact(self, kind: str):
        """Rewrite the file applying retention (atomic replace)."""

        def ts_of(record) -> float:
            # Same junk tolerance as load(): a foreign writer's bad ts
            # must not brick service start (compact runs in __init__).
            try:
                return float(record.get("ts", 0))
            except (TypeError, ValueError):
                return 0.0

        with self._lock:
            records = self._load_unlocked(kind)
            cutoff = time.time() - self._max_age_s
            fresh = [r for r in records if ts_of(r) >= cutoff]
            # max_records <= 0 means NO count cap (age still applies) —
            # the naive [-0:] slice would keep everything, while [] here
            # would irreversibly wipe the store at startup.
            kept = (
                fresh[-self._max_records:]
                if self._max_records > 0
                else fresh
            )
            if len(kept) == len(records):
                return
            path = self._path(kind)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                for r in kept:
                    f.write(json.dumps(r) + "\n")
            os.replace(tmp, path)
            logger.info(
                "brain store %s compacted: %d -> %d records",
                kind, len(records), len(kept),
            )

    def _path(self, kind: str) -> str:
        return os.path.join(self._dir, f"{kind}.jsonl")

    def append(self, kind: str, record: Dict):
        record = dict(record)
        record["ts"] = time.time()
        path = self._path(kind)
        with self._lock:
            # A crash mid-append can leave a torn final line; appending
            # straight after it would merge (and lose) this record too.
            needs_newline = False
            try:
                with open(path, "rb") as f:
                    f.seek(0, os.SEEK_END)
                    if f.tell() > 0:
                        f.seek(-1, os.SEEK_END)
                        needs_newline = f.read(1) != b"\n"
            except OSError:
                pass
            with open(path, "a") as f:
                if needs_newline:
                    f.write("\n")
                f.write(json.dumps(record) + "\n")
            self._appends[kind] = self._appends.get(kind, 0) + 1
            due = self._appends[kind] % self._compact_every == 0
        if due:
            self.compact(kind)

    def load(self, kind: str, job_name: Optional[str] = None) -> List[Dict]:
        with self._lock:
            records = self._load_unlocked(kind)
        if job_name is None:
            return records
        return [r for r in records if r.get("job_name") == job_name]

    def stats(self) -> Dict:
        return {
            "backend": "jsonl",
            "dir": self._dir,
            "records": {
                kind: len(self.load(kind))
                for kind in ("runtime", "completion")
            },
            "max_records": self._max_records,
            "max_age_s": self._max_age_s,
        }

    def job_names(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for kind in ("runtime", "completion"):
            for r in self.load(kind):
                name = r.get("job_name")
                if name:
                    counts[name] = counts.get(name, 0) + 1
        return counts

    def close(self):
        pass

    def _load_unlocked(self, kind: str) -> List[Dict]:
        records = []
        try:
            with open(self._path(kind)) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        record = json.loads(line)
                    except ValueError:
                        continue  # torn line from a crash mid-append
                    if isinstance(record, dict):
                        records.append(record)
        except OSError:
            pass
        return records


class SqliteBrainStore:
    """Persistent-DB datastore (reference go/brain rides MySQL; sqlite
    is the stdlib equivalent for this scale): same interface as the
    JSONL store, but filtering happens in SQL over an indexed table and
    retention is a DELETE, not a file rewrite. Select with
    ``--store sqlite``."""

    def __init__(
        self,
        data_dir: str,
        max_records: int = 10_000,
        max_age_s: float = 30 * 24 * 3600.0,
        compact_every: int = 500,
    ):
        import sqlite3

        os.makedirs(data_dir, exist_ok=True)
        self._dir = data_dir
        self._path = os.path.join(data_dir, "brain.sqlite")
        self._max_records = max_records
        self._max_age_s = max_age_s
        self._compact_every = max(compact_every, 1)
        self._appends = 0
        self._lock = threading.Lock()
        self._db = sqlite3.connect(self._path, check_same_thread=False)
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS metrics ("
            " id INTEGER PRIMARY KEY AUTOINCREMENT,"
            " kind TEXT NOT NULL, job_name TEXT, ts REAL NOT NULL,"
            " record TEXT NOT NULL)"
        )
        self._db.execute(
            "CREATE INDEX IF NOT EXISTS idx_metrics "
            "ON metrics (kind, job_name, ts)"
        )
        self._db.commit()
        self.compact()

    def append(self, kind: str, record: Dict):
        record = dict(record)
        record["ts"] = time.time()
        with self._lock:
            self._db.execute(
                "INSERT INTO metrics (kind, job_name, ts, record) "
                "VALUES (?, ?, ?, ?)",
                (
                    kind,
                    record.get("job_name"),
                    record["ts"],
                    json.dumps(record),
                ),
            )
            self._db.commit()
            self._appends += 1
            due = self._appends % self._compact_every == 0
        if due:
            self.compact()

    def load(self, kind: str, job_name: Optional[str] = None) -> List[Dict]:
        q = "SELECT record FROM metrics WHERE kind = ?"
        args: list = [kind]
        if job_name is not None:
            q += " AND job_name = ?"
            args.append(job_name)
        q += " ORDER BY ts, id"  # id tiebreak: same-tick appends
        with self._lock:
            rows = self._db.execute(q, args).fetchall()
        out = []
        for (blob,) in rows:
            try:
                record = json.loads(blob)
            except ValueError:
                continue
            if isinstance(record, dict):
                out.append(record)
        return out

    def compact(self, kind: Optional[str] = None):
        with self._lock:
            cutoff = time.time() - self._max_age_s
            self._db.execute("DELETE FROM metrics WHERE ts < ?", (cutoff,))
            if self._max_records > 0:
                for k in ("runtime", "completion"):
                    self._db.execute(
                        "DELETE FROM metrics WHERE kind = ? AND id NOT IN"
                        " (SELECT id FROM metrics WHERE kind = ?"
                        "  ORDER BY ts DESC, id DESC LIMIT ?)",
                        (k, k, self._max_records),
                    )
            self._db.commit()

    def stats(self) -> Dict:
        with self._lock:
            rows = self._db.execute(
                "SELECT kind, COUNT(*) FROM metrics GROUP BY kind"
            ).fetchall()
        return {
            "backend": "sqlite",
            "path": self._path,
            "records": {k: n for k, n in rows},
            "max_records": self._max_records,
            "max_age_s": self._max_age_s,
        }

    def job_names(self) -> Dict[str, int]:
        with self._lock:
            rows = self._db.execute(
                "SELECT job_name, COUNT(*) FROM metrics "
                "WHERE job_name IS NOT NULL AND job_name != '' "
                "GROUP BY job_name"
            ).fetchall()
        return {k: n for k, n in rows}

    def close(self):
        with self._lock:
            self._db.close()


STORES = {"jsonl": BrainStore, "sqlite": SqliteBrainStore}


def _job_samples(store: BrainStore, job_name: str):
    samples = []
    for s in store.load("runtime", job_name=job_name):
        try:
            speed = float(s.get("speed", 0))
            count = int(s.get("worker_count", 0))
        except (TypeError, ValueError):
            continue  # records are caller-supplied; skip junk
        if speed > 0 and count > 0:
            samples.append((count, speed))
    return samples


def _mean_speed_by_count(samples) -> Dict[int, float]:
    by_count: Dict[int, List[float]] = {}
    for count, speed in samples:
        by_count.setdefault(count, []).append(speed)
    return {c: sum(v) / len(v) for c, v in by_count.items()}


class SpeedupOptimizer:
    """Cross-job heuristic: among past runs of this job name, prefer the
    worker count with the best observed speed-per-worker (cost-adjusted
    throughput)."""

    def __init__(self, store: BrainStore):
        self._store = store

    def optimize(self, job_name: str) -> Optional[Dict]:
        samples = _job_samples(self._store, job_name)
        if not samples:
            return None
        best_count, best_value = 0, -1.0
        for count, mean in _mean_speed_by_count(samples).items():
            value = mean / count
            if value > best_value:
                best_count, best_value = count, value
        if best_count <= 0:
            return None
        return {
            "worker_count": best_count,
            "evidence_samples": len(samples),
            "optimizer": "speedup",
        }


class MarginalGainOptimizer:
    """Scaling-efficiency heuristic: walk observed worker counts in
    ascending order and keep growing while each scale-up still delivered
    at least ``efficiency`` of its proportional throughput gain —
    answers "how far did this job USEFULLY scale", where speedup answers
    "where was it cheapest"."""

    def __init__(self, store: BrainStore, efficiency: float = 0.7):
        self._store = store
        self._efficiency = efficiency

    def optimize(self, job_name: str) -> Optional[Dict]:
        samples = _job_samples(self._store, job_name)
        if not samples:
            return None
        means = sorted(_mean_speed_by_count(samples).items())
        best_count = means[0][0]
        prev_count, prev_speed = means[0]
        for count, speed in means[1:]:
            ideal = prev_speed * count / prev_count
            if speed >= self._efficiency * ideal:
                best_count = count
                prev_count, prev_speed = count, speed
            else:
                break
        return {
            "worker_count": best_count,
            "evidence_samples": len(samples),
            "optimizer": "marginal-gain",
        }


# Back-compat alias: the original single algorithm.
BrainOptimizer = SpeedupOptimizer

OPTIMIZERS = {
    "speedup": SpeedupOptimizer,
    "marginal-gain": MarginalGainOptimizer,
}


def create_optimizer(name: str, store: BrainStore):
    """Resolve an optimizer: a registry name or an external
    ``pkg.module:factory`` dotted path (the plugin contract — factory
    takes the store, returns an object with ``optimize(job_name)``)."""
    from dlrover_tpu.brain.evaluators import load_plugin

    return load_plugin(name, OPTIMIZERS, store, "optimizer")


class BrainService:
    def __init__(
        self,
        port: int = 0,
        data_dir: str = "/tmp/dlrover_brain",
        optimizer: str = "speedup",
        max_records: int = 10_000,
        max_age_s: float = 30 * 24 * 3600.0,
        store: str = "jsonl",
        evaluators: Optional[List[str]] = None,
    ):
        from dlrover_tpu.brain.evaluators import (
            EVALUATORS,
            OptimizeProcessor,
            create_evaluator,
        )

        if store not in STORES:
            raise ValueError(
                f"unknown store {store!r}; options: {sorted(STORES)}"
            )
        self.store = STORES[store](
            data_dir, max_records=max_records, max_age_s=max_age_s
        )
        self.optimizer = create_optimizer(optimizer, self.store)
        names = (
            evaluators if evaluators is not None
            else sorted(EVALUATORS)
        )
        self.processor = OptimizeProcessor(
            self.optimizer,
            [create_evaluator(n, self.store) for n in names],
            store=self.store,
        )
        self._inflight = 0
        self._inflight_cv = threading.Condition()
        self._server = ThreadingHTTPServer(
            ("0.0.0.0", port), self._make_handler()
        )
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def _make_handler(self):
        service = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def handle(self):
                # In-flight accounting: ThreadingHTTPServer's handler
                # threads are DAEMON threads (server_close joins
                # nothing), so stop() must wait for this count to drain
                # before closing the store under a live handler.
                with service._inflight_cv:
                    service._inflight += 1
                try:
                    super().handle()
                finally:
                    with service._inflight_cv:
                        service._inflight -= 1
                        service._inflight_cv.notify_all()

            def _json(self, code: int, payload):
                data = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_POST(self):
                length = int(self.headers.get("Content-Length", "0"))
                try:
                    body = json.loads(self.rfile.read(length) or b"{}")
                except ValueError:
                    self._json(400, {"error": "bad json"})
                    return
                if self.path == "/persist_metrics":
                    kind = body.get("kind", "runtime")
                    if kind not in ("runtime", "completion"):
                        self._json(400, {"error": f"bad kind {kind}"})
                        return
                    service.store.append(kind, body.get("record", {}))
                    self._json(200, {"ok": True})
                elif self.path == "/optimize":
                    # Full processor response: the optimizer's plan
                    # plus every evaluator's assessment ("plan" key
                    # unchanged for existing clients).
                    self._json(
                        200,
                        service.processor.process(
                            body.get("job_name", "")
                        ),
                    )
                else:
                    self._json(404, {"error": "not found"})

            def do_GET(self):
                # Admin surface (reference brain admin service).
                if self.path == "/admin/jobs":
                    self._json(200, {"jobs": service.store.job_names()})
                elif self.path == "/admin/store":
                    self._json(200, service.store.stats())
                elif self.path == "/admin/evaluators":
                    self._json(200, {
                        "optimizer": type(service.optimizer).__name__,
                        "evaluators":
                            service.processor.evaluator_names,
                    })
                else:
                    self._json(404, {"error": "not found"})

        return Handler

    def start(self):
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="brain", daemon=True
        )
        self._thread.start()
        logger.info("brain service on port %d", self.port)

    def stop(self):
        if self._thread is not None:
            self._server.shutdown()
        self._server.server_close()
        # Handler threads are daemons (server_close joins nothing);
        # wait for in-flight requests to drain before closing the store
        # under them.
        with self._inflight_cv:
            self._inflight_cv.wait_for(
                lambda: self._inflight == 0, timeout=10.0
            )
        self.store.close()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="dlrover-tpu brain")
    parser.add_argument("--port", type=int, default=8600)
    parser.add_argument("--data_dir", type=str, default="/tmp/dlrover_brain")
    parser.add_argument(
        "--optimizer", type=str,
        default=os.getenv("DLROVER_TPU_BRAIN_OPTIMIZER", "speedup"),
        help="registry name (speedup, marginal-gain) or pkg.module:factory",
    )
    parser.add_argument("--max_records", type=int, default=10_000)
    parser.add_argument(
        "--max_age_days", type=float, default=30.0,
        help="retention window for the store",
    )
    parser.add_argument(
        "--store", type=str, default="jsonl",
        choices=sorted(STORES),
        help="datastore backend (sqlite = the reference's persistent DB)",
    )
    parser.add_argument(
        "--evaluators", type=str, default=None,
        help="comma-separated evaluator names or pkg.module:factory "
        'paths; omit for all built-ins, pass "" to disable evaluators',
    )
    args = parser.parse_args(argv)
    service = BrainService(
        args.port,
        args.data_dir,
        optimizer=args.optimizer,
        max_records=args.max_records,
        max_age_s=args.max_age_days * 24 * 3600.0,
        store=args.store,
        evaluators=(
            None if args.evaluators is None
            else [
                e.strip() for e in args.evaluators.split(",")
                if e.strip()
            ]
        ),
    )
    service.start()
    try:
        while True:
            time.sleep(60)
    except KeyboardInterrupt:
        service.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
