"""Brain service: cluster-level stats store + resource optimization.

Parity: reference dlrover/go/brain (gRPC ``optimize`` /
``persist_metrics``, MySQL datastore, optimizer plugins) — re-scoped to
a lightweight HTTP service with a JSON-file datastore: masters report
runtime samples and job completions; ``optimize`` answers with a worker
count learned from completed jobs of the same job name (the cross-job
memory a single-job local optimizer cannot have).

Run: ``python -m dlrover_tpu.brain.service --port 8600 --data_dir /var/brain``
"""

import argparse
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from dlrover_tpu.common.log import logger


class BrainStore:
    """Append-only JSON-lines store of job samples and completions."""

    def __init__(self, data_dir: str):
        self._dir = data_dir
        os.makedirs(data_dir, exist_ok=True)
        self._lock = threading.Lock()

    def _path(self, kind: str) -> str:
        return os.path.join(self._dir, f"{kind}.jsonl")

    def append(self, kind: str, record: Dict):
        record = dict(record)
        record["ts"] = time.time()
        path = self._path(kind)
        with self._lock:
            # A crash mid-append can leave a torn final line; appending
            # straight after it would merge (and lose) this record too.
            needs_newline = False
            try:
                with open(path, "rb") as f:
                    f.seek(0, os.SEEK_END)
                    if f.tell() > 0:
                        f.seek(-1, os.SEEK_END)
                        needs_newline = f.read(1) != b"\n"
            except OSError:
                pass
            with open(path, "a") as f:
                if needs_newline:
                    f.write("\n")
                f.write(json.dumps(record) + "\n")

    def load(self, kind: str) -> List[Dict]:
        records = []
        try:
            with open(self._path(kind)) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        record = json.loads(line)
                    except ValueError:
                        continue  # torn line from a crash mid-append
                    if isinstance(record, dict):
                        records.append(record)
        except OSError:
            pass
        return records


class BrainOptimizer:
    """Cross-job heuristic: among past runs of this job name, prefer the
    worker count with the best observed speed-per-worker (cost-adjusted
    throughput)."""

    def __init__(self, store: BrainStore):
        self._store = store

    def optimize(self, job_name: str) -> Optional[Dict]:
        samples = []
        for s in self._store.load("runtime"):
            if s.get("job_name") != job_name:
                continue
            try:
                speed = float(s.get("speed", 0))
                count = int(s.get("worker_count", 0))
            except (TypeError, ValueError):
                continue  # records are caller-supplied; skip junk
            if speed > 0 and count > 0:
                samples.append((count, speed))
        if not samples:
            return None
        by_count: Dict[int, List[float]] = {}
        for count, speed in samples:
            by_count.setdefault(count, []).append(speed)
        best_count, best_value = 0, -1.0
        for count, speeds in by_count.items():
            if count <= 0:
                continue
            value = (sum(speeds) / len(speeds)) / count
            if value > best_value:
                best_count, best_value = count, value
        if best_count <= 0:
            return None
        return {"worker_count": best_count, "evidence_samples": len(samples)}


class BrainService:
    def __init__(self, port: int = 0, data_dir: str = "/tmp/dlrover_brain"):
        self.store = BrainStore(data_dir)
        self.optimizer = BrainOptimizer(self.store)
        self._server = ThreadingHTTPServer(
            ("0.0.0.0", port), self._make_handler()
        )
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def _make_handler(self):
        service = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _json(self, code: int, payload):
                data = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_POST(self):
                length = int(self.headers.get("Content-Length", "0"))
                try:
                    body = json.loads(self.rfile.read(length) or b"{}")
                except ValueError:
                    self._json(400, {"error": "bad json"})
                    return
                if self.path == "/persist_metrics":
                    kind = body.get("kind", "runtime")
                    if kind not in ("runtime", "completion"):
                        self._json(400, {"error": f"bad kind {kind}"})
                        return
                    service.store.append(kind, body.get("record", {}))
                    self._json(200, {"ok": True})
                elif self.path == "/optimize":
                    plan = service.optimizer.optimize(
                        body.get("job_name", "")
                    )
                    self._json(200, {"plan": plan})
                else:
                    self._json(404, {"error": "not found"})

        return Handler

    def start(self):
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="brain", daemon=True
        )
        self._thread.start()
        logger.info("brain service on port %d", self.port)

    def stop(self):
        if self._thread is not None:
            self._server.shutdown()
        self._server.server_close()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="dlrover-tpu brain")
    parser.add_argument("--port", type=int, default=8600)
    parser.add_argument("--data_dir", type=str, default="/tmp/dlrover_brain")
    args = parser.parse_args(argv)
    service = BrainService(args.port, args.data_dir)
    service.start()
    try:
        while True:
            time.sleep(60)
    except KeyboardInterrupt:
        service.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
