"""Master-side brain integration.

Parity: reference master/resource/brain_optimizer.py
(BrainResoureOptimizer) + master/stats BrainReporter — a StatsReporter
that forwards samples to the brain service, and a ResourceOptimizer that
asks it for cross-job-informed worker counts (falling back to an empty
plan when the brain is unreachable).
"""

import http.client
import json
from typing import Dict

from dlrover_tpu.common.constants import NodeType
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.node import NodeGroupResource
from dlrover_tpu.master.resource.optimizer import (
    ResourceOptimizer,
    ResourcePlan,
)
from dlrover_tpu.master.stats.job_collector import (
    JobCompletionRecord,
    RuntimeMetricSample,
    StatsReporter,
)


def _post(addr: str, path: str, payload: Dict, timeout: float = 5.0):
    host, port = addr.rsplit(":", 1)
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    try:
        body = json.dumps(payload)
        conn.request(
            "POST",
            path,
            body=body,
            headers={"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        data = resp.read()
        if resp.status != 200:
            return None
        return json.loads(data)
    finally:
        conn.close()


class BrainStatsReporter(StatsReporter):
    def __init__(self, brain_addr: str, job_name: str):
        self._addr = brain_addr
        self._job_name = job_name

    def report_runtime_sample(self, sample: RuntimeMetricSample):
        try:
            _post(
                self._addr,
                "/persist_metrics",
                {
                    "kind": "runtime",
                    "record": {
                        "job_name": self._job_name,
                        "global_step": sample.global_step,
                        "speed": sample.speed,
                        "goodput": sample.goodput,
                        "worker_count": sample.worker_count,
                    },
                },
            )
        except Exception:
            logger.warning("brain runtime report failed")

    def report_job_completion(self, record: JobCompletionRecord):
        try:
            _post(
                self._addr,
                "/persist_metrics",
                {
                    "kind": "completion",
                    "record": {
                        "job_name": record.job_name,
                        "success": record.success,
                        "exit_reason": record.exit_reason,
                        "duration_s": record.duration_s,
                        "failure_count": record.failure_count,
                    },
                },
            )
        except Exception:
            logger.warning("brain completion report failed")


class BrainResourceOptimizer(ResourceOptimizer):
    def __init__(self, brain_addr: str, job_name: str):
        self._addr = brain_addr
        self._job_name = job_name

    def generate_plan(self) -> ResourcePlan:
        plan = ResourcePlan()
        try:
            result = _post(
                self._addr, "/optimize", {"job_name": self._job_name}
            )
            suggestion = (result or {}).get("plan")
            if not isinstance(suggestion, dict):
                return plan
            count = int(suggestion.get("worker_count", 0))
        except Exception:
            # Unreachable brain or malformed response: degrade to no-op.
            logger.warning("brain optimize failed; no plan", exc_info=True)
            return plan
        if count > 0:
            plan.node_group_resources[NodeType.WORKER] = NodeGroupResource(
                count=count
            )
            plan.comment = (
                f"brain: {count} workers "
                f"({suggestion.get('evidence_samples')} samples)"
            )
        return plan
