"""Brain evaluators + optimize processor.

Parity: reference go/brain's processor/evaluator architecture
(docs/design/brain.md; go/brain/pkg — an OptimizeProcessor selects
algorithm plugins and JobEvaluators turn raw datastore metrics into
assessments that feed the final plan). Scaled to this repo's metric
vocabulary: evaluators read the BrainStore's runtime samples
({job_name, worker_count, speed, ...}) and completion records
({job_name, worker_count, success, exit_reason, ...}) and each returns
one assessment dict; the processor runs the configured set plus the
resource optimizer and assembles the OptimizeResponse.

Evaluators are pluggable exactly like optimizers: registry names or
``pkg.module:factory`` dotted paths (factory takes the store, returns
an object with ``evaluate(job_name) -> Optional[dict]``).
"""

from typing import Dict, List, Optional

from dlrover_tpu.common.log import logger


def _runtime_samples(store, job_name: str, runtime=None) -> List[Dict]:
    if runtime is None:
        runtime = store.load("runtime", job_name=job_name)
    out = []
    for s in runtime:
        try:
            if float(s.get("speed", 0)) > 0:
                out.append(s)
        except (TypeError, ValueError):
            continue
    return out


class ThroughputTrendEvaluator:
    """Is this job slowing down? Least-squares slope over the newest
    samples, normalized by the mean — a sustained negative trend is the
    degradation signal the reference's trend evaluators raise (node
    slowdowns, creeping stragglers, thermal throttling)."""

    name = "throughput_trend"

    def __init__(self, store, window: int = 20):
        self._store = store
        self._window = window

    def evaluate(self, job_name: str, runtime=None,
                 completions=None) -> Optional[Dict]:
        samples = _runtime_samples(self._store, job_name, runtime)
        speeds = [float(s["speed"]) for s in samples][-self._window:]
        if len(speeds) < 4:
            return None
        n = len(speeds)
        xs = range(n)
        mx, my = (n - 1) / 2.0, sum(speeds) / n
        cov = sum((x - mx) * (y - my) for x, y in zip(xs, speeds))
        var = sum((x - mx) ** 2 for x in xs)
        slope = cov / var if var else 0.0
        rel = slope / my if my else 0.0
        return {
            "evaluator": self.name,
            "samples": n,
            "relative_slope_per_sample": round(rel, 5),
            "degrading": rel < -0.01,
        }


class StragglerEvaluator:
    """Throughput dispersion at a fixed worker count: high variance
    between samples of the SAME configuration is the straggler/flaky-
    host signature (a healthy job's speed is stable)."""

    name = "straggler"

    def __init__(self, store, threshold: float = 0.15):
        self._store = store
        self._threshold = threshold

    def evaluate(self, job_name: str, runtime=None,
                 completions=None) -> Optional[Dict]:
        by_count: Dict[int, List[float]] = {}
        for s in _runtime_samples(self._store, job_name, runtime):
            try:
                by_count.setdefault(
                    int(s.get("worker_count", 0)), []
                ).append(float(s["speed"]))
            except (TypeError, ValueError):
                continue
        worst = 0.0
        for speeds in by_count.values():
            if len(speeds) < 3:
                continue
            mean = sum(speeds) / len(speeds)
            if mean <= 0:
                continue
            var = sum((x - mean) ** 2 for x in speeds) / len(speeds)
            worst = max(worst, (var ** 0.5) / mean)
        if worst == 0.0:
            return None
        return {
            "evaluator": self.name,
            "speed_cv": round(worst, 4),
            "suspected": worst > self._threshold,
        }


class OOMRiskEvaluator:
    """Fraction of this job's completions that died OOM; past the
    threshold the assessment carries the resource bump the reference's
    job optimizer would apply (the master's resource optimizer consumes
    the same signal locally — this is the cross-job memory of it)."""

    name = "oom_risk"

    def __init__(self, store, threshold: float = 0.2):
        self._store = store
        self._threshold = threshold

    def evaluate(self, job_name: str, runtime=None,
                 completions=None) -> Optional[Dict]:
        comps = (
            completions if completions is not None
            else self._store.load("completion", job_name=job_name)
        )
        if not comps:
            return None
        ooms = sum(
            1 for c in comps
            if str(c.get("exit_reason", "")).lower() == "oom"
        )
        frac = ooms / len(comps)
        out = {
            "evaluator": self.name,
            "completions": len(comps),
            "oom_fraction": round(frac, 4),
            "at_risk": frac >= self._threshold,
        }
        if out["at_risk"]:
            out["suggestion"] = "bump per-worker memory ~50% or escalate remat policy"  # noqa: E501
        return out


EVALUATORS = {
    "throughput_trend": ThroughputTrendEvaluator,
    "straggler": StragglerEvaluator,
    "oom_risk": OOMRiskEvaluator,
}


def load_plugin(name: str, registry: Dict, store, what: str):
    """Shared registry-or-dotted-path resolution for optimizer AND
    evaluator plugins (one contract: factory takes the store)."""
    if name in registry:
        return registry[name](store)
    if ":" in name:
        import importlib

        module, attr = name.split(":", 1)
        try:
            factory = getattr(importlib.import_module(module), attr)
        except (ImportError, AttributeError, ValueError) as e:
            raise ValueError(
                f"{what} plugin {name!r} failed to load ({e}); "
                f"expected pkg.module:factory or one of "
                f"{sorted(registry)}"
            ) from e
        return factory(store)
    raise ValueError(
        f"unknown {what} {name!r}; registry: {sorted(registry)} "
        f"or a pkg.module:factory path"
    )


def create_evaluator(name: str, store):
    return load_plugin(name, EVALUATORS, store, "evaluator")


class OptimizeProcessor:
    """The reference's processor: run the resource optimizer plus every
    configured evaluator and assemble one response. An evaluator
    failing must never take optimize() down with it."""

    @property
    def evaluator_names(self) -> List[str]:
        return [
            getattr(e, "name", type(e).__name__)
            for e, _ in self._evaluators
        ]

    def __init__(self, optimizer, evaluators, store=None):
        import inspect

        self._optimizer = optimizer
        self._store = store
        # Detect each evaluator's signature ONCE: a per-call
        # `except TypeError` would misread a genuine TypeError inside
        # an evaluator as a signature mismatch and run it twice.
        self._evaluators = []
        for ev in evaluators:
            try:
                params = inspect.signature(ev.evaluate).parameters
                wants_data = "runtime" in params or any(
                    p.kind == inspect.Parameter.VAR_KEYWORD
                    for p in params.values()
                )
            except (TypeError, ValueError):
                wants_data = False
            self._evaluators.append((ev, wants_data))

    def process(self, job_name: str) -> Dict:
        plan = None
        try:
            plan = self._optimizer.optimize(job_name)
        except Exception:  # noqa: BLE001 - degrade, don't 500
            logger.exception("optimizer failed for %s", job_name)
        # Prefetch ONCE: three evaluators each re-reading (and the
        # JSONL backend re-parsing) the whole store would triple the
        # request's load time and lock hold.
        runtime = completions = None
        if self._store is not None and self._evaluators:
            runtime = self._store.load("runtime", job_name=job_name)
            completions = self._store.load(
                "completion", job_name=job_name
            )
        assessments = []
        for ev, wants_data in self._evaluators:
            try:
                if wants_data:
                    a = ev.evaluate(
                        job_name, runtime=runtime,
                        completions=completions,
                    )
                else:  # external plugins may keep the simple signature
                    a = ev.evaluate(job_name)
            except Exception:  # noqa: BLE001
                logger.exception(
                    "evaluator %s failed for %s",
                    getattr(ev, "name", type(ev).__name__), job_name,
                )
                continue
            if a is not None:
                assessments.append(a)
        return {"plan": plan, "assessments": assessments}
