"""Agent-resident async checkpoint saver.

Parity: reference elastic_agent/torch/ckpt_saver.py (AsyncCheckpointSaver:
399, _sync_shm_to_storage:619, commit_checkpoint:1029, save-on-failure
:581). The saver lives in the AGENT process so a dying worker cannot take
the persistence thread with it; shm segments likewise outlive workers.

Commit protocol (crash-safe):
1. every node writes its proc files + a ``node-<rank>.done`` marker into
   the step dir (all writes are tmp+rename);
2. the leader node (lowest rank in the world) polls until every expected
   marker exists, then atomically replaces the tracker file and reports
   the committed step to the master;
3. a leader dying mid-commit is safe: markers persist, any relaunched
   leader re-runs step 2 idempotently; an uncommitted step dir is garbage-
   collected by the deletion strategy.
"""

import os
import threading
import time
from typing import Dict, List, Optional

from dlrover_tpu.common.constants import NodeEnv
from dlrover_tpu.common.log import logger
from dlrover_tpu.flash_ckpt import storage as ckpt_storage
from dlrover_tpu.flash_ckpt.engine import (
    CKPT_EVENT_QUEUE,
    CKPT_LOCK_PREFIX,
    SaveEvent,
    shm_segment_name,
)
from dlrover_tpu.flash_ckpt.shared_obj import (
    SharedDictServer,
    SharedLockServer,
    SharedQueueServer,
)
from dlrover_tpu.flash_ckpt.shm_handler import SharedMemoryHandler

_MAX_LOCAL_WORKERS = 16


def read_shm_payload(local_rank: int, lock=None):
    """Extract (step, proc_payload) from a local worker's shm image.

    Data is COPIED out while holding ``lock`` (the same SharedLock the
    worker's engine takes while writing), so a concurrent next-step save
    cannot tear the payload; the lock is released before any disk IO.

    Each shard is copied EXACTLY ONCE (shm view -> contiguous host
    array); the raw persist path then streams those bytes straight to
    disk, so the agent holds 1x the node's state in RAM — the old
    ``np.savez`` path copied every shard a second time into its zip
    container, peaking at 2x.
    """
    import numpy as np

    if lock is not None:
        lock.acquire()
    try:
        handler = SharedMemoryHandler(shm_segment_name(local_rank))
        meta = handler.load_meta()
        if meta is None:
            handler.close()
            return None
        from dlrover_tpu.flash_ckpt.shm_handler import _np_dtype

        buf = handler._shm.buf  # noqa: SLF001
        data_start = meta["data_start"]
        arrays = {}
        for leaf_meta in meta["leaves"]:
            dtype = _np_dtype(leaf_meta.dtype)
            for j, shard in enumerate(leaf_meta.shards):
                view = np.ndarray(
                    shard.local_shape,
                    dtype=dtype,
                    buffer=buf,
                    offset=data_start + shard.offset,
                )
                # the single copy out of shm (C-contiguous by layout)
                arrays[f"leaf{leaf_meta.leaf_id}_shard{j}"] = np.array(view)
        step = meta["step"]
        payload = {
            "arrays": arrays,
            "meta": {
                "treedef": meta["treedef"],
                "leaves": meta["leaves"],
                "user_meta": meta.get("user_meta", {}),
            },
        }
        handler.close()
        return step, payload
    finally:
        if lock is not None:
            lock.release()


def default_deletion_strategy(max_to_keep: int = 3):
    """Retention policy for committed checkpoints. Env-selectable:
    DLROVER_TPU_CKPT_KEEP_INTERVAL=N keeps every Nth step forever in
    addition to the newest max_to_keep (sparse history for rollback)."""
    from dlrover_tpu.common.env_utils import get_env_int

    interval = get_env_int("DLROVER_TPU_CKPT_KEEP_INTERVAL", 0)
    if interval > 0:
        return ckpt_storage.KeepStepIntervalDeletionStrategy(
            interval, max_to_keep
        )
    return ckpt_storage.KeepLatestDeletionStrategy(max_to_keep)


def persist_shm_to_storage(
    checkpoint_dir: str,
    step: int,
    node_rank: int,
    local_world_size: int,
    expected_nodes: List[int],
    master_client=None,
    commit_timeout: float = 600.0,
    max_to_keep: int = 3,
    locks: Optional[list] = None,
    deletion_strategy=None,
) -> bool:
    """Persist this node's shm images for ``step`` and run the commit.

    Aborts (returns False) if local ranks hold images of different steps —
    a step dir must never mix shards from different training steps.
    """
    proc_payloads: Dict[int, dict] = {}
    common_step: Optional[int] = None
    for local_rank in range(local_world_size):
        lock = locks[local_rank] if locks else None
        result = read_shm_payload(local_rank, lock)
        if result is None:
            logger.warning(
                "no shm image for local rank %d; aborting persist",
                local_rank,
            )
            return False
        shm_step, payload = result
        if common_step is None:
            common_step = shm_step
        elif shm_step != common_step:
            logger.error(
                "local ranks hold mixed steps (%d vs %d); aborting persist "
                "to avoid committing an inconsistent checkpoint",
                common_step,
                shm_step,
            )
            return False
        process_id = payload["meta"]["user_meta"].get(
            "process_id", local_rank
        )
        proc_payloads[process_id] = payload
    if common_step != step:
        logger.warning(
            "shm images hold step %d (requested %d); persisting step %d",
            common_step,
            step,
            common_step,
        )
        step = common_step
    ckpt_storage.persist_node_shards(
        checkpoint_dir, step, node_rank, proc_payloads
    )

    # Commit (leader only).
    leader = min(expected_nodes) if expected_nodes else node_rank
    if node_rank != leader:
        if master_client is not None:
            try:
                master_client.report_ckpt_step(step, committed=False)
            except Exception:
                pass
        return True
    deadline = time.time() + commit_timeout
    while time.time() < deadline:
        done = ckpt_storage.nodes_done(checkpoint_dir, step)
        if set(done) >= set(expected_nodes):
            ckpt_storage.write_tracker(checkpoint_dir, step)
            strategy = deletion_strategy or default_deletion_strategy(
                max_to_keep
            )
            strategy.clean_up(checkpoint_dir)
            if master_client is not None:
                try:
                    master_client.report_ckpt_step(step, committed=True)
                except Exception:
                    pass
            logger.info("checkpoint step %d committed", step)
            return True
        time.sleep(0.5)
    logger.error("commit of step %d timed out waiting for %s", step,
                 expected_nodes)
    return False


class AsyncCheckpointSaver:
    """Hosted by the agent; singleton per agent process."""

    _instance: Optional["AsyncCheckpointSaver"] = None
    _lock = threading.Lock()

    def __init__(
        self,
        client=None,
        local_world_size: int = _MAX_LOCAL_WORKERS,
        replica_manager=None,
    ):
        self._client = client
        self._replica_manager = replica_manager
        self._last_replica_step = -1
        self._replica_inflight = threading.Event()
        self._node_rank = int(os.getenv(NodeEnv.NODE_RANK, "0"))
        self._event_queue = SharedQueueServer(CKPT_EVENT_QUEUE)
        self._locks = [
            SharedLockServer(f"{CKPT_LOCK_PREFIX}_{r}")
            for r in range(local_world_size)
        ]
        self._conf_dict = SharedDictServer("ckpt_conf")
        self._world_nodes: List[int] = [self._node_rank]
        self._latest_mem_event: Optional[SaveEvent] = None
        self._last_persisted_step = -1
        self._stopped = threading.Event()
        self._thread = threading.Thread(
            target=self._saver_loop, daemon=True, name="ckpt-saver"
        )
        self._thread.start()

    # ---- agent wiring ------------------------------------------------------

    @classmethod
    def start_async_saving_ckpt(
        cls, client=None, replica_manager=None
    ) -> "AsyncCheckpointSaver":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls(
                    client=client, replica_manager=replica_manager
                )
            return cls._instance

    @classmethod
    def reset(cls):
        with cls._lock:
            if cls._instance is not None:
                cls._instance.stop()
                cls._instance = None

    def set_world(self, world: Dict[int, int]):
        """Called by the agent after each rendezvous round."""
        self._world_nodes = sorted(world) if world else [self._node_rank]

    # ---- saver loop --------------------------------------------------------

    def _saver_loop(self):
        while not self._stopped.is_set():
            try:
                event = self._event_queue.get(timeout=1.0)
            except Exception:
                continue
            try:
                self._handle_event(event)
            except Exception:
                logger.exception("checkpoint event handling failed")

    def _handle_event(self, event: SaveEvent):
        if event.kind == SaveEvent.SAVE_MEM:
            self._latest_mem_event = event
            self._push_replicas(event)
            return
        if event.kind == SaveEvent.SAVE_DISK:
            from dlrover_tpu.training_event import TrainerEvents

            self._latest_mem_event = event
            with TrainerEvents.ckpt_persist(event.step) as span:
                ok = persist_shm_to_storage(
                    event.checkpoint_dir,
                    event.step,
                    self._node_rank,
                    event.local_world_size,
                    self._world_nodes,
                    master_client=self._client,
                    locks=self._locks,
                )
                span.content["committed"] = ok
            if ok:
                self._last_persisted_step = event.step
            self._push_replicas(event)

    def _push_replicas(self, event: SaveEvent):
        """Replicate this node's shm image to group peers, in the
        background: uploads of multi-GB images must not stall the saver
        event loop (breakpoint-save freshness) or, worse, the workers."""
        if self._replica_manager is None:
            return
        if event.step <= self._last_replica_step:
            return  # save_to_storage emits SAVE_MEM then SAVE_DISK
        if self._replica_inflight.is_set():
            logger.info(
                "replica push still running; skipping step %d", event.step
            )
            return
        self._last_replica_step = event.step
        self._replica_inflight.set()

        def push():
            try:
                self._replica_manager.set_world(self._world_nodes)
                n = self._replica_manager.push_node_image(
                    event.local_world_size, locks=self._locks
                )
                if n:
                    logger.info(
                        "pushed %d shm segment replicas for step %d",
                        n,
                        event.step,
                    )
            except Exception:
                logger.exception("replica push failed")
            finally:
                self._replica_inflight.clear()

        threading.Thread(
            target=push, name="ckpt-replica-push", daemon=True
        ).start()

    # ---- failure path ------------------------------------------------------

    def save_shm_on_failure(self):
        """Breakpoint save: persist the newest shm image before restart.

        Parity: reference _save_shm_before_exiting / agent
        _save_ckpt_to_storage (training.py:1533)."""
        event = self._latest_mem_event
        if event is None:
            return
        newest = -1
        for r in range(event.local_world_size):
            h = SharedMemoryHandler(shm_segment_name(r))
            newest = max(newest, h.get_step())
            h.close()
        if newest <= self._last_persisted_step or newest < 0:
            return
        tracker = ckpt_storage.read_tracker(event.checkpoint_dir)
        if newest <= tracker:
            return
        logger.info("breakpoint-saving shm step %d to storage", newest)
        ok = persist_shm_to_storage(
            event.checkpoint_dir,
            newest,
            self._node_rank,
            event.local_world_size,
            # A failure save must not block on dead peers: commit with
            # whatever nodes finish; the tracker only advances if all
            # expected markers appear, so use just this node when alone.
            self._world_nodes,
            master_client=self._client,
            commit_timeout=60.0,
            locks=self._locks,
        )
        if ok:
            self._last_persisted_step = newest

    # ---- cleanup -----------------------------------------------------------

    def unlink_all(self, local_world_size: int = _MAX_LOCAL_WORKERS):
        for r in range(local_world_size):
            SharedMemoryHandler(shm_segment_name(r)).unlink()

    def stop(self):
        self._stopped.set()
        self._event_queue.stop()
        for lock in self._locks:
            lock.stop()
        self._conf_dict.stop()
