"""User-facing flash checkpoint API.

Parity: reference trainer/torch/flash_checkpoint/ddp.py (DdpCheckpointer)
/ fsdp.py — collapsed into ONE checkpointer because JAX shardings are
uniform: the same engine handles replicated (DP), per-host sharded
(FSDP-style) and TP/PP-partitioned pytrees; the shard metadata captured at
save time drives any restore.

Usage:
    ckpt = Checkpointer("/tmp/ckpt")
    ckpt.save_checkpoint(step, state)                       # memory only
    ckpt.save_checkpoint(step, state, StorageType.DISK)     # + async disk
    restored = ckpt.load_checkpoint(sharding_tree=shardings)
"""

import os
from typing import Any, Optional

from dlrover_tpu.flash_ckpt.engine import CheckpointEngine, to_device_state
from dlrover_tpu.flash_ckpt.shared_obj import socket_path


class StorageType:
    MEMORY = "memory"
    DISK = "disk"


def _agent_present() -> bool:
    from dlrover_tpu.flash_ckpt.engine import CKPT_EVENT_QUEUE

    return os.path.exists(socket_path(f"queue-{CKPT_EVENT_QUEUE}"))


class Checkpointer:
    def __init__(
        self,
        checkpoint_dir: str,
        standalone: Optional[bool] = None,
    ):
        if standalone is None:
            standalone = not _agent_present()
        self._engine = CheckpointEngine(checkpoint_dir, standalone=standalone)

    def save_checkpoint(
        self,
        step: int,
        state: Any,
        storage_type: str = StorageType.MEMORY,
        user_meta: Optional[dict] = None,
    ) -> float:
        """Returns the training-blocking seconds of the save."""
        if storage_type == StorageType.DISK:
            return self._engine.save_to_storage(step, state, user_meta)
        return self._engine.save_to_memory(step, state, user_meta)

    def save_checkpoint_async(
        self,
        step: int,
        state: Any,
        user_meta: Optional[dict] = None,
    ) -> float:
        """Launch the device->host DMA and return immediately (~ms).

        The TPU hot path: the transfer overlaps the next training steps
        and a writer thread lands it in shm. The caller must not donate
        ``state`` to later steps (keep ``donate=False`` on the jitted
        step). Use ``wait_async_save`` before relying on the snapshot.
        """
        return self._engine.save_to_memory_async(step, state, user_meta)

    def wait_async_save(self, timeout: float = 600.0) -> bool:
        return self._engine.wait_async_save(timeout)

    def load_checkpoint(
        self,
        step: Optional[int] = None,
        sharding_tree: Any = None,
        to_device: bool = True,
    ):
        """Return (step, state, user_meta) or None.

        With ``sharding_tree`` the restored arrays are placed under the
        current mesh (resharding restore); otherwise numpy arrays are
        returned (to_device=False) or default-placed jax arrays.

        When the restore comes from storage, passing ``sharding_tree``
        activates the sharding-aware partial restore: each process reads
        only its addressable byte ranges from the mmap'd shard files and
        host RAM stays O(local bytes) — see docs/DESIGN.md §23.
        """
        result = self._engine.load(
            step, sharding_tree=sharding_tree if to_device else None
        )
        if result is None:
            return None
        found_step, np_state, meta = result
        if not to_device:
            return found_step, np_state, meta
        return found_step, to_device_state(np_state, sharding_tree), meta

    def latest_step(self) -> int:
        return self._engine.latest_step()

    def wait_saving_complete(self, timeout: float = 600.0) -> bool:
        """Block until the engine's last requested DISK save is committed.
        Memory-only saves are not waited on (they have no storage step)."""
        import time

        from dlrover_tpu.flash_ckpt import storage as ckpt_storage

        deadline = time.time() + timeout
        target = self._engine._last_disk_step  # noqa: SLF001
        if target < 0:
            return True
        while time.time() < deadline:
            if ckpt_storage.read_tracker(self._engine.checkpoint_dir) >= target:
                return True
            time.sleep(0.2)
        return False

    def close(self):
        self._engine.close()
