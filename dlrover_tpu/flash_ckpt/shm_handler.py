"""POSIX shared-memory image of a JAX pytree checkpoint.

Parity: reference elastic_agent/torch/ckpt_saver.py:234-398
(SharedMemoryHandler: state dict -> TensorMeta offsets -> memcpy into shm).
JAX re-design: each worker process writes its *addressable shards* of every
leaf (``jax.Array.addressable_shards``) plus global shape/dtype/index
metadata, so the image is mesh-aware: a restarted world with a different
sharding can reassemble any leaf from shard indices (the reference needs
DeepSpeed "universal checkpoint" conversion for this; here it is free).

Layout (self-contained, parseable by any process that attaches):

    [8B magic "DLRTPUC2"][8B meta_len][8B step][pickled meta][padding]
    [leaf shard data...]

Meta: {"step", "user_meta", "treedef" (pickled pytree structure),
"leaves": [LeafMeta], "data_start"}. The step is duplicated in the
fixed header so :meth:`SharedMemoryHandler.get_step` — polled at 20Hz
per sibling by the engine's persist barrier — is a 24-byte read, not a
full meta unpickle. v1 segments ("DLRTPUC1", no step field) are still
readable.
"""

import pickle
import threading
from dataclasses import dataclass, field
from multiprocessing import shared_memory
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from dlrover_tpu.common.log import logger
from dlrover_tpu.common.serialize import loads_pytree

MAGIC = b"DLRTPUC2"
MAGIC_V1 = b"DLRTPUC1"  # pre-step-field layout: meta starts at byte 16
_HDR = 24  # magic + meta_len + step
_ALIGN = 128


def _untrack_shm(shm: shared_memory.SharedMemory):
    """Detach the segment from multiprocessing's resource tracker.

    The checkpoint image MUST outlive the worker process that wrote it —
    that is the whole point of flash checkpoint (a SIGKILLed worker's
    state survives in host memory). Python's resource tracker would
    unlink the segment when the creating process exits cleanly; the agent
    owns cleanup instead (AsyncCheckpointSaver.unlink_all).
    """
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister(shm._name, "shared_memory")  # noqa: SLF001
    except Exception:
        pass


def _dtype_to_str(dtype) -> str:
    return np.dtype(dtype).name if np.dtype(dtype).name != "void" else str(dtype)


def _np_dtype(name: str):
    if name in ("bfloat16", "float8_e4m3fn", "float8_e5m2", "float8_e4m3b11fnuz"):
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))
    return np.dtype(name)


@dataclass
class ShardMeta:
    """One addressable shard of one leaf."""

    index: Tuple[Tuple[Optional[int], Optional[int]], ...]  # slice bounds
    local_shape: Tuple[int, ...]
    offset: int = 0
    nbytes: int = 0


@dataclass
class LeafMeta:
    leaf_id: int
    global_shape: Tuple[int, ...]
    dtype: str
    shards: List[ShardMeta] = field(default_factory=list)
    replicated: bool = False  # every process holds the full leaf


def _index_to_bounds(index) -> Tuple[Tuple[Optional[int], Optional[int]], ...]:
    """Convert a tuple of slices (jax shard .index) to picklable bounds."""
    return tuple((s.start, s.stop) for s in index)


def bounds_to_slices(bounds) -> Tuple[slice, ...]:
    return tuple(slice(b[0], b[1]) for b in bounds)


def extract_leaf_arrays(leaf) -> Tuple[LeafMeta, List[np.ndarray]]:
    """Pull the process-local data of a leaf (jax.Array or np/scalar)."""
    import jax

    if isinstance(leaf, jax.Array):
        global_shape = tuple(leaf.shape)
        dtype = _dtype_to_str(leaf.dtype)
        shards: List[ShardMeta] = []
        arrays: List[np.ndarray] = []
        if leaf.is_fully_replicated:
            arr = np.asarray(jax.device_get(leaf))
            bounds = tuple((0, s) for s in global_shape)
            shards.append(ShardMeta(bounds, tuple(arr.shape)))
            arrays.append(arr)
            meta = LeafMeta(-1, global_shape, dtype, shards, replicated=True)
            return meta, arrays
        seen_indices = set()
        for shard in leaf.addressable_shards:
            bounds = _index_to_bounds(shard.index)
            if bounds in seen_indices:
                continue  # replica of a shard we already captured
            seen_indices.add(bounds)
            arr = np.asarray(shard.data)
            shards.append(ShardMeta(bounds, tuple(arr.shape)))
            arrays.append(arr)
        meta = LeafMeta(-1, global_shape, dtype, shards, replicated=False)
        return meta, arrays
    # numpy / python scalar leaf: fully local
    arr = np.asarray(leaf)
    bounds = tuple((0, s) for s in arr.shape)
    meta = LeafMeta(
        -1,
        tuple(arr.shape),
        _dtype_to_str(arr.dtype),
        [ShardMeta(bounds, tuple(arr.shape))],
        replicated=True,
    )
    return meta, [arr]


class SharedMemoryHandler:
    """Owns one named shm segment holding the latest checkpoint image."""

    def __init__(self, name: str, create: bool = False):
        self._name = name.replace("/", "_")
        self._create = create
        self._shm: Optional[shared_memory.SharedMemory] = None
        self._lock = threading.Lock()

    @property
    def name(self) -> str:
        return self._name

    # ---- plumbing ----------------------------------------------------------

    def _ensure_shm(self, size: int):
        if self._shm is None:
            # A restarted worker reuses the segment its predecessor left.
            self.attach()
        if self._shm is not None and self._shm.size >= size:
            return
        if self._shm is not None:
            self._shm.close()
            try:
                self._shm.unlink()
            except FileNotFoundError:
                pass
            self._shm = None
        # Grow with headroom so steady-state saves never reallocate.
        alloc = max(int(size * 1.2), 1 << 20)
        self._shm = shared_memory.SharedMemory(
            name=self._name, create=True, size=alloc
        )
        _untrack_shm(self._shm)
        logger.info("created shm %s (%d MB)", self._name, alloc >> 20)

    def attach(self) -> bool:
        """Attach to an existing segment (agent side / restarted worker)."""
        if self._shm is not None:
            return True
        try:
            # Attaching (create=False) does not register with the resource
            # tracker on CPython 3.12, so no untrack is needed here.
            self._shm = shared_memory.SharedMemory(name=self._name)
            return True
        except FileNotFoundError:
            return False

    def exists(self) -> bool:
        if self._shm is not None:
            return True
        ok = self.attach()
        return ok

    # ---- save --------------------------------------------------------------

    def save_state_dict(
        self,
        step: int,
        state: Any,
        user_meta: Optional[Dict[str, Any]] = None,
    ) -> float:
        """Write the pytree image; returns bytes written.

        The caller is responsible for synchronizing device work
        (``jax.block_until_ready``) before invoking.
        """
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(state)
        leaf_metas: List[LeafMeta] = []
        leaf_arrays: List[List[np.ndarray]] = []
        for i, leaf in enumerate(leaves):
            meta, arrays = extract_leaf_arrays(leaf)
            meta.leaf_id = i
            leaf_metas.append(meta)
            leaf_arrays.append(arrays)

        # lay out offsets
        offset = 0
        for meta, arrays in zip(leaf_metas, leaf_arrays):
            for shard_meta, arr in zip(meta.shards, arrays):
                shard_meta.nbytes = arr.nbytes
                shard_meta.offset = offset
                offset += arr.nbytes
                offset = (offset + _ALIGN - 1) // _ALIGN * _ALIGN
        data_bytes = offset

        meta_obj = {
            "step": step,
            "user_meta": user_meta or {},
            "treedef": pickle.dumps(treedef),
            "leaves": leaf_metas,
        }
        meta_payload = pickle.dumps(meta_obj)
        # Reserve generous meta space so minor growth doesn't re-layout.
        meta_space = (len(meta_payload) + 4096 + _ALIGN - 1) // _ALIGN * _ALIGN
        data_start = _HDR + meta_space
        total = data_start + data_bytes

        with self._lock:
            self._ensure_shm(total)
            buf = self._shm.buf
            # Invalidate while writing: zero magic first.
            buf[:8] = b"\x00" * 8
            meta_obj["data_start"] = data_start
            meta_payload = pickle.dumps(meta_obj)
            buf[8:16] = len(meta_payload).to_bytes(8, "big")
            # Step in the fixed header: get_step() must not unpickle.
            buf[16:_HDR] = int(step).to_bytes(
                8, "big", signed=True
            )
            buf[_HDR : _HDR + len(meta_payload)] = meta_payload
            for meta, arrays in zip(leaf_metas, leaf_arrays):
                for shard_meta, arr in zip(meta.shards, arrays):
                    start = data_start + shard_meta.offset
                    view = np.ndarray(
                        arr.shape,
                        dtype=arr.dtype,
                        buffer=buf,
                        offset=start,
                    )
                    np.copyto(view, arr)
            buf[:8] = MAGIC  # commit
        return float(total)

    # ---- load --------------------------------------------------------------

    def load_meta(self) -> Optional[dict]:
        if not self.attach():
            return None
        buf = self._shm.buf
        magic = bytes(buf[:8])
        if magic == MAGIC:
            meta_at = _HDR
        elif magic == MAGIC_V1:
            meta_at = 16  # image from a pre-step-field build
        else:
            return None
        meta_len = int.from_bytes(bytes(buf[8:16]), "big")
        # Restricted unpickle: shm bytes can arrive over the replica
        # service, so metadata must never be a code-execution vector.
        return loads_pytree(bytes(buf[meta_at : meta_at + meta_len]))

    def load_state_dict(self) -> Optional[Tuple[int, Any, dict]]:
        """Return (step, pytree-of-numpy, user_meta); leaves are copies.

        Sharded leaves come back as dicts {"__shards__": [...], meta} for
        the engine to reassemble into jax Arrays under the current mesh.
        """
        meta = self.load_meta()
        if meta is None:
            return None
        import jax

        buf = self._shm.buf
        data_start = meta["data_start"]
        treedef = loads_pytree(meta["treedef"])
        leaves = []
        for leaf_meta in meta["leaves"]:
            dtype = _np_dtype(leaf_meta.dtype)
            shard_arrays = []
            for shard in leaf_meta.shards:
                view = np.ndarray(
                    shard.local_shape,
                    dtype=dtype,
                    buffer=buf,
                    offset=data_start + shard.offset,
                )
                shard_arrays.append(np.array(view))  # copy out of shm
            if leaf_meta.replicated:
                leaves.append(shard_arrays[0])
            else:
                leaves.append(
                    {
                        "__shards__": [
                            (shard.index, arr)
                            for shard, arr in zip(
                                leaf_meta.shards, shard_arrays
                            )
                        ],
                        "__global_shape__": leaf_meta.global_shape,
                        "__dtype__": leaf_meta.dtype,
                    }
                )
        state = jax.tree_util.tree_unflatten(treedef, leaves)
        return meta["step"], state, meta.get("user_meta", {})

    def get_step(self) -> int:
        """Step of the current image, or -1. Fast path: a 24-byte header
        read — this is polled by persist barriers, so it must not pay a
        full meta unpickle per call."""
        if not self.attach():
            return -1
        buf = self._shm.buf
        magic = bytes(buf[:8])
        if magic == MAGIC:
            return int.from_bytes(bytes(buf[16:_HDR]), "big", signed=True)
        if magic == MAGIC_V1:
            meta = self.load_meta()
            return -1 if meta is None else meta["step"]
        return -1

    # ---- cleanup -----------------------------------------------------------

    def close(self):
        with self._lock:
            if self._shm is not None:
                self._shm.close()
                self._shm = None

    def unlink(self):
        with self._lock:
            if self._shm is None:
                try:
                    self._shm = shared_memory.SharedMemory(name=self._name)
                except FileNotFoundError:
                    return
            try:
                # Balance the earlier unregister: SharedMemory.unlink()
                # sends its own UNREGISTER to the tracker.
                from multiprocessing import resource_tracker

                resource_tracker.register(
                    self._shm._name, "shared_memory"  # noqa: SLF001
                )
            except Exception:
                pass
            try:
                self._shm.unlink()
            except FileNotFoundError:
                pass
            self._shm.close()
            self._shm = None
