"""Flash checkpoint: JAX pytrees -> host shared memory in O(100ms), with
asynchronous persistence, memory-first resume, and resharding restore.

Parity: reference trainer/torch/flash_checkpoint/* +
elastic_agent/torch/ckpt_saver.py, re-designed for JAX (SURVEY.md section 7):
- the trainer writes device arrays into POSIX shared memory via
  ``jax.device_get`` into preallocated buffers;
- the agent process persists shm -> storage off the training critical path;
- restore prefers shm (same-host restart) and falls back to storage;
- the "universal checkpoint" re-parallelization of the reference collapses
  to metadata: global shape + sharding per leaf lets any new mesh load via
  ``jax.make_array_from_process_local_data``.
"""

from dlrover_tpu.flash_ckpt.checkpointer import (  # noqa: F401
    Checkpointer,
    StorageType,
)
