"""Raw, mmap-able on-disk shard format for flash checkpoints (v1).

Replaces the ``proc-<pid>.npz`` zip container on the persist/restore hot
path. The zip path inflates every shard through a decompressor buffer and
forces the reader to materialize whole arrays; this format lays shards out
as page-aligned raw bytes behind a JSON index, so restore can ``np.memmap``
the file and read **only the byte ranges a process actually needs**
(sharding-aware partial restore), and persist streams each shard to disk
with exactly one copy.

Layout of ``proc-<pid>.raw``::

    [8B magic "DLRTPUS1"][8B header_len big-endian][4B header adler32]
    [JSON header][zero padding to data_start (page aligned)]
    [shard bytes, each shard offset page-aligned]

The 4-byte adler32 of the JSON payload guards the INDEX itself: shard
checksums are useless if a corrupted-but-parseable header misdirects
the reads (a flipped digit in an ``offset`` field would send partial
reads — which verify nothing by design — into another shard's bytes).

Header (pure JSON — no pickle on the index path)::

    {
      "version": 1,
      "step": <int>,
      "process_id": <int>,
      "data_start": <int>,
      "shards": [
        {"key": "leaf3_shard0", "leaf_id": 3, "shard_id": 0,
         "dtype": "float32", "local_shape": [8, 4],
         "bounds": [[0, 8], [null, null]],
         "offset": 0, "nbytes": 128,
         "adler32": 123456, "sum64": 7890}, ...
      ]
    }

``bounds`` are the global slice bounds of the shard (``null`` = open end,
matching ``ShardMeta.index``). Two checksums per shard, both computed
during the streaming write: ``adler32`` (zlib) is the strong check used
by :meth:`RawShardReader.get` / ``verify_all`` and external tooling;
``sum64`` (a ZFS-fletcher-style uint64 word sum, :func:`_sum64`) is
what the RESTORE hot path verifies on full-shard reads — it runs at
SIMD memory bandwidth instead of adler's ~1 GB/s and still catches
every single-event corruption (bitflip, byte change, zeroed range).
Partial range reads verify nothing (they deliberately do not touch
every page) and are documented as such. Truncated files are rejected at
open: the header records exactly how many bytes the data region must
span.

Compat policy: readers must keep accepting every on-disk version they
ever shipped; ``VERSION`` only bumps on layout changes. Old ``.npz``
step dirs remain restorable through ``storage.open_proc_shards``'s
fallback reader, and deleting that fallback requires a major release.
"""

import json
import os
import threading
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from dlrover_tpu.common.log import logger

MAGIC = b"DLRTPUS1"
VERSION = 1
_PREFIX = 20  # magic + header_len + header adler32
PAGE = 4096  # shard offsets are page-aligned so mmap slices hit whole pages
_WRITE_CHUNK = 16 << 20  # stream writes in 16MB chunks (GIL-releasing I/O)

RAW_SUFFIX = ".raw"


class ShardCorruptionError(Exception):
    """A shard file is torn, truncated, or fails its checksum."""


def shard_key(leaf_id: int, shard_id: int) -> str:
    return f"leaf{leaf_id}_shard{shard_id}"


def _dtype_name(dtype) -> str:
    # bfloat16 / float8 round-trip through ml_dtypes by name (the same
    # convention the shm image uses; see shm_handler._np_dtype).
    from dlrover_tpu.flash_ckpt.shm_handler import _dtype_to_str

    return _dtype_to_str(dtype)


def _np_dtype(name: str):
    from dlrover_tpu.flash_ckpt.shm_handler import _np_dtype as _f

    return _f(name)


def _align(n: int, a: int = PAGE) -> int:
    return (n + a - 1) // a * a


def _as_bytes(arr: np.ndarray) -> np.ndarray:
    """Flat uint8 view of a C-contiguous array. memoryview(...).cast("B")
    raises on ml_dtypes (bfloat16/float8) and on zero-size or 0-d
    shapes; a reshape+view is dtype-agnostic and zero-copy."""
    if arr.nbytes == 0:
        return np.empty(0, np.uint8)
    return arr.reshape(-1).view(np.uint8)


_U64_MOD = 1 << 64


def _sum64(chunk: np.ndarray, acc: int = 0) -> int:
    """Running word-sum checksum over uint8 ``chunk`` (ZFS-fletcher-style
    speed/strength tradeoff: SIMD memory-bandwidth fast, catches every
    single-event corruption — any lone bitflip, byte change, or zeroed
    range shifts the sum — while compensating multi-word corruptions
    can escape it; the full adler32 stays in the header for the strong
    path). Chunking-invariant as long as every chunk but the last is a
    multiple of 8 bytes."""
    n8 = chunk.nbytes // 8 * 8
    if n8:
        acc += int(
            np.add.reduce(chunk[:n8].view(np.uint64), dtype=np.uint64)
        )
    tail = chunk[n8:]
    if tail.nbytes:
        acc += int(tail.astype(np.uint64).sum())
    return acc % _U64_MOD


def _json_bounds(bounds) -> Optional[List[List[Optional[int]]]]:
    if bounds is None:
        return None
    return [[b[0], b[1]] for b in bounds]


def _tuple_bounds(bounds):
    if bounds is None:
        return None
    return tuple((b[0], b[1]) for b in bounds)


def write_raw_shards(
    path: str,
    step: int,
    process_id: int,
    arrays: Dict[str, np.ndarray],
    shard_bounds: Optional[Dict[str, tuple]] = None,
    fsync: bool = True,
) -> int:
    """Write ``arrays`` as a v1 raw shard file at ``path``; returns bytes.

    The caller owns atomicity (write to a tmp name, then rename). One
    fsync per file at the end — not one per shard.
    """
    shard_bounds = shard_bounds or {}
    entries = []
    offset = 0
    contiguous: Dict[str, np.ndarray] = {}
    for key in sorted(arrays):
        arr = np.asarray(arrays[key])
        if not arr.flags.c_contiguous:
            # ascontiguousarray promotes 0-d to (1,); restore the shape.
            arr = np.ascontiguousarray(arr).reshape(arr.shape)
        contiguous[key] = arr
        leaf_id = shard_id = -1
        try:
            body = key.split("leaf", 1)[1]
            leaf_s, shard_s = body.split("_shard", 1)
            leaf_id, shard_id = int(leaf_s), int(shard_s)
        except (IndexError, ValueError):
            pass
        entries.append(
            {
                "key": key,
                "leaf_id": leaf_id,
                "shard_id": shard_id,
                "dtype": _dtype_name(arr.dtype),
                "local_shape": list(arr.shape),
                "bounds": _json_bounds(shard_bounds.get(key)),
                "offset": offset,
                "nbytes": int(arr.nbytes),
                # Placeholders at max width: checksums are computed
                # DURING the single streaming write pass and patched
                # afterwards; real values are never longer, so the final
                # header always fits the reserved region.
                "adler32": 0xFFFFFFFF,
                "sum64": _U64_MOD - 1,
            }
        )
        offset = _align(offset + arr.nbytes)
    data_bytes = offset

    header = {
        "version": VERSION,
        "step": int(step),
        "process_id": int(process_id),
        "data_start": 0,  # patched after sizing
        "shards": entries,
    }
    payload = json.dumps(header).encode("utf-8")
    # data_start shifts the JSON length by at most a few digits; give the
    # header its own page multiple and re-encode once.
    data_start = _align(_PREFIX + len(payload) + 32)
    header["data_start"] = data_start

    with open(path, "wb") as f:
        f.write(b"\x00" * _PREFIX)  # prefix lands last (commit ordering)
        f.seek(data_start)
        pos = 0
        for entry in entries:
            if entry["offset"] > pos:
                f.write(b"\x00" * (entry["offset"] - pos))
                pos = entry["offset"]
            flat = _as_bytes(contiguous[entry["key"]])
            csum = 1  # adler32 seed
            wsum = 0
            for lo in range(0, flat.nbytes, _WRITE_CHUNK):
                chunk = flat[lo : lo + _WRITE_CHUNK]
                csum = zlib.adler32(chunk, csum)
                wsum = _sum64(chunk, wsum)
                f.write(chunk)
            entry["adler32"] = csum
            entry["sum64"] = wsum
            pos += flat.nbytes
        if pos < data_bytes:
            f.write(b"\x00" * (data_bytes - pos))
        payload = json.dumps(header).encode("utf-8")
        assert _PREFIX + len(payload) <= data_start
        f.seek(_PREFIX)
        f.write(payload)
        f.write(b"\x00" * (data_start - _PREFIX - len(payload)))
        f.seek(0)
        f.write(MAGIC)
        f.write(len(payload).to_bytes(8, "big"))
        f.write(zlib.adler32(payload).to_bytes(4, "big"))
        f.flush()
        if fsync:
            os.fsync(f.fileno())
    return data_start + data_bytes


class RawShardReader:
    """Zero-copy reader over one ``proc-<pid>.raw`` file.

    ``get`` returns a verified copy; ``view`` returns an mmap-backed
    array (valid until :meth:`close`); ``read_slice`` copies only the
    requested sub-range — the partial-restore primitive. Use as a
    context manager so the mmap is closed deterministically.
    """

    @staticmethod
    def _contig_span(shape, slices, itemsize):
        """(byte_offset, byte_len) within the shard if ``slices`` select
        a contiguous span — the whole shard, or a leading-axis range
        with every later axis full — else None."""
        if not shape:
            return 0, itemsize  # scalar shard
        norm = [
            (s.start or 0, s.stop if s.stop is not None else d)
            for s, d in zip(slices or (), shape)
        ]
        norm += [(0, d) for d in shape[len(norm):]]
        partial = [
            i for i, (b, d) in enumerate(zip(norm, shape))
            if b != (0, d)
        ]
        if partial not in ([], [0]):
            return None
        row = itemsize
        for d in shape[1:]:
            row *= d
        lo0, hi0 = norm[0]
        return lo0 * row, (hi0 - lo0) * row

    def __init__(self, path: str):
        self.path = path
        with open(path, "rb") as f:
            head = f.read(_PREFIX)
            if len(head) < _PREFIX or head[:8] != MAGIC:
                raise ShardCorruptionError(
                    f"{path}: bad magic (torn or not a raw shard file)"
                )
            header_len = int.from_bytes(head[8:16], "big")
            header_sum = int.from_bytes(head[16:_PREFIX], "big")
            payload = f.read(header_len)
            if len(payload) < header_len:
                raise ShardCorruptionError(f"{path}: truncated header")
            if zlib.adler32(payload) != header_sum:
                # The index tells every read where to look; corruption
                # here would misdirect the (unverified-by-design)
                # partial-range reads, so it must die at open.
                raise ShardCorruptionError(
                    f"{path}: header checksum mismatch"
                )
            try:
                header = json.loads(payload.decode("utf-8"))
            except (UnicodeDecodeError, json.JSONDecodeError) as e:
                raise ShardCorruptionError(
                    f"{path}: unparseable header ({e})"
                ) from e
        if header.get("version") != VERSION:
            raise ShardCorruptionError(
                f"{path}: unsupported raw format version "
                f"{header.get('version')!r}"
            )
        self.step = int(header["step"])
        self.process_id = int(header["process_id"])
        self._data_start = int(header["data_start"])
        self._index: Dict[str, dict] = {
            e["key"]: e for e in header["shards"]
        }
        end = self._data_start + max(
            (e["offset"] + e["nbytes"] for e in self._index.values()),
            default=0,
        )
        size = os.path.getsize(path)
        if size < end:
            raise ShardCorruptionError(
                f"{path}: truncated data region ({size} < {end} bytes)"
            )
        self._mm: Optional[np.memmap] = None
        self._mm_lock = threading.Lock()
        self._fd: Optional[int] = None  # pread path; offset-less, shared
        self.bytes_read = 0

    # ---- mapping interface -------------------------------------------------

    def keys(self):
        return self._index.keys()

    def __contains__(self, key: str) -> bool:
        return key in self._index

    def bounds(self, key: str):
        return _tuple_bounds(self._index[key]["bounds"])

    def _mmap(self) -> np.memmap:
        # Restore fans leaf reads over a thread pool that shares one
        # reader per proc file; guard the lazy map (reads themselves are
        # lock-free — the mapping is immutable once created).
        with self._mm_lock:
            if self._mm is None:
                self._mm = np.memmap(self.path, dtype=np.uint8, mode="r")
            return self._mm

    def view(self, key: str) -> np.ndarray:
        """Zero-copy mmap-backed array; only touched pages are read."""
        e = self._index[key]
        mm = self._mmap()
        start = self._data_start + e["offset"]
        flat = mm[start : start + e["nbytes"]]
        return flat.view(_np_dtype(e["dtype"])).reshape(
            tuple(e["local_shape"])
        )

    def get(self, key: str, verify: bool = True) -> np.ndarray:
        """Full-shard copy, checksum-verified by default."""
        e = self._index[key]
        arr = np.array(self.view(key))  # copy out of the mmap
        self.bytes_read += arr.nbytes
        if verify:
            csum = zlib.adler32(_as_bytes(arr))
            if csum != e["adler32"]:
                raise ShardCorruptionError(
                    f"{self.path}: checksum mismatch on {key} "
                    f"(stored {e['adler32']}, read {csum})"
                )
        return arr

    def read_slice(self, key: str, slices: Tuple[slice, ...]) -> np.ndarray:
        """Copy of ``shard[slices]`` — reads only the pages the slice
        touches. No checksum (verifying would read the whole shard and
        defeat the point of a partial restore)."""
        out = np.array(self.view(key)[slices])
        self.bytes_read += out.nbytes
        return out

    def read_slice_into(
        self,
        key: str,
        slices: Tuple[slice, ...],
        dest: np.ndarray,
        verify: bool = False,
    ):
        """Copy ``shard[slices]`` straight from the mmap into ``dest``
        (a writable view) — one copy, no intermediate buffer.

        ``verify=True`` is only meaningful when the read covers the
        WHOLE shard (the engine passes it exactly then): the copied
        bytes are crc-checked against the header so full-shard restores
        honor the format's bitflip guarantee; a mismatch raises before
        the caller can use the poisoned region."""
        e = self._index[key]
        # The stored checksum covers the WHOLE shard; a sub-range read
        # cannot be verified against it.
        verify = verify and dest.nbytes == e["nbytes"]
        span = None
        if dest.flags.c_contiguous and dest.nbytes:
            span = self._contig_span(
                tuple(e["local_shape"]), slices,
                _np_dtype(e["dtype"]).itemsize,
            )
        if span is not None:
            # pread path: a contiguous byte span read straight into the
            # destination buffer skips the mmap's ~64k minor faults per
            # GB; the sum64 checksum (full-shard reads only) runs per
            # chunk while the bytes are cache-hot at SIMD speed.
            if self._fd is None:
                with self._mm_lock:
                    if self._fd is None:
                        self._fd = os.open(self.path, os.O_RDONLY)
            file_off = self._data_start + e["offset"] + span[0]
            dflat = _as_bytes(dest)
            wsum = 0
            chunk = 4 << 20
            for lo in range(0, dflat.nbytes, chunk):
                part = dflat[lo : lo + chunk]
                n = os.preadv(self._fd, [part], file_off + lo)
                if n != part.nbytes:
                    raise ShardCorruptionError(
                        f"{self.path}: short read on {key} "
                        f"({n} != {part.nbytes} bytes)"
                    )
                if verify:
                    wsum = _sum64(part, wsum)
        else:
            src = self.view(key)
            if slices:
                src = src[slices]
            np.copyto(dest, src)
            wsum = (
                _sum64(_as_bytes(np.ascontiguousarray(dest)))
                if verify
                else 0
            )
        self.bytes_read += dest.nbytes
        if verify and wsum != e["sum64"]:
            raise ShardCorruptionError(
                f"{self.path}: checksum mismatch on {key} "
                f"(stored sum64 {e['sum64']}, read {wsum})"
            )

    def verify_all(self) -> bool:
        try:
            for key in self._index:
                self.get(key, verify=True)
        except ShardCorruptionError as e:
            logger.error("%s", e)
            return False
        return True

    # ---- lifecycle ---------------------------------------------------------

    def close(self):
        if self._fd is not None:
            fd = self._fd
            self._fd = None
            try:
                os.close(fd)
            except OSError:
                pass
        if self._mm is not None:
            mm = self._mm
            self._mm = None
            # np.memmap keeps the mapping alive through ._mmap; close it
            # deterministically instead of waiting on the GC.
            try:
                mm._mmap.close()  # noqa: SLF001
            except (AttributeError, BufferError):
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):  # best-effort backstop; close() is the contract
        try:
            self.close()
        except Exception:
            pass
