"""Orbax interop: export/import flash checkpoints to the JAX
ecosystem's standard layout.

Parity intent: the reference's savers deliberately write framework-
native formats so checkpoints interop with the surrounding ecosystem
(elastic_agent/torch/ckpt_saver.py:1341-1450 writes real torch/
DeepSpeed/Megatron layouts). The flash engine's own format (raw
mmap-able shards + restricted-pickle meta, flash_ckpt/raw_format.py;
legacy npz step dirs stay readable) is optimized for the
shm fast path and self-restore; this module bridges it to orbax
(tensorstore) so anything in the JAX world — orbax restore in another
trainer, model surgery tools, eval harnesses — can consume or produce
dlrover-tpu checkpoints.

    from dlrover_tpu.flash_ckpt import orbax_io
    orbax_io.export_step(flash_dir, orbax_dir)           # latest step
    step, state = orbax_io.load_orbax(orbax_dir)         # any tool
    orbax_io.import_step(orbax_dir, flash_dir)           # back in

CLI: ``python -m dlrover_tpu.flash_ckpt.orbax_io export|import ...``.
"""

import argparse
import json
import os
import pickle
from typing import Any, Optional, Tuple

from dlrover_tpu.common.log import logger
from dlrover_tpu.flash_ckpt import storage as fstorage

_META_FILE = "dlrover_tpu_meta.json"


def _ocp():
    import orbax.checkpoint as ocp

    return ocp


# ---------------------------------------------------------------------------
# Flash -> orbax
# ---------------------------------------------------------------------------


def export_step(
    flash_dir: str,
    orbax_dir: str,
    step: Optional[int] = None,
) -> int:
    """Write one flash step as an orbax checkpoint
    (``{orbax_dir}/{step}``). Returns the exported step."""
    from dlrover_tpu.flash_ckpt.engine import load_global_state

    ocp = _ocp()
    if step is None:
        committed = fstorage.read_tracker(flash_dir)
        steps = fstorage.list_step_dirs(flash_dir)
        candidates = [s for s in steps if s <= committed] or steps
        if not candidates:
            raise FileNotFoundError(
                f"no flash checkpoint steps under {flash_dir}"
            )
        step = max(candidates)
    metas = fstorage.load_step_meta(flash_dir, step)
    if not metas:
        raise FileNotFoundError(
            f"flash step {step} has no metadata under {flash_dir}"
        )
    loaded = load_global_state(flash_dir, step, metas)
    if loaded is None:
        raise RuntimeError(
            f"flash step {step} is incomplete (missing shards)"
        )
    _, state, user_meta = loaded
    path = os.path.join(os.path.abspath(orbax_dir), str(step))
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path, state, force=True)
    with open(os.path.join(path, _META_FILE), "w") as f:
        json.dump({"step": step, "user_meta": user_meta}, f, default=str)
    logger.info("exported flash step %d -> orbax at %s", step, path)
    return step


# ---------------------------------------------------------------------------
# Orbax -> flash (or direct use)
# ---------------------------------------------------------------------------


def list_orbax_steps(orbax_dir: str):
    steps = []
    try:
        for name in os.listdir(orbax_dir):
            if name.isdigit():
                steps.append(int(name))
    except OSError:
        pass
    return sorted(steps)


def load_orbax(
    orbax_dir: str, step: Optional[int] = None
) -> Tuple[int, Any]:
    """Load an orbax checkpoint (written by this module or any orbax
    producer) as a numpy pytree."""
    ocp = _ocp()
    if step is None:
        steps = list_orbax_steps(orbax_dir)
        if not steps:
            raise FileNotFoundError(f"no orbax steps under {orbax_dir}")
        step = steps[-1]
    path = os.path.join(os.path.abspath(orbax_dir), str(step))
    with ocp.PyTreeCheckpointer() as ckptr:
        state = ckptr.restore(path)
    return step, state


def import_step(
    orbax_dir: str,
    flash_dir: str,
    step: Optional[int] = None,
) -> int:
    """Bring an orbax checkpoint into the flash layout so the elastic
    restore path (memory-first fallback to storage, replicas, re-mesh
    device placement) can serve it."""
    import jax
    import numpy as np

    from dlrover_tpu.flash_ckpt.shm_handler import LeafMeta, ShardMeta

    step, state = load_orbax(orbax_dir, step)
    leaves, treedef = jax.tree_util.tree_flatten(state)
    arrays = {}
    leaf_metas = []
    for i, leaf in enumerate(leaves):
        arr = np.asarray(leaf)
        bounds = tuple((0, s) for s in arr.shape)
        leaf_metas.append(
            LeafMeta(
                leaf_id=i,
                global_shape=tuple(arr.shape),
                dtype=str(arr.dtype),
                shards=[ShardMeta(bounds, tuple(arr.shape))],
                replicated=True,
            )
        )
        arrays[f"leaf{i}_shard0"] = arr
    meta = {
        "step": step,
        "treedef": pickle.dumps(treedef),
        "leaves": leaf_metas,
        "user_meta": {"imported_from": os.path.abspath(orbax_dir)},
        "num_processes": 1,
    }
    fstorage.persist_node_shards(
        flash_dir, step, node_rank=0,
        proc_payloads={0: {"arrays": arrays, "meta": meta}},
    )
    fstorage.write_tracker(flash_dir, step)
    logger.info("imported orbax step %d -> flash at %s", step, flash_dir)
    return step


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="flash <-> orbax bridge")
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_exp = sub.add_parser("export", help="flash checkpoint -> orbax")
    p_exp.add_argument("--flash-dir", required=True)
    p_exp.add_argument("--orbax-dir", required=True)
    p_exp.add_argument("--step", type=int, default=None)
    p_imp = sub.add_parser("import", help="orbax checkpoint -> flash")
    p_imp.add_argument("--orbax-dir", required=True)
    p_imp.add_argument("--flash-dir", required=True)
    p_imp.add_argument("--step", type=int, default=None)
    args = parser.parse_args(argv)
    if args.cmd == "export":
        step = export_step(args.flash_dir, args.orbax_dir, args.step)
    else:
        step = import_step(args.orbax_dir, args.flash_dir, args.step)
    print(step)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
