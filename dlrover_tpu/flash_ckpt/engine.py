"""Trainer-side flash checkpoint engine.

Parity: reference trainer/torch/flash_checkpoint/engine.py
(CheckpointEngine.save_state_dict_to_memory:365,
get_state_dict_from_memory:406) adapted to JAX pytrees: the blocking cost
of a save is one ``jax.device_get`` of the state into shared memory; the
agent persists asynchronously. Restore is memory-first, storage-fallback,
with resharding handled through shard metadata +
``jax.make_array_from_callback`` under the *current* mesh.
"""

import os
import queue
import time
from typing import Any, Dict, Optional

import numpy as np

from dlrover_tpu.common.constants import NodeEnv
from dlrover_tpu.common.log import logger
from dlrover_tpu.flash_ckpt import storage as ckpt_storage
from dlrover_tpu.flash_ckpt.shared_obj import (
    SharedLockClient,
    SharedQueueClient,
)
from dlrover_tpu.flash_ckpt.shm_handler import (
    SharedMemoryHandler,
    bounds_to_slices,
)
from dlrover_tpu.trainer.runtime import get_context

CKPT_EVENT_QUEUE = "ckpt_event"
CKPT_LOCK_PREFIX = "ckpt_shm"


def shm_segment_name(local_rank: int) -> str:
    """Per-worker shm segment. The node rank is part of the name so
    same-host multi-node setups (tests, packed dev boxes) never collide;
    agent and workers of one node share the same NODE_RANK env."""
    job = os.getenv(NodeEnv.JOB_NAME, "job")
    node_rank = os.getenv(NodeEnv.NODE_RANK, "0")
    return f"dlrover_tpu_ckpt_{job}_n{node_rank}_{local_rank}"


class SaveEvent:
    SAVE_MEM = "save_mem"
    SAVE_DISK = "save_disk"

    def __init__(
        self,
        kind: str,
        step: int,
        checkpoint_dir: str = "",
        local_world_size: int = 1,
    ):
        self.kind = kind
        self.step = step
        self.checkpoint_dir = checkpoint_dir
        self.local_world_size = local_world_size


class CheckpointEngine:
    """One instance per worker process."""

    def __init__(
        self,
        checkpoint_dir: str,
        standalone: bool = False,
    ):
        """``standalone=True`` runs without an agent (no UDS servers): saves
        go to shm and persistence happens synchronously in-process — used
        for notebooks/tests and as a degraded mode."""
        self.checkpoint_dir = checkpoint_dir
        self._ctx = get_context()
        self._local_rank = self._ctx.local_rank
        self._shm = SharedMemoryHandler(shm_segment_name(self._local_rank))
        self._standalone = standalone
        if standalone:
            self._lock = None
            self._event_queue = None
        else:
            self._lock = SharedLockClient(
                f"{CKPT_LOCK_PREFIX}_{self._local_rank}"
            )
            self._event_queue = SharedQueueClient(CKPT_EVENT_QUEUE)
        self._last_save_time = 0.0
        self._last_disk_step = -1  # newest step a disk save was requested for
        # Async snapshot pipeline: the training thread only LAUNCHES the
        # device->host DMA; a writer thread materializes the arrays (the
        # np conversion completes the in-flight transfer) and writes shm.
        import threading

        self._snap_cond = threading.Condition()
        # Serializes ALL shm writes in this process (training thread's
        # direct saves vs the async writer thread); the UDS SharedLock
        # only guards against the agent, not intra-process races, and is
        # absent entirely in standalone mode.
        self._save_mutex = threading.Lock()
        self._pending_snapshot = None  # (step, state, user_meta)
        self._writing_step = -1
        self._last_written_step = -1
        self._write_error: Optional[BaseException] = None
        self._writer_thread = None
        self._writer_stop = False
        from dlrover_tpu.flash_ckpt.autotune import SaveCostTracker

        self.cost_tracker = SaveCostTracker()
        from dlrover_tpu.observability.registry import default_registry

        registry = default_registry()
        self._saves_counter = registry.counter(
            "flash_ckpt_memory_saves_total",
            "flash checkpoint shm saves completed",
        )
        self._save_block_hist = registry.histogram(
            "flash_ckpt_save_block_seconds",
            "training-thread seconds blocked per shm save",
        )

    # ---- save --------------------------------------------------------------

    def save_to_memory(
        self,
        step: int,
        state: Any,
        user_meta: Optional[Dict[str, Any]] = None,
    ) -> float:
        """Blocking-path save: device -> shm. Returns block seconds.

        For a TRAINING-THREAD caller the whole elapsed is the blocking
        cost the Young/Daly autotuner needs, so it is recorded as such
        here; the async writer thread must use :meth:`_save_to_memory`
        instead — its shm write overlaps training and recording it as a
        blocking cost would inflate the recommended cadence ~100x."""
        elapsed = self._save_to_memory(step, state, user_meta)
        if elapsed > 0.0:
            self.cost_tracker.record_block(elapsed)
            self._save_block_hist.observe(elapsed)
        return elapsed

    def _save_to_memory(
        self,
        step: int,
        state: Any,
        user_meta: Optional[Dict[str, Any]] = None,
    ) -> float:
        start = time.time()
        with self._save_mutex:
            if step < self._last_written_step:
                # shm must only move forward: an older (async) snapshot
                # racing a newer direct save is refused, not written.
                logger.warning(
                    "refusing to write step %d over newer shm step %d",
                    step,
                    self._last_written_step,
                )
                return 0.0
            return self._save_to_memory_locked(
                step, state, user_meta, start
            )

    def _save_to_memory_locked(self, step, state, user_meta, start):
        import jax

        from dlrover_tpu.training_event import TrainerEvents

        with TrainerEvents.ckpt_save_memory(step) as span:
            jax.block_until_ready(state)
            meta = dict(user_meta or {})
            meta["process_id"] = self._ctx.process_id
            meta["num_processes"] = self._ctx.num_processes
            meta["local_rank"] = self._local_rank
            # Identity stamp: a segment left behind by a DIFFERENT job
            # that happened to share the shm name must never be restored.
            # realpath: '/a/ckpt/' vs '/a/ckpt' vs symlink spellings of
            # the same dir must not false-reject our own image.
            meta["ckpt_dir"] = os.path.realpath(self.checkpoint_dir)
            if self._lock is not None:
                self._lock.acquire()
            try:
                self._shm.save_state_dict(step, state, meta)
            finally:
                if self._lock is not None:
                    self._lock.release()
            if self._event_queue is not None and self._local_rank == 0:
                self._event_queue.put(
                    SaveEvent(
                        SaveEvent.SAVE_MEM,
                        step,
                        self.checkpoint_dir,
                        self._ctx.local_world_size,
                    )
                )
            elapsed = time.time() - start
            span.content["block_s"] = elapsed
        self._last_save_time = time.time()
        self._last_written_step = max(self._last_written_step, step)
        self.cost_tracker.record_drain(elapsed)
        self._saves_counter.inc()
        logger.info(
            "flash ckpt step %d -> shm in %.3fs", step, elapsed
        )
        return elapsed

    def save_to_memory_async(
        self,
        step: int,
        state: Any,
        user_meta: Optional[Dict[str, Any]] = None,
    ) -> float:
        """Non-blocking save: launch device->host DMA and return.

        The TPU flash-checkpoint hot path: ``copy_to_host_async`` starts
        the transfer, compute on the next step overlaps with the DMA, and
        a writer thread lands the bytes in shm when they arrive. The
        caller must NOT donate the passed state to later steps (keep
        ``donate=False`` on the jitted step, or pass a copy).

        Returns the blocking seconds (async-copy launch cost, ~ms even
        for multi-GB states).
        """
        import jax

        start = time.time()
        for leaf in jax.tree_util.tree_leaves(state):
            if hasattr(leaf, "copy_to_host_async"):
                leaf.copy_to_host_async()
        with self._snap_cond:
            if self._pending_snapshot is not None:
                logger.info(
                    "dropping unwritten snapshot of step %d for step %d",
                    self._pending_snapshot[0],
                    step,
                )
            self._pending_snapshot = (step, state, user_meta)
            self._ensure_writer()
            self._snap_cond.notify_all()
        elapsed = time.time() - start
        self.cost_tracker.record_block(elapsed)
        logger.info(
            "flash ckpt step %d async-launched in %.4fs", step, elapsed
        )
        return elapsed

    def recommended_interval_s(self, mtbf_s: float = 3600.0):
        """Young/Daly save cadence from THIS engine's measured costs
        (flash_ckpt/autotune.py); None until a save was measured."""
        return self.cost_tracker.recommended_interval_s(mtbf_s)

    def wait_async_save(self, timeout: float = 600.0) -> bool:
        """Block until every launched snapshot has landed in shm.

        False on timeout OR if the last write failed (the caller must
        not assume the launched step is restorable)."""
        deadline = time.time() + timeout
        with self._snap_cond:
            while (
                self._pending_snapshot is not None
                or self._writing_step >= 0
            ):
                remaining = deadline - time.time()
                if remaining <= 0:
                    return False
                self._snap_cond.wait(min(remaining, 1.0))
            return self._write_error is None

    def _ensure_writer(self):
        import threading

        if self._writer_thread is None or not self._writer_thread.is_alive():
            self._writer_stop = False
            self._writer_thread = threading.Thread(
                target=self._writer_loop, name="ckpt-writer", daemon=True
            )
            self._writer_thread.start()

    def _writer_loop(self):
        while True:
            with self._snap_cond:
                while self._pending_snapshot is None:
                    if self._writer_stop:
                        return
                    self._snap_cond.wait(1.0)
                step, state, user_meta = self._pending_snapshot
                self._pending_snapshot = None
                if step <= self._last_written_step:
                    # A direct save_to_memory of a NEWER step landed while
                    # this snapshot waited: writing it would regress shm.
                    logger.info(
                        "skipping stale async snapshot of step %d "
                        "(step %d already in shm)",
                        step,
                        self._last_written_step,
                    )
                    self._snap_cond.notify_all()
                    continue
                self._writing_step = step
            try:
                # _save_to_memory, NOT save_to_memory: this thread's shm
                # write overlaps training — it is drain, not block.
                self._save_to_memory(step, state, user_meta)
                with self._snap_cond:
                    self._write_error = None
            except Exception as e:
                logger.exception("async snapshot write failed")
                with self._snap_cond:
                    self._write_error = e
            finally:
                with self._snap_cond:
                    self._writing_step = -1
                    self._snap_cond.notify_all()

    def save_to_storage(
        self,
        step: int,
        state: Any,
        user_meta: Optional[Dict[str, Any]] = None,
    ) -> float:
        """Save to shm, then request async persistence to storage."""
        elapsed = self.save_to_memory(step, state, user_meta)
        prev_disk_step = self._last_disk_step
        self._last_disk_step = step
        if self._standalone:
            # Mirror the agent path: one persister per node. Every local
            # worker writing the node's files concurrently would race on
            # the shared tmp names and multiply checkpoint I/O by the
            # local world size.
            if self._local_rank == 0 and not self._persist_in_process(step):
                logger.error(
                    "standalone persist of step %d failed; the disk "
                    "checkpoint for this step was NOT committed",
                    step,
                )
                # This process KNOWS the step never committed: leaving it
                # recorded would make wait_saving_complete block its full
                # timeout on a tracker that will never advance.
                self._last_disk_step = prev_disk_step
        elif self._local_rank == 0:
            self._event_queue.put(
                SaveEvent(
                    SaveEvent.SAVE_DISK,
                    step,
                    self.checkpoint_dir,
                    self._ctx.local_world_size,
                )
            )
        return elapsed

    def _persist_in_process(self, step: int) -> bool:
        from dlrover_tpu.flash_ckpt.saver import persist_shm_to_storage

        node_rank = int(os.getenv(NodeEnv.NODE_RANK, "0"))
        # Standalone has no shm locks and no agent: sibling local workers
        # write their segments on their own schedule, so wait (bounded)
        # until every local segment holds >= the requested step before
        # reading — otherwise the persist sees a missing/older sibling
        # image and the step's disk checkpoint is silently dropped.
        if not self._wait_local_segments(step, timeout=30.0):
            logger.error(
                "not all %d local shm segments reached step %d within "
                "30s; aborting standalone persist",
                self._ctx.local_world_size,
                step,
            )
            return False
        # Expect every node of the world: only the leader (lowest rank)
        # commits, and only after all nodes' shard markers exist — each
        # node committing alone would advance the tracker to steps whose
        # peer shards aren't on disk yet (unrestorable "latest" step).
        # The agent injects the ACTUAL membership; arithmetic over
        # process counts would be wrong for uneven or non-contiguous
        # worlds.
        expected = list(self._ctx.node_ranks) or [node_rank]
        return persist_shm_to_storage(
            self.checkpoint_dir,
            step,
            node_rank,
            local_world_size=self._ctx.local_world_size,
            expected_nodes=expected,
            # Standalone runs the commit on the TRAINING thread: a dead
            # peer must cost seconds, not the agent path's 10 minutes.
            commit_timeout=30.0,
        )

    def _wait_local_segments(self, step: int, timeout: float) -> bool:
        """True once every local worker's shm segment holds >= ``step``."""
        deadline = time.time() + timeout
        while True:
            ready = True
            for lr in range(self._ctx.local_world_size):
                if lr == self._local_rank:
                    continue  # our own save already landed
                handler = SharedMemoryHandler(shm_segment_name(lr))
                sibling_step = handler.get_step()
                handler.close()
                if sibling_step < step:
                    ready = False
                    break
            if ready:
                return True
            if time.time() >= deadline:
                return False
            time.sleep(0.05)

    # ---- load --------------------------------------------------------------

    def load(self, step: Optional[int] = None):
        """Return (step, np-pytree, user_meta) or None.

        Memory-first: the shm image survives worker restarts on the same
        host. Falls back to the committed storage checkpoint.
        """
        from dlrover_tpu.training_event import TrainerEvents

        result = self._load_from_memory(step)
        if result is not None:
            logger.info("restored step %d from host memory", result[0])
            TrainerEvents.ckpt_restore(result[0], "memory")
            return result
        result = self._load_from_storage(step)
        if result is not None:
            logger.info("restored step %d from storage", result[0])
            TrainerEvents.ckpt_restore(result[0], "storage")
        return result

    def _load_from_memory(self, step: Optional[int] = None):
        mem_step = self._shm.get_step()
        if mem_step < 0 or (step is not None and mem_step != step):
            return None
        loaded = self._shm.load_state_dict()
        if loaded is None:
            return None
        mem_step, state, meta = loaded
        if self._is_foreign_image(meta):
            # Leftover segment from another job sharing the shm name
            # (default JOB_NAME, reused dev box): not our checkpoint.
            logger.warning(
                "ignoring shm image of foreign checkpoint %s",
                meta.get("ckpt_dir"),
            )
            return None
        if meta.get("num_processes") != self._ctx.num_processes:
            # World changed: per-process shm images do not cover the same
            # index set; storage has the complete picture.
            return None
        state = assemble_sharded_leaves(state)
        if state is None:
            return None
        return mem_step, state, meta

    def _load_from_storage(self, step: Optional[int] = None):
        target = step
        if target is None:
            target = ckpt_storage.read_tracker(self.checkpoint_dir)
        if target < 0:
            return None
        metas = ckpt_storage.load_step_meta(self.checkpoint_dir, target)
        if not metas:
            return None
        return load_global_state(self.checkpoint_dir, target, metas)

    def _is_foreign_image(self, meta: dict) -> bool:
        stamped = meta.get("ckpt_dir")
        return stamped is not None and stamped != os.path.realpath(
            self.checkpoint_dir
        )

    def latest_step(self) -> int:
        """Newest restorable step (max of shm image and storage tracker).
        A foreign job's shm image is not restorable by us and must not
        be advertised."""
        mem_step = -1
        meta = self._shm.load_meta()
        if meta is not None and not self._is_foreign_image(
            meta.get("user_meta", {})
        ):
            mem_step = meta.get("step", -1)
        return max(
            mem_step,
            ckpt_storage.read_tracker(self.checkpoint_dir),
        )

    def close(self):
        drained = self.wait_async_save(timeout=60.0)
        with self._snap_cond:
            self._writer_stop = True
            self._snap_cond.notify_all()
        if not drained:
            logger.error(
                "async snapshot did not drain cleanly before close; the "
                "newest launched step may not be restorable from memory"
            )
        # Let the writer finish/exit before closing shm under it.
        if self._writer_thread is not None:
            self._writer_thread.join(timeout=10.0)
            if self._writer_thread.is_alive():
                # Never close the segment under an in-progress write: a
                # leaked handle beats a torn snapshot. The daemon thread
                # dies with the process.
                logger.error(
                    "ckpt writer still running at close; leaving shm open"
                )
                return
        self._shm.close()


# --------------------------------------------------------------------------
# Reassembly helpers
# --------------------------------------------------------------------------


def assemble_sharded_leaves(state):
    """Convert {"__shards__": ...} leaf records into full numpy arrays.

    Returns None if any leaf's shards don't cover its global shape (the
    caller must then use storage, which has every process's shards).
    """
    import jax

    incomplete = []

    def fix(leaf):
        if not (isinstance(leaf, dict) and "__shards__" in leaf):
            return leaf
        assembled = _assemble_from_shards(
            leaf["__global_shape__"], leaf["__dtype__"], leaf["__shards__"]
        )
        if assembled is None:
            incomplete.append(leaf["__global_shape__"])
        return assembled

    is_record = lambda x: isinstance(x, dict) and "__shards__" in x  # noqa: E731
    out = jax.tree_util.tree_map(fix, state, is_leaf=is_record)
    if incomplete:
        return None
    return out


def _assemble_from_shards(global_shape, dtype_name, shards):
    from dlrover_tpu.flash_ckpt.shm_handler import _np_dtype

    dtype = _np_dtype(dtype_name)
    out = np.zeros(global_shape, dtype=dtype)
    covered = np.zeros(global_shape, dtype=bool) if global_shape else None
    for bounds, arr in shards:
        slices = bounds_to_slices(bounds)
        out[slices] = arr
        if covered is not None:
            covered[slices] = True
    if covered is not None and not covered.all():
        return None
    return out


def load_global_state(checkpoint_dir: str, step: int, metas: Dict[int, dict]):
    """Assemble the full global state from every process's shard files."""
    import jax

    from dlrover_tpu.common.serialize import loads_pytree
    from dlrover_tpu.flash_ckpt.shm_handler import _np_dtype

    first = metas[min(metas)]
    treedef = loads_pytree(first["treedef"])
    num_leaves = len(first["leaves"])
    leaves = [None] * num_leaves
    user_meta = first.get("user_meta", {})
    for pid, meta in sorted(metas.items()):
        arrays = ckpt_storage.load_proc_arrays(checkpoint_dir, step, pid)
        if arrays is None:
            continue
        for leaf_meta in meta["leaves"]:
            i = leaf_meta.leaf_id
            dtype = _np_dtype(leaf_meta.dtype)
            if leaves[i] is None:
                leaves[i] = np.zeros(leaf_meta.global_shape, dtype=dtype)
            for j, shard in enumerate(leaf_meta.shards):
                key = f"leaf{i}_shard{j}"
                if key in arrays:
                    slices = bounds_to_slices(shard.index)
                    leaves[i][slices] = arrays[key]
    if any(l is None for l in leaves):
        return None
    state = jax.tree_util.tree_unflatten(treedef, leaves)
    return step, state, user_meta


def to_device_state(np_state, sharding_tree=None):
    """Put a numpy pytree onto devices under the current mesh.

    sharding_tree: matching pytree of ``jax.sharding.Sharding`` (or None
    for single-device default placement). Each process materializes only
    its addressable shards — the resharding restore path ("universal
    checkpoint" analogue).

    A single batched ``device_put`` lets the runtime pipeline all leaf
    transfers (~10x faster restore than per-leaf puts on slow links);
    the per-leaf ``make_array_from_callback`` path is the fallback for
    runtimes that reject global host arrays under non-addressable
    shardings.
    """
    import jax

    if sharding_tree is None:
        return jax.tree_util.tree_map(jax.numpy.asarray, np_state)

    try:
        from jax.errors import JaxRuntimeError as _XlaRuntimeError
    except ImportError:  # older jaxlib spelling
        from jaxlib.xla_extension import XlaRuntimeError as _XlaRuntimeError

    try:
        return jax.device_put(np_state, sharding_tree)
    except (ValueError, NotImplementedError, _XlaRuntimeError) as e:
        # The known "runtime rejects global host arrays under
        # non-addressable shardings" shapes only — anything else (host
        # OOM, dtype corruption) must surface, not be absorbed by the
        # slower per-leaf fallback.
        logger.warning(
            "batched device_put restore unavailable (%s: %s); using "
            "per-leaf transfers",
            type(e).__name__,
            e,
        )

    def put(arr, sharding):
        arr = np.asarray(arr)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx]
        )

    return jax.tree_util.tree_map(put, np_state, sharding_tree)


_fetch_probe = None


def fetch_barrier(tree) -> float:
    """Reliable completion barrier over every leaf of ``tree``.

    ``jax.block_until_ready`` can return before async dispatch actually
    lands on remote-attached backends (measured on the axon tunnel), so
    restore timings taken with it silently leak the H2D cost into
    whatever runs next. This fetches ONE element of every leaf through a
    single jitted reduction — one dispatch, and the host fetch cannot
    complete until every input transfer has."""
    import jax
    import jax.numpy as jnp

    global _fetch_probe
    if _fetch_probe is None:
        def probe(leaves):
            acc = jnp.zeros((), jnp.float32)
            for leaf in leaves:
                acc = acc + jnp.sum(
                    jnp.ravel(leaf)[:1].astype(jnp.float32)
                )
            return acc

        _fetch_probe = jax.jit(probe)
    leaves = [
        x for x in jax.tree_util.tree_leaves(tree)
        if hasattr(x, "dtype")
    ]
    return float(_fetch_probe(leaves))
