"""Trainer-side flash checkpoint engine.

Parity: reference trainer/torch/flash_checkpoint/engine.py
(CheckpointEngine.save_state_dict_to_memory:365,
get_state_dict_from_memory:406) adapted to JAX pytrees: the blocking cost
of a save is one ``jax.device_get`` of the state into shared memory; the
agent persists asynchronously. Restore is memory-first, storage-fallback,
with resharding handled through shard metadata +
``jax.make_array_from_callback`` under the *current* mesh.
"""

import os
import queue
import time
from typing import Any, Dict, Optional

import numpy as np

from dlrover_tpu.common.constants import NodeEnv
from dlrover_tpu.common.env_utils import get_env_int
from dlrover_tpu.common.log import logger
from dlrover_tpu.fault import FaultInjected, fault_point
from dlrover_tpu.flash_ckpt import storage as ckpt_storage
from dlrover_tpu.flash_ckpt.shared_obj import (
    SharedLockClient,
    SharedQueueClient,
)
from dlrover_tpu.flash_ckpt.shm_handler import (
    SharedMemoryHandler,
    bounds_to_slices,
)
from dlrover_tpu.trainer.runtime import get_context

CKPT_EVENT_QUEUE = "ckpt_event"
CKPT_LOCK_PREFIX = "ckpt_shm"


def shm_segment_name(local_rank: int) -> str:
    """Per-worker shm segment. The node rank is part of the name so
    same-host multi-node setups (tests, packed dev boxes) never collide;
    agent and workers of one node share the same NODE_RANK env."""
    job = os.getenv(NodeEnv.JOB_NAME, "job")
    node_rank = os.getenv(NodeEnv.NODE_RANK, "0")
    return f"dlrover_tpu_ckpt_{job}_n{node_rank}_{local_rank}"


class SaveEvent:
    SAVE_MEM = "save_mem"
    SAVE_DISK = "save_disk"

    def __init__(
        self,
        kind: str,
        step: int,
        checkpoint_dir: str = "",
        local_world_size: int = 1,
    ):
        self.kind = kind
        self.step = step
        self.checkpoint_dir = checkpoint_dir
        self.local_world_size = local_world_size


class CheckpointEngine:
    """One instance per worker process."""

    def __init__(
        self,
        checkpoint_dir: str,
        standalone: bool = False,
    ):
        """``standalone=True`` runs without an agent (no UDS servers): saves
        go to shm and persistence happens synchronously in-process — used
        for notebooks/tests and as a degraded mode."""
        self.checkpoint_dir = checkpoint_dir
        self._ctx = get_context()
        self._local_rank = self._ctx.local_rank
        self._shm = SharedMemoryHandler(shm_segment_name(self._local_rank))
        self._standalone = standalone
        if standalone:
            self._lock = None
            self._event_queue = None
        else:
            self._lock = SharedLockClient(
                f"{CKPT_LOCK_PREFIX}_{self._local_rank}"
            )
            self._event_queue = SharedQueueClient(CKPT_EVENT_QUEUE)
        self._last_save_time = 0.0
        self._last_disk_step = -1  # newest step a disk save was requested for
        # Async snapshot pipeline: the training thread only LAUNCHES the
        # device->host DMA; a writer thread materializes the arrays (the
        # np conversion completes the in-flight transfer) and writes shm.
        import threading

        self._snap_cond = threading.Condition()
        # Serializes ALL shm writes in this process (training thread's
        # direct saves vs the async writer thread); the UDS SharedLock
        # only guards against the agent, not intra-process races, and is
        # absent entirely in standalone mode.
        self._save_mutex = threading.Lock()
        self._pending_snapshot = None  # (step, state, user_meta)
        self._writing_step = -1
        self._last_written_step = -1
        self._write_error: Optional[BaseException] = None
        self._writer_thread = None
        self._writer_stop = False
        from dlrover_tpu.flash_ckpt.autotune import SaveCostTracker

        self.cost_tracker = SaveCostTracker()
        from dlrover_tpu.observability.registry import default_registry

        registry = default_registry()
        self._saves_counter = registry.counter(
            "flash_ckpt_memory_saves_total",
            "flash checkpoint shm saves completed",
        )
        self._save_block_hist = registry.histogram(
            "flash_ckpt_save_block_seconds",
            "training-thread seconds blocked per shm save",
        )
        self._restore_hist = registry.histogram(
            "flash_ckpt_restore_seconds",
            "storage restore wall seconds (read + assembly)",
        )
        self._restore_bw_hist = registry.histogram(
            "flash_ckpt_restore_mb_per_s",
            "storage restore bandwidth (local bytes / wall seconds)",
            buckets=(1, 4, 16, 64, 256, 1024, 4096, 16384),
        )
        self._restore_bytes = registry.counter(
            "flash_ckpt_restore_bytes_total",
            "bytes materialized by storage restores",
        )
        self._restore_rejected = registry.counter(
            "flash_ckpt_restore_steps_rejected_total",
            "checkpoint steps rejected at restore (torn/corrupt shards)",
        )
        # How many earlier step dirs a restore may fall back through
        # when the newest is corrupt; retention keeps ~max_to_keep dirs.
        self._restore_fallback_steps = get_env_int(
            "DLROVER_TPU_CKPT_RESTORE_FALLBACK_STEPS", 3
        )

    # ---- save --------------------------------------------------------------

    def save_to_memory(
        self,
        step: int,
        state: Any,
        user_meta: Optional[Dict[str, Any]] = None,
    ) -> float:
        """Blocking-path save: device -> shm. Returns block seconds.

        For a TRAINING-THREAD caller the whole elapsed is the blocking
        cost the Young/Daly autotuner needs, so it is recorded as such
        here; the async writer thread must use :meth:`_save_to_memory`
        instead — its shm write overlaps training and recording it as a
        blocking cost would inflate the recommended cadence ~100x."""
        elapsed = self._save_to_memory(step, state, user_meta)
        if elapsed > 0.0:
            self.cost_tracker.record_block(elapsed)
            self._save_block_hist.observe(elapsed)
        return elapsed

    def _save_to_memory(
        self,
        step: int,
        state: Any,
        user_meta: Optional[Dict[str, Any]] = None,
    ) -> float:
        start = time.time()
        with self._save_mutex:
            if step < self._last_written_step:
                # shm must only move forward: an older (async) snapshot
                # racing a newer direct save is refused, not written.
                logger.warning(
                    "refusing to write step %d over newer shm step %d",
                    step,
                    self._last_written_step,
                )
                return 0.0
            return self._save_to_memory_locked(
                step, state, user_meta, start
            )

    def _save_to_memory_locked(self, step, state, user_meta, start):
        import jax

        from dlrover_tpu.training_event import TrainerEvents

        with TrainerEvents.ckpt_save_memory(step) as span:
            jax.block_until_ready(state)
            meta = dict(user_meta or {})
            meta["process_id"] = self._ctx.process_id
            meta["num_processes"] = self._ctx.num_processes
            meta["local_rank"] = self._local_rank
            # Identity stamp: a segment left behind by a DIFFERENT job
            # that happened to share the shm name must never be restored.
            # realpath: '/a/ckpt/' vs '/a/ckpt' vs symlink spellings of
            # the same dir must not false-reject our own image.
            meta["ckpt_dir"] = os.path.realpath(self.checkpoint_dir)
            if self._lock is not None:
                self._lock.acquire()
            try:
                self._shm.save_state_dict(step, state, meta)
            finally:
                if self._lock is not None:
                    self._lock.release()
            if self._event_queue is not None and self._local_rank == 0:
                self._event_queue.put(
                    SaveEvent(
                        SaveEvent.SAVE_MEM,
                        step,
                        self.checkpoint_dir,
                        self._ctx.local_world_size,
                    )
                )
            elapsed = time.time() - start
            span.content["block_s"] = elapsed
        self._last_save_time = time.time()
        self._last_written_step = max(self._last_written_step, step)
        self.cost_tracker.record_drain(elapsed)
        self._saves_counter.inc()
        logger.info(
            "flash ckpt step %d -> shm in %.3fs", step, elapsed
        )
        return elapsed

    def save_to_memory_async(
        self,
        step: int,
        state: Any,
        user_meta: Optional[Dict[str, Any]] = None,
    ) -> float:
        """Non-blocking save: launch device->host DMA and return.

        The TPU flash-checkpoint hot path: ``copy_to_host_async`` starts
        the transfer, compute on the next step overlaps with the DMA, and
        a writer thread lands the bytes in shm when they arrive. The
        caller must NOT donate the passed state to later steps (keep
        ``donate=False`` on the jitted step, or pass a copy).

        Returns the blocking seconds (async-copy launch cost, ~ms even
        for multi-GB states).
        """
        import jax

        start = time.time()
        for leaf in jax.tree_util.tree_leaves(state):
            if hasattr(leaf, "copy_to_host_async"):
                leaf.copy_to_host_async()
        with self._snap_cond:
            if self._pending_snapshot is not None:
                logger.info(
                    "dropping unwritten snapshot of step %d for step %d",
                    self._pending_snapshot[0],
                    step,
                )
            self._pending_snapshot = (step, state, user_meta)
            self._ensure_writer()
            self._snap_cond.notify_all()
        elapsed = time.time() - start
        self.cost_tracker.record_block(elapsed)
        logger.info(
            "flash ckpt step %d async-launched in %.4fs", step, elapsed
        )
        return elapsed

    def recommended_interval_s(self, mtbf_s: float = 3600.0):
        """Young/Daly save cadence from THIS engine's measured costs
        (flash_ckpt/autotune.py); None until a save was measured."""
        return self.cost_tracker.recommended_interval_s(mtbf_s)

    def wait_async_save(self, timeout: float = 600.0) -> bool:
        """Block until every launched snapshot has landed in shm.

        False on timeout OR if the last write failed (the caller must
        not assume the launched step is restorable)."""
        deadline = time.time() + timeout
        with self._snap_cond:
            while (
                self._pending_snapshot is not None
                or self._writing_step >= 0
            ):
                remaining = deadline - time.time()
                if remaining <= 0:
                    return False
                self._snap_cond.wait(min(remaining, 1.0))
            return self._write_error is None

    def _ensure_writer(self):
        import threading

        if self._writer_thread is None or not self._writer_thread.is_alive():
            self._writer_stop = False
            self._writer_thread = threading.Thread(
                target=self._writer_loop, name="ckpt-writer", daemon=True
            )
            self._writer_thread.start()

    def _writer_loop(self):
        while True:
            with self._snap_cond:
                while self._pending_snapshot is None:
                    if self._writer_stop:
                        return
                    self._snap_cond.wait(1.0)
                step, state, user_meta = self._pending_snapshot
                self._pending_snapshot = None
                if step <= self._last_written_step:
                    # A direct save_to_memory of a NEWER step landed while
                    # this snapshot waited: writing it would regress shm.
                    logger.info(
                        "skipping stale async snapshot of step %d "
                        "(step %d already in shm)",
                        step,
                        self._last_written_step,
                    )
                    self._snap_cond.notify_all()
                    continue
                self._writing_step = step
            try:
                # _save_to_memory, NOT save_to_memory: this thread's shm
                # write overlaps training — it is drain, not block.
                self._save_to_memory(step, state, user_meta)
                with self._snap_cond:
                    self._write_error = None
            except Exception as e:
                logger.exception("async snapshot write failed")
                with self._snap_cond:
                    self._write_error = e
            finally:
                with self._snap_cond:
                    self._writing_step = -1
                    self._snap_cond.notify_all()

    def save_to_storage(
        self,
        step: int,
        state: Any,
        user_meta: Optional[Dict[str, Any]] = None,
    ) -> float:
        """Save to shm, then request async persistence to storage."""
        elapsed = self.save_to_memory(step, state, user_meta)
        prev_disk_step = self._last_disk_step
        self._last_disk_step = step
        if self._standalone:
            # Mirror the agent path: one persister per node. Every local
            # worker writing the node's files concurrently would race on
            # the shared tmp names and multiply checkpoint I/O by the
            # local world size.
            if self._local_rank == 0 and not self._persist_in_process(step):
                logger.error(
                    "standalone persist of step %d failed; the disk "
                    "checkpoint for this step was NOT committed",
                    step,
                )
                # This process KNOWS the step never committed: leaving it
                # recorded would make wait_saving_complete block its full
                # timeout on a tracker that will never advance.
                self._last_disk_step = prev_disk_step
        elif self._local_rank == 0:
            self._event_queue.put(
                SaveEvent(
                    SaveEvent.SAVE_DISK,
                    step,
                    self.checkpoint_dir,
                    self._ctx.local_world_size,
                )
            )
        return elapsed

    def _persist_in_process(self, step: int) -> bool:
        from dlrover_tpu.flash_ckpt.saver import persist_shm_to_storage

        node_rank = int(os.getenv(NodeEnv.NODE_RANK, "0"))
        # Standalone has no shm locks and no agent: sibling local workers
        # write their segments on their own schedule, so wait (bounded)
        # until every local segment holds >= the requested step before
        # reading — otherwise the persist sees a missing/older sibling
        # image and the step's disk checkpoint is silently dropped.
        if not self._wait_local_segments(step, timeout=30.0):
            logger.error(
                "not all %d local shm segments reached step %d within "
                "30s; aborting standalone persist",
                self._ctx.local_world_size,
                step,
            )
            return False
        # Expect every node of the world: only the leader (lowest rank)
        # commits, and only after all nodes' shard markers exist — each
        # node committing alone would advance the tracker to steps whose
        # peer shards aren't on disk yet (unrestorable "latest" step).
        # The agent injects the ACTUAL membership; arithmetic over
        # process counts would be wrong for uneven or non-contiguous
        # worlds.
        expected = list(self._ctx.node_ranks) or [node_rank]
        # Standalone runs the commit on the TRAINING thread: a dead peer
        # must cost seconds, not the agent path's 10 minutes. Tunable
        # because this wait is uninterruptible — a live-rescale worker
        # whose peer was just killed is blind to the superseding plan
        # until the commit wait returns, so rescale harnesses cap it.
        commit_timeout = get_env_int(
            "DLROVER_TPU_CKPT_COMMIT_TIMEOUT_S", 30
        )
        return persist_shm_to_storage(
            self.checkpoint_dir,
            step,
            node_rank,
            local_world_size=self._ctx.local_world_size,
            expected_nodes=expected,
            commit_timeout=float(commit_timeout),
        )

    def _wait_local_segments(self, step: int, timeout: float) -> bool:
        """True once every local worker's shm segment holds >= ``step``.

        One SharedMemoryHandler per sibling is attached ONCE and polled,
        not opened/closed every 50ms (each open is a shm_open+mmap
        syscall pair). A lagging sibling's handler is re-attached about
        once a second — the rare case where the sibling unlinked and
        recreated a larger segment would otherwise pin us to the stale
        mapping forever.
        """
        deadline = time.time() + timeout
        handlers = {
            lr: SharedMemoryHandler(shm_segment_name(lr))
            for lr in range(self._ctx.local_world_size)
            if lr != self._local_rank  # our own save already landed
        }
        try:
            polls = 0
            while True:
                ready = True
                for lr, handler in handlers.items():
                    if handler.get_step() < step:
                        ready = False
                        if polls and polls % 20 == 0:
                            handler.close()  # re-attach next poll
                        break
                if ready:
                    return True
                if time.time() >= deadline:
                    return False
                polls += 1
                time.sleep(0.05)
        finally:
            for handler in handlers.values():
                handler.close()

    # ---- load --------------------------------------------------------------

    def load(self, step: Optional[int] = None, sharding_tree=None):
        """Return (step, state, user_meta) or None.

        Memory-first: the shm image survives worker restarts on the same
        host (its leaves come back as numpy). Falls back to the committed
        storage checkpoint; with ``sharding_tree`` the storage path is a
        sharding-aware partial restore — only this process's addressable
        byte ranges are read and leaves come back as placed jax Arrays.
        """
        from dlrover_tpu.training_event import TrainerEvents

        result = self._load_from_memory(step)
        if result is not None:
            logger.info("restored step %d from host memory", result[0])
            TrainerEvents.ckpt_restore(result[0], "memory")
            return result
        result = self._load_from_storage(step, sharding_tree)
        if result is not None:
            logger.info("restored step %d from storage", result[0])
            TrainerEvents.ckpt_restore(result[0], "storage")
        return result

    def _load_from_memory(self, step: Optional[int] = None):
        try:
            fault_point("ckpt.restore.memory", step=step)
        except FaultInjected:
            # Chaos: the host (and its shm) was replaced — there is no
            # memory image to restore; storage must carry the recovery.
            logger.warning("chaos: shm image treated as lost")
            return None
        mem_step = self._shm.get_step()
        if mem_step < 0 or (step is not None and mem_step != step):
            return None
        loaded = self._shm.load_state_dict()
        if loaded is None:
            return None
        mem_step, state, meta = loaded
        if self._is_foreign_image(meta):
            # Leftover segment from another job sharing the shm name
            # (default JOB_NAME, reused dev box): not our checkpoint.
            logger.warning(
                "ignoring shm image of foreign checkpoint %s",
                meta.get("ckpt_dir"),
            )
            return None
        if meta.get("num_processes") != self._ctx.num_processes:
            # World changed: per-process shm images do not cover the same
            # index set; storage has the complete picture.
            return None
        state = assemble_sharded_leaves(state)
        if state is None:
            return None
        return mem_step, state, meta

    def _load_from_storage(
        self, step: Optional[int] = None, sharding_tree=None
    ):
        """Restore the requested (or tracker) step; when that step's
        shard files are torn/corrupt/incomplete AND no explicit step was
        demanded, fall back to the newest earlier step dir that still
        restores — a torn write must cost one checkpoint interval, not
        the job (docs/DESIGN.md §26 invariant 2). Explicit ``step``
        requests never silently substitute a different step."""
        target = step
        if target is None:
            target = ckpt_storage.read_tracker(self.checkpoint_dir)
        if target < 0:
            return None
        candidates = [target]
        if step is None:
            candidates += [
                s
                for s in sorted(
                    ckpt_storage.list_step_dirs(self.checkpoint_dir),
                    reverse=True,
                )
                if s < target
            ][: self._restore_fallback_steps]
        for i, cand in enumerate(candidates):
            metas = ckpt_storage.load_step_meta(self.checkpoint_dir, cand)
            if not metas:
                continue
            start = time.time()
            result = load_global_state(
                self.checkpoint_dir, cand, metas, sharding_tree
            )
            if result is None:
                self._restore_rejected.inc()
                logger.error(
                    "checkpoint step %d is not restorable (torn/corrupt/"
                    "incomplete shards); trying an earlier step", cand
                )
                continue
            if i > 0:
                logger.warning(
                    "restored FALLBACK step %d (newest step %d was "
                    "unrestorable)", cand, target
                )
            elapsed = max(time.time() - start, 1e-9)
            nbytes = _state_local_nbytes(result[1])
            self._restore_hist.observe(elapsed)
            self._restore_bytes.inc(nbytes)
            self._restore_bw_hist.observe(nbytes / 1e6 / elapsed)
            return result
        return None

    def _is_foreign_image(self, meta: dict) -> bool:
        stamped = meta.get("ckpt_dir")
        return stamped is not None and stamped != os.path.realpath(
            self.checkpoint_dir
        )

    def latest_step(self) -> int:
        """Newest restorable step (max of shm image and storage tracker).
        A foreign job's shm image is not restorable by us and must not
        be advertised."""
        mem_step = -1
        meta = self._shm.load_meta()
        if meta is not None and not self._is_foreign_image(
            meta.get("user_meta", {})
        ):
            mem_step = meta.get("step", -1)
        return max(
            mem_step,
            ckpt_storage.read_tracker(self.checkpoint_dir),
        )

    def close(self):
        drained = self.wait_async_save(timeout=60.0)
        with self._snap_cond:
            self._writer_stop = True
            self._snap_cond.notify_all()
        if not drained:
            logger.error(
                "async snapshot did not drain cleanly before close; the "
                "newest launched step may not be restorable from memory"
            )
        # Let the writer finish/exit before closing shm under it.
        if self._writer_thread is not None:
            self._writer_thread.join(timeout=10.0)
            if self._writer_thread.is_alive():
                # Never close the segment under an in-progress write: a
                # leaked handle beats a torn snapshot. The daemon thread
                # dies with the process.
                logger.error(
                    "ckpt writer still running at close; leaving shm open"
                )
                return
        self._shm.close()


# --------------------------------------------------------------------------
# Reassembly helpers
# --------------------------------------------------------------------------


def assemble_sharded_leaves(state):
    """Convert {"__shards__": ...} leaf records into full numpy arrays.

    Returns None if any leaf's shards don't cover its global shape (the
    caller must then use storage, which has every process's shards).
    """
    import jax

    incomplete = []

    def fix(leaf):
        if not (isinstance(leaf, dict) and "__shards__" in leaf):
            return leaf
        assembled = _assemble_from_shards(
            leaf["__global_shape__"], leaf["__dtype__"], leaf["__shards__"]
        )
        if assembled is None:
            incomplete.append(leaf["__global_shape__"])
        return assembled

    is_record = lambda x: isinstance(x, dict) and "__shards__" in x  # noqa: E731
    out = jax.tree_util.tree_map(fix, state, is_leaf=is_record)
    if incomplete:
        return None
    return out


def _assemble_from_shards(global_shape, dtype_name, shards):
    from dlrover_tpu.flash_ckpt.shm_handler import _np_dtype

    dtype = _np_dtype(dtype_name)
    out = np.zeros(global_shape, dtype=dtype)
    covered = np.zeros(global_shape, dtype=bool) if global_shape else None
    for bounds, arr in shards:
        slices = bounds_to_slices(bounds)
        out[slices] = arr
        if covered is not None:
            covered[slices] = True
    if covered is not None and not covered.all():
        return None
    return out


def _state_local_nbytes(state) -> int:
    """Bytes this process materialized for ``state``: DISTINCT
    addressable shard bytes for jax Arrays (the partial-restore
    footprint; replicas of the same index dedupe — the restore read
    them from disk once), full nbytes for host arrays."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(state):
        if isinstance(leaf, jax.Array):
            try:
                seen = set()
                for s in leaf.addressable_shards:
                    key = tuple(
                        (sl.start, sl.stop) for sl in s.index
                    )
                    if key in seen:
                        continue
                    seen.add(key)
                    total += s.data.nbytes
                continue
            except Exception:
                pass
        if hasattr(leaf, "nbytes"):
            total += leaf.nbytes
    return total


def _norm_bounds(bounds, global_shape):
    """Close open slice ends: ((0,None),) over (8,) -> ((0,8),)."""
    return tuple(
        (lo if lo is not None else 0, hi if hi is not None else dim)
        for (lo, hi), dim in zip(bounds, global_shape)
    )


def _norm_index(index, global_shape):
    """Normalize a tuple of slices (a jax shard index) to closed bounds."""
    return tuple(
        (s.start if s.start is not None else 0,
         s.stop if s.stop is not None else dim)
        for s, dim in zip(index, global_shape)
    )


def _intersect_bounds(a, b):
    """Intersection of two closed bounds tuples, or None if empty."""
    out = []
    for (a0, a1), (b0, b1) in zip(a, b):
        lo, hi = max(a0, b0), min(a1, b1)
        if lo >= hi:
            return None
        out.append((lo, hi))
    return tuple(out)


def _bounds_volume(b) -> int:
    vol = 1
    for lo, hi in b:
        vol *= hi - lo
    return vol


def _tiles_exactly(region, inters) -> bool:
    """True if ``inters`` (intersections already clipped to ``region``)
    are pairwise disjoint and their volumes sum to the region's — an
    O(h^2) proof of full coverage that replaces an O(region-bytes)
    boolean mask for the common disjoint-shard layout."""
    if sum(_bounds_volume(b) for b in inters) != _bounds_volume(region):
        return False
    for i in range(len(inters)):
        for j in range(i + 1, len(inters)):
            if _intersect_bounds(inters[i], inters[j]) is not None:
                return False
    return True


def _needed_region_bounds(sharding, global_shape, addressable=None):
    """The distinct index bounds THIS process must materialize for a
    leaf under ``sharding`` — the partial-restore index set. Replicas
    collapse; non-addressable devices' shards are never read."""
    if addressable is None:
        addressable = sharding.addressable_devices
    imap = sharding.devices_indices_map(tuple(global_shape))
    needed = {}
    for dev, idx in imap.items():
        if dev not in addressable:
            continue
        needed[_norm_index(idx, global_shape)] = True
    return list(needed)


class _LazyReaders:
    """Opens a process's shard file on FIRST use, not up front: after a
    re-mesh on a large world, a partial restore may need bytes from a
    handful of the N proc files — eagerly opening all N (open + header
    parse + stat each, per restoring process, against shared storage)
    would put O(world size) metadata I/O on the hot path."""

    def __init__(self, checkpoint_dir: str, step: int, pids):
        import threading

        self._dir = checkpoint_dir
        self._step = step
        self._pids = set(pids)
        self._lock = threading.Lock()
        self._open: Dict[int, Any] = {}
        self._missing = set()

    def get(self, pid: int):
        if pid not in self._pids or pid in self._missing:
            return None
        with self._lock:
            reader = self._open.get(pid)
            if reader is None and pid not in self._missing:
                reader = ckpt_storage.open_proc_shards(
                    self._dir, self._step, pid
                )
                if reader is None:
                    self._missing.add(pid)
                else:
                    self._open[pid] = reader
            return reader

    def close_all(self):
        with self._lock:
            for reader in self._open.values():
                reader.close()
            self._open.clear()


def _index_shard_locations(metas: Dict[int, dict]):
    """Build (leaf_info, locations) from per-process metas.

    leaf_info[i] = (global_shape, dtype_name);
    locations[i] = [(pid, key, closed shard bounds), ...].
    """
    first = metas[min(metas)]
    num_leaves = len(first["leaves"])
    leaf_info = [None] * num_leaves
    locations = [[] for _ in range(num_leaves)]
    for pid, meta in sorted(metas.items()):
        for leaf_meta in meta["leaves"]:
            i = leaf_meta.leaf_id
            gshape = tuple(leaf_meta.global_shape)
            leaf_info[i] = (gshape, leaf_meta.dtype)
            for j, shard in enumerate(leaf_meta.shards):
                locations[i].append(
                    (pid, f"leaf{i}_shard{j}",
                     _norm_bounds(shard.index, gshape))
                )
    return leaf_info, locations


def _assemble_leaf_regions(info, shard_locs, readers, region_bounds_list):
    """Read exactly the byte ranges covering ``region_bounds_list`` for
    one leaf. Allocates O(region bytes) host memory — never the global
    shape (the partial-restore guarantee). Returns {bounds: array}, or
    None if any region is not fully covered by the stored shards.
    """
    from dlrover_tpu.flash_ckpt.shm_handler import _np_dtype

    gshape, dtype_name = info
    dtype = _np_dtype(dtype_name)
    regions = {}
    for rb in region_bounds_list:
        shape = tuple(hi - lo for lo, hi in rb)
        if 0 in shape:
            # Zero-size leaf (empty optimizer slot): there are no bytes
            # to read and no coverage to prove — _intersect_bounds
            # treats empty extents as "no hit", which must not make the
            # whole checkpoint unrestorable.
            regions[rb] = np.empty(shape, dtype=dtype)
            continue
        # Which stored shards intersect this region? Identical
        # intersections dedupe (a leaf replicated across P processes
        # appears in every proc file — reading it P times would multiply
        # disk I/O by P and the overlap would force the coverage mask).
        hits = []
        seen_inter = set()
        for pid, key, sb in shard_locs:
            # Intersect on the METADATA bounds before touching the
            # reader: with lazy opening, a proc file none of whose
            # shards intersect our regions is never even opened.
            if shape:
                inter = _intersect_bounds(rb, sb)
                if inter is None or inter in seen_inter:
                    continue
            reader = readers.get(pid)
            if reader is None or key not in reader:
                continue
            if not shape:
                hits.append((reader, key, (), ()))
                continue
            seen_inter.add(inter)
            hits.append((reader, key, inter, sb))
        if not hits:
            return None
        out = np.empty(shape, dtype=dtype)
        if not shape:
            reader, key, _, _ = hits[0]
            reader.read_slice_into(key, (), out, verify=True)
            regions[rb] = out
            continue
        # Coverage proof without the O(region) bool mask when possible:
        # disjoint intersections whose volumes sum to the region volume
        # tile it exactly (the normal sharded-save layout). The mask is
        # only materialized for overlapping shards (replicas straddling
        # a region boundary).
        exact = _tiles_exactly(rb, [h[2] for h in hits])
        covered = None if exact else np.zeros(shape, dtype=bool)
        for reader, key, inter, sb in hits:
            src = tuple(
                slice(lo - s0, hi - s0)
                for (lo, hi), (s0, _) in zip(inter, sb)
            )
            dst = tuple(
                slice(lo - r0, hi - r0)
                for (lo, hi), (r0, _) in zip(inter, rb)
            )
            # Full-shard reads checksum the copied bytes (the format's
            # bitflip guarantee); sub-range reads can't without reading
            # the whole shard, which would defeat partial restore.
            reader.read_slice_into(
                key, src, out[dst], verify=(inter == sb)
            )
            if covered is not None:
                covered[dst] = True
        if covered is not None and not covered.all():
            return None
        regions[rb] = out
    return regions


def load_global_state(
    checkpoint_dir: str,
    step: int,
    metas: Dict[int, dict],
    sharding_tree=None,
):
    """Assemble the state for ``step`` from the per-process shard files.

    Without ``sharding_tree``: full global numpy leaves (every byte is
    read), leaf reads fanned out over a thread pool.

    With ``sharding_tree`` (matching pytree of ``jax.sharding.Sharding``):
    sharding-aware partial restore — each leaf's addressable index set is
    computed from its sharding, ONLY the intersecting byte ranges are
    read from the mmap'd shard files, and leaves come back as jax Arrays
    built with ``jax.make_array_from_callback``. Host RAM is O(local
    bytes), and completed leaves stream into device transfer while later
    leaves are still on disk (pipelined restore).
    """
    import jax

    from dlrover_tpu.common.serialize import loads_pytree
    from dlrover_tpu.flash_ckpt.raw_format import ShardCorruptionError

    first = metas[min(metas)]
    treedef = loads_pytree(first["treedef"])
    user_meta = first.get("user_meta", {})
    leaf_info, locations = _index_shard_locations(metas)
    num_leaves = len(leaf_info)

    shardings = None
    if sharding_tree is not None:
        try:
            shardings = treedef.flatten_up_to(sharding_tree)
        except ValueError as e:
            logger.warning(
                "sharding_tree does not match the checkpoint's structure "
                "(%s); falling back to full-state restore", e
            )

    readers = _LazyReaders(checkpoint_dir, step, metas)
    try:

        def region_bounds_for(i):
            gshape = leaf_info[i][0]
            sharding = shardings[i] if shardings is not None else None
            if sharding is None:
                return [tuple((0, d) for d in gshape)]  # full leaf
            return _needed_region_bounds(sharding, gshape)

        leaves = [None] * num_leaves
        from concurrent.futures import ThreadPoolExecutor, as_completed

        # Pipelined restore: the pool only READS (host region buffers);
        # jax-array construction runs here on the caller's thread as
        # each leaf's bytes land, so H2D transfer of early leaves
        # overlaps disk reads of later ones.
        with ThreadPoolExecutor(
            max_workers=ckpt_storage.io_threads(max(num_leaves, 1)),
            thread_name_prefix="ckpt-restore",
        ) as pool:
            futures = {
                pool.submit(
                    _assemble_leaf_regions,
                    leaf_info[i],
                    locations[i],
                    readers,
                    region_bounds_for(i),
                ): i
                for i in range(num_leaves)
                if leaf_info[i] is not None
            }
            for fut in as_completed(futures):
                i = futures[fut]
                regions = fut.result()
                if regions is None:
                    continue
                gshape = leaf_info[i][0]
                sharding = (
                    shardings[i] if shardings is not None else None
                )
                if sharding is None:
                    leaves[i] = regions[tuple((0, d) for d in gshape)]
                    continue

                def cb(idx, _regions=regions, _gshape=gshape):
                    return _regions[_norm_index(idx, _gshape)]

                leaves[i] = jax.make_array_from_callback(
                    gshape, sharding, cb
                )
    except ShardCorruptionError as e:
        logger.error(
            "refusing corrupt checkpoint step %d: %s", step, e
        )
        return None
    finally:
        readers.close_all()
    if any(l is None for l in leaves):
        return None
    state = jax.tree_util.tree_unflatten(treedef, leaves)
    return step, state, user_meta


def load_state_regions(
    checkpoint_dir: str,
    step: int,
    regions_by_leaf: Optional[Dict[int, list]] = None,
):
    """Explicit-region partial restore (the live-rescale path for hosts
    that address their shards by byte range rather than a jax sharding).

    ``regions_by_leaf``: leaf_id -> list of closed bounds tuples
    (``((lo, hi), ...)`` per dim); leaves absent from the map are read
    in full. Reads ONLY the intersecting byte ranges from the step's
    mmap'd shard files through the same lazy-reader machinery the
    sharding-tree restore uses — after an N→M re-mesh each survivor
    pays O(its new bytes), not O(global state).

    Returns ``(step, leaves, user_meta)`` with
    ``leaves[leaf_id] = {bounds: np.ndarray}``, or None when the step is
    missing/torn/not fully covering a requested region.
    """
    from dlrover_tpu.flash_ckpt.raw_format import ShardCorruptionError

    metas = ckpt_storage.load_step_meta(checkpoint_dir, step)
    if not metas:
        return None
    first = metas[min(metas)]
    user_meta = first.get("user_meta", {})
    leaf_info, locations = _index_shard_locations(metas)
    regions_by_leaf = regions_by_leaf or {}
    readers = _LazyReaders(checkpoint_dir, step, metas)
    leaves: Dict[int, dict] = {}
    try:
        for i, info in enumerate(leaf_info):
            if info is None:
                return None
            gshape = info[0]
            bounds_list = regions_by_leaf.get(i)
            if bounds_list is None:
                bounds_list = [tuple((0, d) for d in gshape)]
            bounds_list = [
                tuple(tuple(b) for b in bounds) for bounds in bounds_list
            ]
            regions = _assemble_leaf_regions(
                info, locations[i], readers, bounds_list
            )
            if regions is None:
                logger.error(
                    "step %d leaf %d: requested regions not covered by "
                    "stored shards", step, i
                )
                return None
            leaves[i] = regions
    except ShardCorruptionError as e:
        logger.error("refusing corrupt checkpoint step %d: %s", step, e)
        return None
    finally:
        readers.close_all()
    return step, leaves, user_meta


def to_device_state(np_state, sharding_tree=None):
    """Put a numpy pytree onto devices under the current mesh.

    sharding_tree: matching pytree of ``jax.sharding.Sharding`` (or None
    for single-device default placement). Each process materializes only
    its addressable shards — the resharding restore path ("universal
    checkpoint" analogue).

    A single batched ``device_put`` lets the runtime pipeline all leaf
    transfers (~10x faster restore than per-leaf puts on slow links);
    the per-leaf ``make_array_from_callback`` path is the fallback for
    runtimes that reject global host arrays under non-addressable
    shardings.

    Leaves that are ALREADY placed jax Arrays under their requested
    sharding (the partial-restore path returns these) pass through
    untouched — re-putting them would be a no-op at best.
    """
    import jax

    leaves = jax.tree_util.tree_leaves(np_state)
    if leaves and all(isinstance(l, jax.Array) for l in leaves):
        if sharding_tree is None:
            return np_state
        placed = jax.tree_util.tree_leaves(sharding_tree)
        if len(placed) == len(leaves) and all(
            getattr(l, "sharding", None) == s
            for l, s in zip(leaves, placed)
        ):
            return np_state

    if sharding_tree is None:
        return jax.tree_util.tree_map(jax.numpy.asarray, np_state)

    try:
        from jax.errors import JaxRuntimeError as _XlaRuntimeError
    except ImportError:  # older jaxlib spelling
        from jaxlib.xla_extension import XlaRuntimeError as _XlaRuntimeError

    try:
        return jax.device_put(np_state, sharding_tree)
    except (ValueError, NotImplementedError, _XlaRuntimeError) as e:
        # The known "runtime rejects global host arrays under
        # non-addressable shardings" shapes only — anything else (host
        # OOM, dtype corruption) must surface, not be absorbed by the
        # slower per-leaf fallback.
        logger.warning(
            "batched device_put restore unavailable (%s: %s); using "
            "per-leaf transfers",
            type(e).__name__,
            e,
        )

    def put(arr, sharding):
        arr = np.asarray(arr)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx]
        )

    return jax.tree_util.tree_map(put, np_state, sharding_tree)


_fetch_probe = None


def fetch_barrier(tree) -> float:
    """Reliable completion barrier over every leaf of ``tree``.

    ``jax.block_until_ready`` can return before async dispatch actually
    lands on remote-attached backends (measured on the axon tunnel), so
    restore timings taken with it silently leak the H2D cost into
    whatever runs next. This fetches ONE element of every leaf through a
    single jitted reduction — one dispatch, and the host fetch cannot
    complete until every input transfer has."""
    import jax
    import jax.numpy as jnp

    global _fetch_probe
    if _fetch_probe is None:
        def probe(leaves):
            acc = jnp.zeros((), jnp.float32)
            for leaf in leaves:
                acc = acc + jnp.sum(
                    jnp.ravel(leaf)[:1].astype(jnp.float32)
                )
            return acc

        _fetch_probe = jax.jit(probe)
    leaves = [
        x for x in jax.tree_util.tree_leaves(tree)
        if hasattr(x, "dtype")
    ]
    return float(_fetch_probe(leaves))
