"""Checkpoint storage backends + on-disk layout.

Parity: reference dlrover/python/common/storage.py (CheckpointStorage,
PosixDiskStorage) and the commit protocol of ckpt_saver.py:914-1078
(step dirs, done markers, rank0 atomic tracker update).

Layout under ``checkpoint_dir``:

    checkpoint-<step>/
        proc-<process_id>.npz     # leaf shards written by that process
        proc-<process_id>.meta    # pickled shard metadata
        .done/node-<rank>.done    # per-node completion markers
    latest_checkpointed_iteration.txt   # tracker, atomically replaced
"""

import os
import pickle
import shutil
from abc import ABC, abstractmethod
from typing import Dict, List, Optional

import numpy as np

from dlrover_tpu.common.constants import CheckpointConstant
from dlrover_tpu.common.log import logger


class CheckpointStorage(ABC):
    @abstractmethod
    def write(self, content: bytes, path: str):
        ...

    @abstractmethod
    def read(self, path: str) -> Optional[bytes]:
        ...

    @abstractmethod
    def exists(self, path: str) -> bool:
        ...

    @abstractmethod
    def listdir(self, path: str) -> List[str]:
        ...

    @abstractmethod
    def makedirs(self, path: str):
        ...

    @abstractmethod
    def remove(self, path: str):
        ...


class PosixDiskStorage(CheckpointStorage):
    def write(self, content: bytes, path: str):
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(content)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def read(self, path: str) -> Optional[bytes]:
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return f.read()

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def listdir(self, path: str) -> List[str]:
        return os.listdir(path) if os.path.isdir(path) else []

    def makedirs(self, path: str):
        os.makedirs(path, exist_ok=True)

    def remove(self, path: str):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.unlink(path)


def step_dir(checkpoint_dir: str, step: int) -> str:
    return os.path.join(
        checkpoint_dir, f"{CheckpointConstant.STEP_DIR_PREFIX}{step}"
    )


def tracker_path(checkpoint_dir: str) -> str:
    return os.path.join(checkpoint_dir, CheckpointConstant.TRACKER_FILE)


def read_tracker(checkpoint_dir: str) -> int:
    path = tracker_path(checkpoint_dir)
    try:
        with open(path) as f:
            return int(f.read().strip())
    except (FileNotFoundError, ValueError):
        return -1


def write_tracker(checkpoint_dir: str, step: int):
    os.makedirs(checkpoint_dir, exist_ok=True)
    # Per-process tmp name: concurrent committers (multi-node standalone)
    # must not os.replace each other's tmp files out from under them.
    tmp = tracker_path(checkpoint_dir) + f".tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            f.write(str(step))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, tracker_path(checkpoint_dir))
    except OSError:
        # Unique names never self-overwrite: reclaim the orphan.
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def persist_node_shards(
    checkpoint_dir: str,
    step: int,
    node_rank: int,
    proc_payloads: Dict[int, dict],
):
    """Write one node's processes' shard files + its done marker.

    proc_payloads: process_id -> {"arrays": {name: np.ndarray},
    "meta": picklable}.
    """
    sdir = step_dir(checkpoint_dir, step)
    os.makedirs(sdir, exist_ok=True)
    for process_id, payload in proc_payloads.items():
        npz_tmp = os.path.join(sdir, f".proc-{process_id}.npz.tmp")
        with open(npz_tmp, "wb") as f:
            np.savez(f, **payload["arrays"])
            f.flush()
            os.fsync(f.fileno())
        os.replace(npz_tmp, os.path.join(sdir, f"proc-{process_id}.npz"))
        meta_tmp = os.path.join(sdir, f".proc-{process_id}.meta.tmp")
        with open(meta_tmp, "wb") as f:
            pickle.dump(payload["meta"], f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(meta_tmp, os.path.join(sdir, f"proc-{process_id}.meta"))
    done_dir = os.path.join(sdir, CheckpointConstant.DONE_DIR)
    os.makedirs(done_dir, exist_ok=True)
    done_tmp = os.path.join(done_dir, f".node-{node_rank}.tmp")
    with open(done_tmp, "w") as f:
        f.write("1")
    os.replace(done_tmp, os.path.join(done_dir, f"node-{node_rank}.done"))


def nodes_done(checkpoint_dir: str, step: int) -> List[int]:
    done_dir = os.path.join(
        step_dir(checkpoint_dir, step), CheckpointConstant.DONE_DIR
    )
    ranks = []
    if os.path.isdir(done_dir):
        for name in os.listdir(done_dir):
            if name.startswith("node-") and name.endswith(".done"):
                try:
                    ranks.append(int(name[5:-5]))
                except ValueError:
                    pass
    return sorted(ranks)


def load_step_meta(checkpoint_dir: str, step: int) -> Dict[int, dict]:
    """process_id -> meta for every proc file present."""
    # Restricted unpickle: checkpoint dirs may live on shared storage.
    from dlrover_tpu.common.serialize import loads_pytree

    sdir = step_dir(checkpoint_dir, step)
    metas: Dict[int, dict] = {}
    if not os.path.isdir(sdir):
        return metas
    for name in os.listdir(sdir):
        if name.startswith("proc-") and name.endswith(".meta"):
            pid = int(name[5:-5])
            with open(os.path.join(sdir, name), "rb") as f:
                metas[pid] = loads_pytree(f.read())
    return metas


def load_proc_arrays(checkpoint_dir: str, step: int, process_id: int):
    path = os.path.join(step_dir(checkpoint_dir, step), f"proc-{process_id}.npz")
    if not os.path.exists(path):
        return None
    return np.load(path, allow_pickle=False)


def list_step_dirs(checkpoint_dir: str) -> List[int]:
    steps = []
    if os.path.isdir(checkpoint_dir):
        for name in os.listdir(checkpoint_dir):
            if name.startswith(CheckpointConstant.STEP_DIR_PREFIX):
                try:
                    steps.append(
                        int(name[len(CheckpointConstant.STEP_DIR_PREFIX):])
                    )
                except ValueError:
                    pass
    return sorted(steps)


class KeepStepIntervalDeletionStrategy:
    """Keep the newest ``max_to_keep`` steps AND every step that is a
    multiple of ``keep_interval`` (reference storage.py
    KeepStepIntervalStrategy): long-horizon jobs keep sparse history for
    evaluation/rollback without unbounded disk growth."""

    def __init__(self, keep_interval: int, max_to_keep: int = 3):
        self.keep_interval = max(keep_interval, 1)
        self.max_to_keep = max_to_keep

    def clean_up(self, checkpoint_dir: str):
        steps = list_step_dirs(checkpoint_dir)
        committed = read_tracker(checkpoint_dir)
        # steps[-0:] would be the WHOLE list, not "none recent".
        recent = (
            set(steps[-self.max_to_keep :]) if self.max_to_keep > 0 else set()
        )
        for s in steps:
            if s == committed or s in recent:
                continue
            if s % self.keep_interval == 0:
                continue
            logger.info("removing old checkpoint step %d", s)
            shutil.rmtree(step_dir(checkpoint_dir, s), ignore_errors=True)


class KeepLatestDeletionStrategy:
    """Retain the newest ``max_to_keep`` step dirs (reference
    storage.py deletion strategies)."""

    def __init__(self, max_to_keep: int = 3):
        self.max_to_keep = max_to_keep

    def clean_up(self, checkpoint_dir: str):
        steps = list_step_dirs(checkpoint_dir)
        committed = read_tracker(checkpoint_dir)
        victims = [s for s in steps if s != committed][: -self.max_to_keep]
        for s in victims:
            if s == committed:
                continue
            logger.info("removing old checkpoint step %d", s)
            shutil.rmtree(step_dir(checkpoint_dir, s), ignore_errors=True)
