"""Checkpoint storage backends + on-disk layout.

Parity: reference dlrover/python/common/storage.py (CheckpointStorage,
PosixDiskStorage) and the commit protocol of ckpt_saver.py:914-1078
(step dirs, done markers, rank0 atomic tracker update).

Layout under ``checkpoint_dir``:

    checkpoint-<step>/
        proc-<process_id>.raw     # v1 raw shard file (see raw_format.py)
        proc-<process_id>.meta    # pickled shard metadata (treedef etc.)
        .done/node-<rank>.done    # per-node completion markers
    latest_checkpointed_iteration.txt   # tracker, atomically replaced

Read compat: step dirs written before the raw format carry
``proc-<pid>.npz`` instead; :func:`open_proc_shards` transparently falls
back to a zip-backed reader for those, so old checkpoints stay
restorable (docs/DESIGN.md §23).
"""

import contextlib
import os
import pickle
import shutil
from abc import ABC, abstractmethod
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional

import numpy as np

from dlrover_tpu.common.constants import CheckpointConstant
from dlrover_tpu.common.env_utils import get_env_int
from dlrover_tpu.common.log import logger
from dlrover_tpu.fault import FaultAction, fault_point
from dlrover_tpu.flash_ckpt.raw_format import (
    RAW_SUFFIX,
    RawShardReader,
    ShardCorruptionError,
    write_raw_shards,
)

RAW_FORMAT = "raw"
NPZ_FORMAT = "npz"


def io_threads(n_tasks: int) -> int:
    """Thread-pool width for checkpoint file I/O. Disk writes/reads are
    GIL-releasing and spend much of their time stalled on page faults /
    device queues, so 2x-cpu oversubscription (capped at 8) measures
    fastest even on small hosts; DLROVER_TPU_CKPT_IO_THREADS overrides."""
    configured = get_env_int("DLROVER_TPU_CKPT_IO_THREADS", 0)
    if configured > 0:
        return max(1, min(configured, n_tasks))
    return max(1, min(n_tasks, 2 * (os.cpu_count() or 2), 8))


def fsync_dir(path: str):
    """fsync a directory so renames into it survive a crash."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class CheckpointStorage(ABC):
    @abstractmethod
    def write(self, content: bytes, path: str):
        ...

    @abstractmethod
    def read(self, path: str) -> Optional[bytes]:
        ...

    @abstractmethod
    def exists(self, path: str) -> bool:
        ...

    @abstractmethod
    def listdir(self, path: str) -> List[str]:
        ...

    @abstractmethod
    def makedirs(self, path: str):
        ...

    @abstractmethod
    def remove(self, path: str):
        ...


class PosixDiskStorage(CheckpointStorage):
    def write(self, content: bytes, path: str):
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(content)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def read(self, path: str) -> Optional[bytes]:
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return f.read()

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def listdir(self, path: str) -> List[str]:
        return os.listdir(path) if os.path.isdir(path) else []

    def makedirs(self, path: str):
        os.makedirs(path, exist_ok=True)

    def remove(self, path: str):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.unlink(path)


def step_dir(checkpoint_dir: str, step: int) -> str:
    return os.path.join(
        checkpoint_dir, f"{CheckpointConstant.STEP_DIR_PREFIX}{step}"
    )


def tracker_path(checkpoint_dir: str) -> str:
    return os.path.join(checkpoint_dir, CheckpointConstant.TRACKER_FILE)


def read_tracker(checkpoint_dir: str) -> int:
    path = tracker_path(checkpoint_dir)
    try:
        with open(path) as f:
            return int(f.read().strip())
    except (FileNotFoundError, ValueError):
        return -1


def write_tracker(checkpoint_dir: str, step: int):
    os.makedirs(checkpoint_dir, exist_ok=True)
    # Per-process tmp name: concurrent committers (multi-node standalone)
    # must not os.replace each other's tmp files out from under them.
    tmp = tracker_path(checkpoint_dir) + f".tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            f.write(str(step))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, tracker_path(checkpoint_dir))
        # The rename is the commit point: make it durable, not just the
        # file contents (a crash could otherwise roll the tracker back).
        fsync_dir(checkpoint_dir)
    except OSError:
        # Unique names never self-overwrite: reclaim the orphan.
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _tear_file(path: str, nbytes: int):
    """Chaos: chop ``nbytes`` off a just-landed shard file, simulating a
    write torn by a crash/power cut after the rename. The reader's
    open-time length/checksum validation must reject the file."""
    size = os.path.getsize(path)
    keep = max(size - max(nbytes, 1), 0)
    with open(path, "r+b") as f:
        f.truncate(keep)
        f.flush()
        os.fsync(f.fileno())
    logger.warning(
        "chaos: tore %d bytes off %s (%d -> %d)", size - keep, path,
        size, keep,
    )


def _persist_one_proc(sdir: str, step: int, process_id: int, payload: dict,
                      fmt: str):
    """Write one process's shard + meta files (tmp + rename, one fsync
    per file). Runs on a persist-pool thread."""
    fault_point("ckpt.persist.proc_file", step=step, process_id=process_id)
    if fmt == NPZ_FORMAT:
        # Legacy writer: kept for the A/B bench and compat tests only.
        npz_tmp = os.path.join(sdir, f".proc-{process_id}.npz.tmp")
        with open(npz_tmp, "wb") as f:
            np.savez(f, **payload["arrays"])
            f.flush()
            os.fsync(f.fileno())
        os.replace(npz_tmp, os.path.join(sdir, f"proc-{process_id}.npz"))
    else:
        raw_tmp = os.path.join(sdir, f".proc-{process_id}{RAW_SUFFIX}.tmp")
        bounds = payload.get("shard_bounds") or _bounds_from_meta(
            payload.get("meta")
        )
        write_raw_shards(
            raw_tmp, step, process_id, payload["arrays"], bounds
        )
        raw_final = os.path.join(sdir, f"proc-{process_id}{RAW_SUFFIX}")
        os.replace(raw_tmp, raw_final)
        directive = fault_point(
            "ckpt.persist.torn_write",
            step=step, process_id=process_id, path=raw_final,
        )
        if directive and directive.get("action") == FaultAction.TRUNCATE:
            _tear_file(raw_final, directive.get("truncate_bytes", 64))
    meta_tmp = os.path.join(sdir, f".proc-{process_id}.meta.tmp")
    with open(meta_tmp, "wb") as f:
        pickle.dump(payload["meta"], f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(meta_tmp, os.path.join(sdir, f"proc-{process_id}.meta"))


def _bounds_from_meta(meta) -> Dict[str, tuple]:
    """shard key -> global slice bounds, from the pickled LeafMeta list
    (so the raw header's JSON index is self-describing)."""
    bounds: Dict[str, tuple] = {}
    if not isinstance(meta, dict):
        return bounds
    for leaf_meta in meta.get("leaves", []):
        for j, shard in enumerate(leaf_meta.shards):
            bounds[f"leaf{leaf_meta.leaf_id}_shard{j}"] = shard.index
    return bounds


def persist_node_shards(
    checkpoint_dir: str,
    step: int,
    node_rank: int,
    proc_payloads: Dict[int, dict],
    fmt: str = RAW_FORMAT,
):
    """Write one node's processes' shard files + its done marker.

    proc_payloads: process_id -> {"arrays": {name: np.ndarray},
    "meta": picklable}. Proc files fan out over a thread pool (the
    writes are GIL-releasing I/O); each file is fsynced once, and the
    step dir is fsynced after the renames so the commit protocol's
    done-marker implies durable shard files.
    """
    sdir = step_dir(checkpoint_dir, step)
    os.makedirs(sdir, exist_ok=True)
    if proc_payloads:
        with ThreadPoolExecutor(
            max_workers=io_threads(len(proc_payloads)),
            thread_name_prefix="ckpt-persist",
        ) as pool:
            futures = [
                pool.submit(
                    _persist_one_proc, sdir, step, pid, payload, fmt
                )
                for pid, payload in proc_payloads.items()
            ]
            for fut in futures:
                fut.result()  # surface the first failure
    fsync_dir(sdir)
    done_dir = os.path.join(sdir, CheckpointConstant.DONE_DIR)
    os.makedirs(done_dir, exist_ok=True)
    done_tmp = os.path.join(done_dir, f".node-{node_rank}.tmp")
    with open(done_tmp, "w") as f:
        f.write("1")
        f.flush()
        os.fsync(f.fileno())
    os.replace(done_tmp, os.path.join(done_dir, f"node-{node_rank}.done"))
    fsync_dir(done_dir)


def nodes_done(checkpoint_dir: str, step: int) -> List[int]:
    done_dir = os.path.join(
        step_dir(checkpoint_dir, step), CheckpointConstant.DONE_DIR
    )
    ranks = []
    if os.path.isdir(done_dir):
        for name in os.listdir(done_dir):
            if name.startswith("node-") and name.endswith(".done"):
                try:
                    ranks.append(int(name[5:-5]))
                except ValueError:
                    pass
    return sorted(ranks)


def load_step_meta(checkpoint_dir: str, step: int) -> Dict[int, dict]:
    """process_id -> meta for every proc file present."""
    # Restricted unpickle: checkpoint dirs may live on shared storage.
    from dlrover_tpu.common.serialize import loads_pytree

    sdir = step_dir(checkpoint_dir, step)
    metas: Dict[int, dict] = {}
    if not os.path.isdir(sdir):
        return metas
    for name in os.listdir(sdir):
        if name.startswith("proc-") and name.endswith(".meta"):
            pid = int(name[5:-5])
            with open(os.path.join(sdir, name), "rb") as f:
                metas[pid] = loads_pytree(f.read())
    return metas


class NpzShardReader:
    """Read-compat adapter over a legacy ``proc-<pid>.npz`` step file,
    presenting the same surface as :class:`RawShardReader`. The zip
    container has no checksums and no sub-range reads: ``read_slice``
    inflates the full shard and slices (correct, just not partial-I/O).
    """

    step = -1  # the zip carries no step stamp; the dir name does
    process_id = -1

    def __init__(self, path: str):
        import threading

        self.path = path
        self._npz = np.load(path, allow_pickle=False)
        # NpzFile shares one zip file handle; concurrent reads from the
        # restore pool would interleave seeks.
        self._read_lock = threading.Lock()
        # Partial restore makes one read PER INTERSECTING REGION; the
        # zip can only inflate whole members, so cache each inflated
        # member or an N-region leaf costs N full decompressions (all
        # serialized under the lock). Dropped on close.
        self._cache: dict = {}
        self.bytes_read = 0

    def keys(self):
        return self._npz.files

    def __contains__(self, key: str) -> bool:
        return key in self._npz.files

    def _member(self, key: str) -> np.ndarray:
        with self._read_lock:
            arr = self._cache.get(key)
            if arr is None:
                arr = self._npz[key]  # zipfile crc-checks the inflate
                self._cache[key] = arr
            return arr

    def get(self, key: str, verify: bool = True) -> np.ndarray:
        arr = self._member(key)
        self.bytes_read += arr.nbytes
        return arr

    def read_slice(self, key: str, slices) -> np.ndarray:
        out = np.ascontiguousarray(self._member(key)[slices])
        self.bytes_read += out.nbytes
        return out

    def read_slice_into(self, key: str, slices, dest: np.ndarray,
                        verify: bool = False):
        # ``verify`` is moot here: zipfile already crc-checks every
        # member as it inflates.
        src = self._member(key)
        if slices:
            src = src[slices]
        np.copyto(dest, src)
        self.bytes_read += dest.nbytes

    def close(self):
        self._cache.clear()
        self._npz.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def open_proc_shards(checkpoint_dir: str, step: int, process_id: int):
    """Open one process's shard file for ``step``; None if absent.

    Prefers the raw v1 format; falls back to the legacy ``.npz`` layout.
    The returned reader owns a file handle / mmap — close it (it is a
    context manager) or the mapping lives until GC.
    """
    base = os.path.join(step_dir(checkpoint_dir, step), f"proc-{process_id}")
    raw_path = base + RAW_SUFFIX
    if os.path.exists(raw_path):
        return RawShardReader(raw_path)
    npz_path = base + ".npz"
    if os.path.exists(npz_path):
        return NpzShardReader(npz_path)
    return None


@contextlib.contextmanager
def load_proc_arrays(checkpoint_dir: str, step: int, process_id: int):
    """Context-managed access to one process's shard arrays (or None).

    Replaces the old leaky variant that returned a bare ``NpzFile``
    nobody closed; the handle/mmap is now released deterministically on
    exit.
    """
    reader = open_proc_shards(checkpoint_dir, step, process_id)
    try:
        yield reader
    finally:
        if reader is not None:
            reader.close()


def list_step_dirs(checkpoint_dir: str) -> List[int]:
    steps = []
    if os.path.isdir(checkpoint_dir):
        for name in os.listdir(checkpoint_dir):
            if name.startswith(CheckpointConstant.STEP_DIR_PREFIX):
                try:
                    steps.append(
                        int(name[len(CheckpointConstant.STEP_DIR_PREFIX):])
                    )
                except ValueError:
                    pass
    return sorted(steps)


class KeepStepIntervalDeletionStrategy:
    """Keep the newest ``max_to_keep`` steps AND every step that is a
    multiple of ``keep_interval`` (reference storage.py
    KeepStepIntervalStrategy): long-horizon jobs keep sparse history for
    evaluation/rollback without unbounded disk growth."""

    def __init__(self, keep_interval: int, max_to_keep: int = 3):
        self.keep_interval = max(keep_interval, 1)
        self.max_to_keep = max_to_keep

    def clean_up(self, checkpoint_dir: str):
        steps = list_step_dirs(checkpoint_dir)
        committed = read_tracker(checkpoint_dir)
        # steps[-0:] would be the WHOLE list, not "none recent".
        recent = (
            set(steps[-self.max_to_keep :]) if self.max_to_keep > 0 else set()
        )
        for s in steps:
            if s == committed or s in recent:
                continue
            if s % self.keep_interval == 0:
                continue
            logger.info("removing old checkpoint step %d", s)
            shutil.rmtree(step_dir(checkpoint_dir, s), ignore_errors=True)


class KeepLatestDeletionStrategy:
    """Retain the newest ``max_to_keep`` step dirs (reference
    storage.py deletion strategies)."""

    def __init__(self, max_to_keep: int = 3):
        self.max_to_keep = max_to_keep

    def clean_up(self, checkpoint_dir: str):
        steps = list_step_dirs(checkpoint_dir)
        committed = read_tracker(checkpoint_dir)
        victims = [s for s in steps if s != committed]
        # lst[:-0] is the WHOLE list: max_to_keep=0 must mean "keep only
        # the committed step", not "keep everything" (same guard
        # KeepStepIntervalDeletionStrategy carries).
        if self.max_to_keep > 0:
            victims = victims[: -self.max_to_keep]
        for s in victims:
            if s == committed:
                continue
            logger.info("removing old checkpoint step %d", s)
            shutil.rmtree(step_dir(checkpoint_dir, s), ignore_errors=True)
