"""Checkpoint-cadence autotuning from measured costs.

Young/Daly optimal checkpoint interval, adapted for ASYNC flash saves:
``tau = sqrt(2 * delta * MTBF)`` where ``delta`` is the cost a save
imposes on training — for flash checkpoints that is the ~ms blocking
launch of the device->host DMA, not the transfer itself (it overlaps
compute). Two floors keep the result physical:

- a new snapshot cannot start before the previous drain finished, so
  the interval never drops below 2x the measured drain time;
- an absolute minimum keeps pathological measurements (zero-cost saves
  on tiny models) from requesting per-step checkpoints.

Parity: the reference's dynamic-optimization design
(docs/design/dynamic-optimization.md) prescribes tuning runtime knobs
from measured job stats instead of constants; its flash-checkpoint
paper pitch is exactly "save as often as the blocking cost allows".
The previous bench hard-coded a 60s cadence; with a measured ~3ms
block cost the optimal cadence is ~5s, which cuts the expected lost
work per failure from ~30s to ~2.5s of steps.
"""

import math
from collections import deque
from typing import Optional


def optimal_save_interval_s(
    save_block_s: float,
    drain_s: float = 0.0,
    mtbf_s: float = 3600.0,
    min_interval_s: float = 2.0,
    max_interval_s: float = 600.0,
) -> float:
    """Interval minimizing expected overhead: per-save blocking cost
    amortized vs expected replay of half an interval per failure."""
    delta = max(float(save_block_s), 1e-4)
    tau = math.sqrt(2.0 * delta * max(float(mtbf_s), 1.0))
    tau = max(tau, 2.0 * max(float(drain_s), 0.0), float(min_interval_s))
    return min(tau, float(max_interval_s))


def expected_goodput_pct(
    save_interval_s: float,
    save_block_s: float,
    recovery_s: float,
    mtbf_s: float = 3600.0,
    drain_s: float = 0.0,
) -> float:
    """Goodput at an operating point: per-MTBF overhead = save blocks +
    one failure's downtime (recovery + expected replay of half an
    interval plus the snapshot's drain lag)."""
    saves = mtbf_s / max(save_interval_s, 1e-6)
    overhead = saves * save_block_s
    downtime = recovery_s + save_interval_s / 2.0 + drain_s
    return 100.0 * mtbf_s / (mtbf_s + overhead + downtime)


class MtbfTracker:
    """Rolling observed mean time between failures.

    The live counterpart of the constant ``mtbf_s`` the bench assumes:
    the autoscaler feeds failure arrival timestamps in (node deaths,
    worker SIGKILLs) and reads the windowed mean inter-arrival back out
    to drive :func:`optimal_save_interval_s`. ``None`` until at least
    ``min_failures`` arrivals landed — one failure is an anecdote, not
    a rate.
    """

    def __init__(self, window: int = 32, min_failures: int = 2):
        self._times = deque(maxlen=max(window, 2))
        self._min_failures = max(min_failures, 2)

    def record_failure(self, ts: float):
        self._times.append(float(ts))

    @property
    def failures_seen(self) -> int:
        return len(self._times)

    def observed_mtbf_s(self) -> Optional[float]:
        if len(self._times) < self._min_failures:
            return None
        times = sorted(self._times)
        gaps = [b - a for a, b in zip(times, times[1:]) if b > a]
        if not gaps:
            return None
        return sum(gaps) / len(gaps)


class SaveCostTracker:
    """Rolling medians of measured save costs, feeding the autotuner."""

    def __init__(self, window: int = 16):
        self._block = deque(maxlen=window)
        self._drain = deque(maxlen=window)

    def record_block(self, seconds: float):
        self._block.append(float(seconds))

    def record_drain(self, seconds: float):
        self._drain.append(float(seconds))

    @staticmethod
    def _median(values) -> Optional[float]:
        if not values:
            return None
        vals = sorted(values)
        return vals[len(vals) // 2]

    @property
    def block_s(self) -> Optional[float]:
        return self._median(self._block)

    @property
    def drain_s(self) -> Optional[float]:
        return self._median(self._drain)

    def recommended_interval_s(
        self, mtbf_s: float = 3600.0, **kwargs
    ) -> Optional[float]:
        """None until at least one save was measured."""
        block = self.block_s
        if block is None:
            return None
        return optimal_save_interval_s(
            block, self.drain_s or block, mtbf_s, **kwargs
        )
