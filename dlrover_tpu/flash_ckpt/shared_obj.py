"""Cross-process shared objects between trainer and agent on one host.

Parity: reference dlrover/python/common/multi_process.py:180-747
(SharedLock/SharedQueue/SharedDict over Unix domain sockets). The agent
hosts tiny UDS servers; trainer processes connect as clients. Used by the
flash-checkpoint engine to hand the agent save events and to serialize
shm access.
"""

import os
import pickle
import queue as _queue
import socket
import threading
import time
from typing import Any, Dict, Optional

from dlrover_tpu.common.log import logger

SOCKET_DIR_ENV = "DLROVER_TPU_SHARED_DIR"


def default_socket_dir() -> str:
    d = os.getenv(SOCKET_DIR_ENV, "")
    if not d:
        d = os.path.join(
            "/tmp", f"dlrover_tpu_{os.getenv('DLROVER_TPU_JOB_NAME', 'job')}"
        )
    os.makedirs(d, exist_ok=True)
    return d


def socket_path(name: str, sock_dir: str = "") -> str:
    return os.path.join(sock_dir or default_socket_dir(), f"{name}.sock")


def _recv_msg(conn: socket.socket) -> Optional[dict]:
    header = conn.recv(8)
    if len(header) < 8:
        return None
    size = int.from_bytes(header, "big")
    chunks = []
    while size > 0:
        chunk = conn.recv(min(size, 1 << 20))
        if not chunk:
            return None
        chunks.append(chunk)
        size -= len(chunk)
    # UDS sockets are filesystem-permission scoped, but keep the same
    # no-arbitrary-code deserialization policy as every other boundary.
    from dlrover_tpu.common.serialize import loads_pytree

    return loads_pytree(b"".join(chunks))


def _send_msg(conn: socket.socket, obj: Any):
    payload = pickle.dumps(obj)
    conn.sendall(len(payload).to_bytes(8, "big") + payload)


class _UdsServer(threading.Thread):
    """One request-per-connection UDS server running in the agent."""

    def __init__(self, name: str, handler, sock_dir: str = ""):
        super().__init__(daemon=True, name=f"uds-{name}")
        self._path = socket_path(name, sock_dir)
        if os.path.exists(self._path):
            os.unlink(self._path)
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(self._path)
        self._sock.listen(64)
        self._handler = handler
        self._stopped = False

    def run(self):
        while not self._stopped:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                break
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn: socket.socket):
        with conn:
            while True:
                try:
                    request = _recv_msg(conn)
                except (ConnectionResetError, OSError):
                    return
                if request is None:
                    return
                try:
                    response = self._handler(request)
                except Exception as e:  # noqa: BLE001
                    logger.exception("UDS handler error")
                    response = {"error": str(e)}
                try:
                    _send_msg(conn, response)
                except (BrokenPipeError, OSError):
                    return

    def stop(self):
        self._stopped = True
        try:
            self._sock.close()
        finally:
            if os.path.exists(self._path):
                os.unlink(self._path)


class _UdsClient:
    def __init__(self, name: str, sock_dir: str = "", connect_timeout: float = 60.0):
        self._path = socket_path(name, sock_dir)
        self._lock = threading.Lock()
        self._conn: Optional[socket.socket] = None
        self._connect_timeout = connect_timeout

    def _ensure_conn(self) -> socket.socket:
        if self._conn is None:
            deadline = time.time() + self._connect_timeout
            while True:
                try:
                    conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                    conn.connect(self._path)
                    self._conn = conn
                    break
                except (FileNotFoundError, ConnectionRefusedError):
                    if time.time() > deadline:
                        raise
                    time.sleep(0.1)
        return self._conn

    def call(self, request: dict) -> dict:
        with self._lock:
            conn = self._ensure_conn()
            try:
                _send_msg(conn, request)
                resp = _recv_msg(conn)
            except (BrokenPipeError, ConnectionResetError, OSError):
                self._conn = None
                conn = self._ensure_conn()
                _send_msg(conn, request)
                resp = _recv_msg(conn)
            if resp is None:
                self._conn = None
                raise ConnectionError(f"UDS server {self._path} hung up")
            if "error" in resp:
                raise RuntimeError(resp["error"])
            return resp

    def close(self):
        with self._lock:
            if self._conn is not None:
                self._conn.close()
                self._conn = None


# --------------------------------------------------------------------------
# SharedQueue
# --------------------------------------------------------------------------


class SharedQueueServer:
    def __init__(self, name: str, maxsize: int = 0, sock_dir: str = ""):
        self._queue: _queue.Queue = _queue.Queue(maxsize)
        self._server = _UdsServer(f"queue-{name}", self._handle, sock_dir)
        self._server.start()

    def _handle(self, req: dict) -> dict:
        op = req["op"]
        if op == "put":
            try:
                self._queue.put(req["item"], timeout=req.get("timeout"))
                return {"ok": True}
            except _queue.Full:
                return {"ok": False, "full": True}
        if op == "get":
            try:
                item = self._queue.get(
                    block=req.get("block", True), timeout=req.get("timeout")
                )
                return {"ok": True, "item": item}
            except _queue.Empty:
                return {"ok": False, "empty": True}
        if op == "qsize":
            return {"ok": True, "size": self._queue.qsize()}
        return {"error": f"unknown op {op}"}

    # direct (in-process) access for the hosting agent
    def get(self, block=True, timeout=None):
        return self._queue.get(block=block, timeout=timeout)

    def put(self, item, timeout=None):
        self._queue.put(item, timeout=timeout)

    def qsize(self) -> int:
        return self._queue.qsize()

    def stop(self):
        self._server.stop()


class SharedQueueClient:
    def __init__(self, name: str, sock_dir: str = ""):
        self._client = _UdsClient(f"queue-{name}", sock_dir)

    def put(self, item, timeout: Optional[float] = None):
        resp = self._client.call({"op": "put", "item": item, "timeout": timeout})
        if not resp.get("ok"):
            raise _queue.Full()

    def get(self, block: bool = True, timeout: Optional[float] = None):
        resp = self._client.call(
            {"op": "get", "block": block, "timeout": timeout}
        )
        if not resp.get("ok"):
            raise _queue.Empty()
        return resp["item"]

    def qsize(self) -> int:
        return self._client.call({"op": "qsize"})["size"]


# --------------------------------------------------------------------------
# SharedLock
# --------------------------------------------------------------------------


class SharedLockServer:
    def __init__(self, name: str, sock_dir: str = ""):
        self._lock = threading.Lock()
        self._owner: Optional[str] = None
        self._cond = threading.Condition()
        self._server = _UdsServer(f"lock-{name}", self._handle, sock_dir)
        self._server.start()

    def _handle(self, req: dict) -> dict:
        op = req["op"]
        owner = req.get("owner", "")
        if op == "acquire":
            blocking = req.get("blocking", True)
            timeout = req.get("timeout", 60.0)
            deadline = time.time() + (timeout if blocking else 0)
            with self._cond:
                while self._owner is not None and self._owner != owner:
                    remaining = deadline - time.time()
                    if not blocking or remaining <= 0:
                        return {"ok": True, "acquired": False}
                    self._cond.wait(min(remaining, 1.0))
                self._owner = owner
                return {"ok": True, "acquired": True}
        if op == "release":
            with self._cond:
                if self._owner == owner:
                    self._owner = None
                    self._cond.notify_all()
            return {"ok": True}
        if op == "locked":
            with self._cond:
                return {"ok": True, "locked": self._owner is not None}
        return {"error": f"unknown op {op}"}

    # In-process acquire/release for the hosting agent (the saver thread
    # must hold the same lock workers use before reading shm).
    def acquire(self, owner: str = "agent-local", timeout: float = 60.0) -> bool:
        resp = self._handle(
            {"op": "acquire", "owner": owner, "blocking": True, "timeout": timeout}
        )
        return resp.get("acquired", False)

    def release(self, owner: str = "agent-local"):
        self._handle({"op": "release", "owner": owner})

    def stop(self):
        self._server.stop()


class SharedLockClient:
    def __init__(self, name: str, sock_dir: str = ""):
        self._client = _UdsClient(f"lock-{name}", sock_dir)
        self._owner = f"{os.getpid()}-{id(self)}"

    def acquire(self, blocking: bool = True, timeout: float = 60.0) -> bool:
        resp = self._client.call(
            {
                "op": "acquire",
                "owner": self._owner,
                "blocking": blocking,
                "timeout": timeout,
            }
        )
        return resp.get("acquired", False)

    def release(self):
        self._client.call({"op": "release", "owner": self._owner})

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()


# --------------------------------------------------------------------------
# SharedDict
# --------------------------------------------------------------------------


class SharedDictServer:
    def __init__(self, name: str, sock_dir: str = ""):
        self._dict: Dict[str, Any] = {}
        self._lock = threading.Lock()
        self._server = _UdsServer(f"dict-{name}", self._handle, sock_dir)
        self._server.start()

    def _handle(self, req: dict) -> dict:
        op = req["op"]
        if op == "set":
            with self._lock:
                self._dict[req["key"]] = req["value"]
            return {"ok": True}
        if op == "get":
            with self._lock:
                return {"ok": True, "value": self._dict.get(req["key"])}
        if op == "update":
            with self._lock:
                self._dict.update(req["items"])
            return {"ok": True}
        if op == "dump":
            with self._lock:
                return {"ok": True, "items": dict(self._dict)}
        if op == "delete":
            with self._lock:
                self._dict.pop(req["key"], None)
            return {"ok": True}
        return {"error": f"unknown op {op}"}

    # in-process access
    def get(self, key: str, default=None):
        with self._lock:
            return self._dict.get(key, default)

    def set(self, key: str, value):
        with self._lock:
            self._dict[key] = value

    def dump(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._dict)

    def stop(self):
        self._server.stop()


class SharedDictClient:
    def __init__(self, name: str, sock_dir: str = ""):
        self._client = _UdsClient(f"dict-{name}", sock_dir)

    def set(self, key: str, value):
        self._client.call({"op": "set", "key": key, "value": value})

    def get(self, key: str, default=None):
        value = self._client.call({"op": "get", "key": key})["value"]
        return default if value is None else value

    def update(self, items: Dict[str, Any]):
        self._client.call({"op": "update", "items": items})

    def dump(self) -> Dict[str, Any]:
        return self._client.call({"op": "dump"})["items"]

    def delete(self, key: str):
        self._client.call({"op": "delete", "key": key})
