"""Cross-host in-memory checkpoint replication.

Parity: reference trainer/torch/flash_checkpoint/replica.py:28-352
(CkptReplicaManger/ShardCkptReplicaManager) — each node keeps a backup of
its replica-group peers' shm checkpoint images so a RELAUNCHED node can
restore from a live peer's memory instead of (slow) storage.

TPU-native design note: the reference exchanges replicas with torch
collectives inside a checkpoint process group. A relaunched JAX process
cannot rejoin the old world to gather (``jax.distributed`` worlds are
immutable), and replica traffic is control-plane, not compute — so the
exchange runs agent-to-agent over HTTP: after each shm save the agent
pushes its raw segment images to its group peers; a relaunched agent
pulls its segments back before workers start. Peer addresses go through
the master KV store.

Segment payloads are the raw shm bytes (magic + meta + data), so a
restored segment is byte-identical to what the lost node held and the
normal memory-first engine load path just works.
"""

import http.client
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from dlrover_tpu.common.constants import CheckpointConstant
from dlrover_tpu.common.log import logger
from dlrover_tpu.flash_ckpt.engine import shm_segment_name
from dlrover_tpu.flash_ckpt.shm_handler import SharedMemoryHandler

_ADDR_KEY = "ckpt-replica-addr/{rank}"
REPLICA_TOKEN_KEY = CheckpointConstant.REPLICA_TOKEN_KEY


class ReplicaTokenUnavailable(RuntimeError):
    """No usable shared secret for the replica service."""


def resolve_auth_token(master_client=None, timeout: float = 30.0) -> str:
    """Shared-secret header value for the replica service.

    Replica payloads end up in workers' shm segments, so writes must be
    limited to job members. The secret is either the operator-provided
    DLROVER_TPU_REPLICA_TOKEN (the strong option: never on the wire via
    the master) or the random per-job token the master generates at
    startup and serves via its KV store — not derivable offline, though
    readable by anyone who can already reach the master's RPC port.
    Without either, the service refuses to start.
    """
    token = os.getenv("DLROVER_TPU_REPLICA_TOKEN", "")
    if token:
        return token
    if master_client is not None:
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                value = master_client.kv_store_get(REPLICA_TOKEN_KEY)
            except Exception:
                value = b""
            if value:
                return value.decode()
            time.sleep(0.5)
    raise ReplicaTokenUnavailable(
        "checkpoint replica service needs DLROVER_TPU_REPLICA_TOKEN or a "
        "master-distributed per-job token; refusing to open the port"
    )


# ---------------------------------------------------------------------------
# Raw segment snapshot / restore
# ---------------------------------------------------------------------------


def snapshot_segment(name: str, lock=None) -> Optional[bytes]:
    """Copy the valid bytes of a committed shm segment (None if absent
    or mid-write)."""
    if lock is not None:
        lock.acquire()
    try:
        handler = SharedMemoryHandler(name)
        meta = handler.load_meta()
        if meta is None:
            handler.close()
            return None
        end = meta["data_start"]
        for leaf in meta["leaves"]:
            for shard in leaf.shards:
                end = max(end, meta["data_start"] + shard.offset + shard.nbytes)
        payload = bytes(handler._shm.buf[:end])  # noqa: SLF001
        handler.close()
        return payload
    finally:
        if lock is not None:
            lock.release()


def restore_segment(name: str, payload: bytes):
    """Write a snapshot back into a (possibly new) shm segment with the
    same commit ordering as a normal save."""
    handler = SharedMemoryHandler(name)
    handler._ensure_shm(len(payload))  # noqa: SLF001
    buf = handler._shm.buf  # noqa: SLF001
    buf[:8] = b"\x00" * 8
    buf[8 : len(payload)] = payload[8:]
    # Commit with the PAYLOAD's magic, not this build's: a snapshot from
    # an older layout version must keep its own version stamp or the
    # reader would parse v1 offsets with v2 rules.
    buf[:8] = payload[:8]
    handler.close()


# ---------------------------------------------------------------------------
# Replica HTTP service (runs in the agent)
# ---------------------------------------------------------------------------


class _ReplicaStore:
    def __init__(self):
        self._lock = threading.Lock()
        self._data: Dict[Tuple[int, int], bytes] = {}

    def put(self, owner_rank: int, local_rank: int, payload: bytes):
        with self._lock:
            self._data[(owner_rank, local_rank)] = payload

    def get(self, owner_rank: int, local_rank: int) -> Optional[bytes]:
        with self._lock:
            return self._data.get((owner_rank, local_rank))

    def owners(self) -> List[int]:
        with self._lock:
            return sorted({o for o, _ in self._data})


def _make_handler(store: _ReplicaStore, token: str):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):
            pass

        def _authorized(self) -> bool:
            if self.headers.get("X-Replica-Token") == token:
                return True
            self.send_response(403)
            self.end_headers()
            return False

        def _parse(self):
            parts = self.path.strip("/").split("/")
            if len(parts) != 3 or parts[0] != "replica":
                return None
            try:
                return int(parts[1]), int(parts[2])
            except ValueError:
                return None

        def do_PUT(self):
            if not self._authorized():
                return
            key = self._parse()
            if key is None:
                self.send_response(404)
                self.end_headers()
                return
            length = int(self.headers.get("Content-Length", "0"))
            payload = self.rfile.read(length)
            store.put(key[0], key[1], payload)
            self.send_response(200)
            self.end_headers()

        def do_GET(self):
            if not self._authorized():
                return
            key = self._parse()
            payload = None if key is None else store.get(key[0], key[1])
            if payload is None:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

    return Handler


class CkptReplicaManager:
    """Agent-side replica push/pull coordinator.

    ``group_size`` nodes form a replica group (consecutive ranks); each
    node pushes its segments to every other group member after a save.
    """

    def __init__(
        self,
        node_rank: int,
        master_client=None,
        group_size: int = 2,
        port: int = 0,
        addr_map: Optional[Dict[int, str]] = None,
    ):
        self._node_rank = node_rank
        self._client = master_client
        self._group_size = max(1, group_size)
        self._store = _ReplicaStore()
        self._token = resolve_auth_token(master_client)
        self._server = ThreadingHTTPServer(
            ("0.0.0.0", port), _make_handler(self._store, self._token)
        )
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None
        self._world: List[int] = [node_rank]
        # Static address map for tests / masterless runs.
        self._addr_map = addr_map or {}

    # ---- lifecycle ---------------------------------------------------------

    def start(self, advertise_host: str = "127.0.0.1"):
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="ckpt-replica-server",
            daemon=True,
        )
        self._thread.start()
        addr = f"{advertise_host}:{self.port}"
        if self._client is not None:
            try:
                self._client.kv_store_set(
                    _ADDR_KEY.format(rank=self._node_rank),
                    addr.encode(),
                )
            except Exception:
                logger.warning("replica addr publish failed", exc_info=True)
        logger.info("ckpt replica service on %s", addr)

    def stop(self):
        if self._thread is not None:
            # shutdown() blocks until serve_forever acknowledges; calling
            # it on a never-started server would wait forever.
            self._server.shutdown()
        self._server.server_close()

    def set_world(self, world_nodes: List[int]):
        self._world = sorted(world_nodes) or [self._node_rank]

    # ---- group topology ----------------------------------------------------

    def group_peers(self, rank: Optional[int] = None) -> List[int]:
        """Other members of ``rank``'s replica group (consecutive blocks
        of group_size over the sorted world)."""
        rank = self._node_rank if rank is None else rank
        world = self._world
        if rank not in world or self._group_size <= 1:
            return []
        i = world.index(rank)
        start = i - (i % self._group_size)
        return [
            r
            for r in world[start : start + self._group_size]
            if r != rank
        ]

    def _peer_addr(self, rank: int) -> Optional[str]:
        if rank in self._addr_map:
            return self._addr_map[rank]
        if self._client is None:
            return None
        try:
            value = self._client.kv_store_get(_ADDR_KEY.format(rank=rank))
            return value.decode() if value else None
        except Exception:
            return None

    # ---- push (after save) --------------------------------------------------

    def push_node_image(
        self, local_world_size: int, locks: Optional[list] = None
    ) -> int:
        """Push this node's shm segments to its group peers; returns the
        number of segment replicas delivered."""
        peers = self.group_peers()
        if not peers:
            return 0
        payloads = []
        for local_rank in range(local_world_size):
            lock = locks[local_rank] if locks else None
            payload = snapshot_segment(shm_segment_name(local_rank), lock)
            if payload is not None:
                payloads.append((local_rank, payload))
        delivered = 0
        for peer in peers:
            addr = self._peer_addr(peer)
            if addr is None:
                continue
            for local_rank, payload in payloads:
                if self._http_put(addr, self._node_rank, local_rank, payload):
                    delivered += 1
        return delivered

    # ---- pull (relaunched node) ---------------------------------------------

    def restore_missing_segments(
        self,
        local_world_size: int,
        candidate_ranks: Optional[List[int]] = None,
    ) -> int:
        """Fetch this node's segments from peers when the local shm is
        empty (fresh host after relaunch). Returns segments restored.

        ``candidate_ranks``: peers to ask. Defaults to the group peers,
        but a relaunched node should pass every possible rank — the push
        side grouped by the *actual* rendezvous world at save time, which
        the fresh node cannot reconstruct; a 404 from a non-holder is
        cheap, a missed holder costs a slow storage restore.
        """
        if candidate_ranks is None:
            candidate_ranks = self.group_peers()
        candidates = [r for r in candidate_ranks if r != self._node_rank]
        restored = 0
        for local_rank in range(local_world_size):
            name = shm_segment_name(local_rank)
            handler = SharedMemoryHandler(name)
            have = handler.load_meta() is not None
            handler.close()
            if have:
                continue
            for peer in candidates:
                addr = self._peer_addr(peer)
                if addr is None:
                    continue
                payload = self._http_get(
                    addr, self._node_rank, local_rank
                )
                if payload is not None:
                    restore_segment(name, payload)
                    logger.info(
                        "restored shm segment %s from peer %d", name, peer
                    )
                    restored += 1
                    break
        return restored

    # ---- http plumbing ------------------------------------------------------

    def _http_put(
        self, addr: str, owner: int, local_rank: int, payload: bytes
    ) -> bool:
        try:
            host, port = addr.rsplit(":", 1)
            conn = http.client.HTTPConnection(host, int(port), timeout=60)
            conn.request(
                "PUT",
                f"/replica/{owner}/{local_rank}",
                body=payload,
                headers={"X-Replica-Token": self._token},
            )
            ok = conn.getresponse().status == 200
            conn.close()
            return ok
        except Exception:
            # Peer churn mid-transfer must never break the save path.
            logger.warning("replica push to %s failed", addr)
            return False

    def _http_get(
        self, addr: str, owner: int, local_rank: int
    ) -> Optional[bytes]:
        try:
            host, port = addr.rsplit(":", 1)
            conn = http.client.HTTPConnection(host, int(port), timeout=60)
            conn.request(
                "GET",
                f"/replica/{owner}/{local_rank}",
                headers={"X-Replica-Token": self._token},
            )
            resp = conn.getresponse()
            payload = resp.read() if resp.status == 200 else None
            conn.close()
            return payload
        except Exception:
            return None
