"""What-if replay: run a recorded signal stream through candidate
policies offline, diff counterfactual ledgers, score them.

The §30 rules are CLOCKLESS by design — every time comparison is
between snapshot timestamps, never a live clock read — so feeding the
recorded snapshot stream back through the SAME :class:`PolicyConfig`
must reproduce the live run's decision ledger *decision for decision*
(same actions, same targets, same order). That identity is the
invariant :func:`assert_replay_identity` pins, and it is what licenses
the interesting use: replay the stream through a *candidate* config and
read the counterfactual ledger a different policy WOULD have produced,
without touching the live job.

Scoring is a goodput model over the recorded horizon, calibrated from
MEASURED actuation costs (:class:`CostModel` defaults come from the
bench history: rescale-to-first-step seconds, ckpt blocking cost). Per
candidate it estimates lost wall time in four explainable buckets —
actuation pauses, ckpt save overhead along the candidate's interval
trajectory, replay exposure at the failures the recording actually
observed, and the straggler tax accrued while flagged ranks went
unevicted — and returns an estimated goodput fraction. The model is a
counterfactual lower bound, not ground truth (the recording's signals
embed what the LIVE policy did); its job is to rank candidates, and the
recorded policy's own score cross-checks against the measured run.

``SEED_WORLD`` ledger entries are brain-prior seeds, not policy output;
identity comparison excludes them (replay has no brain to ask).
"""

import time
from dataclasses import dataclass, field, fields
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from dlrover_tpu.autoscaler.policy import (
    EVICT_STRAGGLER,
    GROW_FLEET,
    GROW_WORLD,
    PolicyConfig,
    RulePolicy,
    ScaleDecision,
    SEED_WORLD,
    SET_CKPT_INTERVAL,
    SHRINK_FLEET,
    SHRINK_WORLD,
)
from dlrover_tpu.autoscaler.recorder import Recording
from dlrover_tpu.autoscaler.signals import SignalSnapshot


class ReplayMismatch(AssertionError):
    """Replaying the recorded policy did not reproduce its ledger."""


def replay_policy(
    snapshots: Iterable[SignalSnapshot],
    config: Optional[PolicyConfig] = None,
) -> List[ScaleDecision]:
    """Feed snapshots (in recorded order) through a fresh RulePolicy;
    the returned decisions are the counterfactual ledger (seq assigned
    1..N, no outcomes — nothing was actuated)."""
    policy = RulePolicy(config or PolicyConfig())
    out: List[ScaleDecision] = []
    for snap in snapshots:
        for decision in policy.decide(snap):
            decision.seq = len(out) + 1
            out.append(decision)
    return out


def replay_recording(
    recording: Recording,
    config: Optional[PolicyConfig] = None,
) -> List[ScaleDecision]:
    """Replay a loaded recording: with ``config=None`` the RECORDED
    policy config is used (the identity case)."""
    if config is None:
        if recording.policy_config is None:
            raise ValueError(
                "recording carries no policy config; pass one"
            )
        config = PolicyConfig.from_dict(recording.policy_config)
    return replay_policy(recording.snapshots, config)


# ---------------------------------------------------------------------------
# Ledger diffing + the identity invariant
# ---------------------------------------------------------------------------


def _decision_key(d) -> Tuple[str, str, float]:
    """Order-comparable identity of one decision: (action, target, ts).
    Accepts ScaleDecision or a recorded dict. Targets compare as
    strings (JSON round-trips ints losslessly, floats were rounded at
    fire time)."""
    if isinstance(d, dict):
        return (
            str(d.get("action")), str(d.get("target")),
            round(float(d.get("ts", 0.0)), 6),
        )
    return (str(d.action), str(d.target), round(float(d.ts), 6))


def policy_decisions(decisions: Sequence) -> List:
    """Drop non-policy entries (the brain's SEED_WORLD prior) before an
    identity comparison."""
    out = []
    for d in decisions:
        action = d.get("action") if isinstance(d, dict) else d.action
        if action != SEED_WORLD:
            out.append(d)
    return out


def diff_ledgers(recorded: Sequence, replayed: Sequence) -> Dict:
    """Positional diff of two decision sequences (recorded entries may
    be dicts, replayed ones ScaleDecisions)."""
    rec = [_decision_key(d) for d in policy_decisions(recorded)]
    rep = [_decision_key(d) for d in policy_decisions(replayed)]
    matched = 0
    first_divergence = None
    for i, (a, b) in enumerate(zip(rec, rep)):
        if a == b:
            matched += 1
        else:
            first_divergence = {"index": i, "recorded": a, "replayed": b}
            break
    if first_divergence is None and len(rec) != len(rep):
        i = min(len(rec), len(rep))
        first_divergence = {
            "index": i,
            "recorded": rec[i] if i < len(rec) else None,
            "replayed": rep[i] if i < len(rep) else None,
        }
    return {
        "identical": first_divergence is None,
        "recorded_total": len(rec),
        "replayed_total": len(rep),
        "matched": matched,
        "first_divergence": first_divergence,
    }


def assert_replay_identity(recording: Recording) -> Dict:
    """The §34 invariant: the recorded signal stream through the
    recorded PolicyConfig reproduces the recorded ledger exactly.
    Returns the (identical) diff; raises :class:`ReplayMismatch` with
    the first divergence otherwise.

    Only meaningful on a COMPLETE recording: when the rotation bound
    deleted the stream's beginning, a fresh policy cannot know the
    cooldowns/streaks accrued in the deleted era, so identity is
    undecidable and this raises ``ReplayMismatch`` naming the
    truncation rather than reporting a spurious divergence."""
    if recording.truncated:
        raise ReplayMismatch(
            "recording is truncated (oldest rotation generation "
            "deleted); replay identity is undecidable from mid-stream"
        )
    replayed = replay_recording(recording)
    diff = diff_ledgers(recording.decisions, replayed)
    if not diff["identical"]:
        raise ReplayMismatch(
            f"replay of the recorded policy diverged from the live "
            f"ledger at {diff['first_divergence']}"
        )
    return diff


# ---------------------------------------------------------------------------
# Counterfactual scoring
# ---------------------------------------------------------------------------


@dataclass
class CostModel:
    """Measured actuation costs the goodput model charges. Defaults are
    the 2-core-CPU bench numbers; :meth:`from_bench` recalibrates from
    the newest bench artifact that carries the keys."""

    rescale_to_first_step_s: float = 0.4   # bench `rescale` phase
    evict_pause_s: float = 0.4             # evict == one rescale pause
    fleet_change_s: float = 0.05           # router add/drain latency
    save_block_s: float = 0.01             # ckpt blocking cost per save
    straggler_flag_threshold: float = 1.5  # score at which tax accrues

    _BENCH_KEYS = {
        "rescale_to_first_step_s": "rescale_to_first_step_s",
        "ckpt_save_block_s": "save_block_s",
    }

    @classmethod
    def from_bench(cls, paths: Iterable[str]) -> "CostModel":
        """Best-effort calibration from bench JSON artifacts, newest
        first. Each cost key takes the FIRST (newest) artifact that
        carries it — an artifact missing a key does not stop the scan,
        and keys no artifact carries keep their defaults."""
        import json
        import os

        model = cls()
        remaining = dict(cls._BENCH_KEYS)
        for path in paths:
            if not remaining:
                break
            if not os.path.exists(path):
                continue
            try:
                data = json.loads(open(path).read())
            except (OSError, ValueError):
                continue
            for bench_key in list(remaining):
                value = data.get(bench_key)
                if isinstance(value, (int, float)) and value > 0:
                    setattr(model, remaining.pop(bench_key),
                            float(value))
        if "rescale_to_first_step_s" not in remaining:
            # The eviction pause IS one rescale pause; keep the pair
            # coherent when the rescale number was calibrated.
            model.evict_pause_s = model.rescale_to_first_step_s
        return model

    def to_dict(self) -> Dict[str, float]:
        return {
            f.name: getattr(self, f.name)
            for f in fields(self) if not f.name.startswith("_")
        }


def _snap_clock(snap: SignalSnapshot) -> float:
    """Replay arithmetic runs on the monotonic stamp when the recording
    has one (wall steps must not warp the horizon); old recordings
    (mono==0) fall back to wall."""
    return snap.mono if snap.mono else snap.ts


def score_ledger(
    snapshots: Sequence[SignalSnapshot],
    decisions: Sequence,
    cost: Optional[CostModel] = None,
) -> Dict:
    """Estimated goodput of running ``decisions`` over the recorded
    horizon. See module docstring for the four loss buckets."""
    cost = cost or CostModel()
    if len(snapshots) < 2:
        return {
            "horizon_s": 0.0, "est_goodput_frac": 0.0,
            "decisions_total": len(list(decisions)),
        }
    decisions = policy_decisions(decisions)

    def d_clock(d):
        """Decision time on the SAME clock family as _snap_clock: mono
        when stamped (every §34 recording), wall otherwise — a wall
        step mid-recording must not un-apply a retune or un-mitigate
        an eviction in the comparisons below."""
        if isinstance(d, dict):
            mono = float(d.get("mono", 0.0))
            return mono if mono else float(d.get("ts", 0.0))
        return d.mono if d.mono else d.ts

    def d_action(d):
        return d.get("action") if isinstance(d, dict) else d.action

    def d_target(d):
        return d.get("target") if isinstance(d, dict) else d.target

    horizon = max(
        _snap_clock(snapshots[-1]) - _snap_clock(snapshots[0]), 1e-9
    )
    # Actuation pauses: every world move / evict pays a rescale pause,
    # every fleet change its add/drain latency; retunes are free.
    actuation_cost = 0.0
    evict_ts: List[float] = []
    for d in decisions:
        action = d_action(d)
        if action == EVICT_STRAGGLER:
            actuation_cost += cost.evict_pause_s
            evict_ts.append(d_clock(d))
        elif action in (GROW_WORLD, SHRINK_WORLD):
            actuation_cost += cost.rescale_to_first_step_s
        elif action in (GROW_FLEET, SHRINK_FLEET):
            actuation_cost += cost.fleet_change_s

    # Ckpt interval trajectory: the candidate's retunes, applied at
    # their decision timestamps, govern save overhead and the replay
    # exposure charged at each failure the recording observed.
    retunes = sorted(
        (
            (d_clock(d), float(d_target(d)))
            for d in decisions if d_action(d) == SET_CKPT_INTERVAL
        ),
        key=lambda x: x[0],
    )

    first = snapshots[0]
    interval = first.get("ckpt.interval_s")
    save_block = float(
        first.get("ckpt.save_block_s", cost.save_block_s) or
        cost.save_block_s
    )
    save_overhead = 0.0
    replay_exposure = 0.0
    straggler_tax = 0.0
    failures_seen = 0
    retune_idx = 0
    prev = snapshots[0]
    prev_fail = float(prev.get("fault.failures_total", 0) or 0)
    for snap in snapshots[1:]:
        dt = max(_snap_clock(snap) - _snap_clock(prev), 0.0)
        while (retune_idx < len(retunes)
               and retunes[retune_idx][0] <= _snap_clock(prev)):
            interval = retunes[retune_idx][1]
            retune_idx += 1
        if interval and dt > 0:
            save_overhead += dt / max(float(interval), 1e-9) * save_block
        fails = float(snap.get("fault.failures_total", prev_fail)
                      or prev_fail)
        if fails > prev_fail:
            n = fails - prev_fail
            failures_seen += int(n)
            if interval:
                # Expected replay at a Poisson failure: interval/2,
                # plus the restart pause per death.
                replay_exposure += n * (
                    float(interval) / 2.0 + cost.rescale_to_first_step_s
                )
            prev_fail = fails
        # Straggler tax: while a rank scores over the flag bar and the
        # candidate has not yet evicted ANY rank by this point in the
        # stream, the whole world loses the excess fraction of dt.
        scores = prev.get("perf.straggler_scores") or {}
        worst = 0.0
        for s in scores.values():
            try:
                worst = max(worst, float(s))
            except (TypeError, ValueError):
                continue
        if worst >= cost.straggler_flag_threshold:
            mitigated = any(
                t <= _snap_clock(prev) for t in evict_ts
            )
            if not mitigated:
                straggler_tax += dt * (1.0 - 1.0 / worst)
        prev = snap

    lost = actuation_cost + save_overhead + replay_exposure + straggler_tax
    return {
        "horizon_s": round(horizon, 4),
        "actuation_cost_s": round(actuation_cost, 4),
        "save_overhead_s": round(save_overhead, 4),
        "replay_exposure_s": round(replay_exposure, 4),
        "straggler_tax_s": round(straggler_tax, 4),
        "failures_seen": failures_seen,
        "est_lost_s": round(lost, 4),
        "est_goodput_frac": round(
            max(horizon - lost, 0.0) / horizon, 4
        ),
        "decisions_total": len(decisions),
        "cost_model": cost.to_dict(),
    }


# ---------------------------------------------------------------------------
# Candidate ranking
# ---------------------------------------------------------------------------


@dataclass
class RankedCandidate:
    name: str
    config: PolicyConfig
    score: Dict = field(default_factory=dict)
    diff_vs_recorded: Dict = field(default_factory=dict)
    decisions: List[ScaleDecision] = field(default_factory=list)

    def to_dict(self, with_decisions: bool = False) -> Dict:
        out = {
            "name": self.name,
            "est_goodput_frac": self.score.get("est_goodput_frac"),
            "score": dict(self.score),
            "identical_to_recorded": self.diff_vs_recorded.get(
                "identical"
            ),
            "decisions_total": len(self.decisions),
        }
        if with_decisions:
            out["decisions"] = [d.to_dict() for d in self.decisions]
        return out


def rank_policies(
    recording: Recording,
    candidates: Sequence[Tuple[str, PolicyConfig]],
    cost: Optional[CostModel] = None,
    with_decisions: bool = False,
) -> Dict:
    """Replay + score every candidate over one recording; the recorded
    policy rides along as the baseline (and its replay is asserted
    identical first — a broken identity invalidates every ranking).
    On a TRUNCATED recording (oldest rotation generation deleted)
    identity is undecidable, so it is reported skipped instead of
    asserted — long production recordings must still be rankable.
    Returns {"identity": diff, "ranked": [...best-first...],
    "replay_snapshots_per_s": measured replay throughput}."""
    cost = cost or CostModel()
    ranked: List[RankedCandidate] = []
    snapshots = recording.snapshots
    recorded_config = PolicyConfig.from_dict(
        recording.policy_config or {}
    )
    total_replayed = 0
    t0 = time.monotonic()
    # One replay of the recorded config serves BOTH the identity check
    # and the baseline ranking entry — a second full pass over a
    # production-sized stream would be pure waste.
    recorded_decisions = replay_policy(snapshots, recorded_config)
    total_replayed += len(snapshots)
    recorded_diff = diff_ledgers(recording.decisions,
                                 recorded_decisions)
    if recording.truncated:
        identity: Dict = {
            "identical": None,
            "skipped": "truncated recording: replay identity is "
                       "undecidable from mid-stream",
        }
    else:
        identity = recorded_diff
        if not identity["identical"]:
            raise ReplayMismatch(
                f"replay of the recorded policy diverged from the "
                f"live ledger at {identity['first_divergence']}"
            )
    ranked.append(RankedCandidate(
        name="recorded",
        config=recorded_config,
        score=score_ledger(snapshots, recorded_decisions, cost),
        diff_vs_recorded=recorded_diff,
        decisions=recorded_decisions,
    ))
    for name, config in candidates:
        decisions = replay_policy(snapshots, config)
        total_replayed += len(snapshots)
        ranked.append(RankedCandidate(
            name=name,
            config=config,
            score=score_ledger(snapshots, decisions, cost),
            diff_vs_recorded=diff_ledgers(
                recording.decisions, decisions
            ),
            decisions=decisions,
        ))
    elapsed = max(time.monotonic() - t0, 1e-9)
    ranked.sort(
        key=lambda c: c.score.get("est_goodput_frac", 0.0),
        reverse=True,
    )
    return {
        "identity": identity,
        "snapshots": len(snapshots),
        "candidates": len(ranked),
        "replay_snapshots_per_s": round(total_replayed / elapsed, 1),
        "ranked": [
            c.to_dict(with_decisions=with_decisions) for c in ranked
        ],
        "best": ranked[0].name if ranked else None,
    }
