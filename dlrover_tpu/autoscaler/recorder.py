"""SignalRecorder: durable, replayable stream of everything the loop saw.

The §30 autoscaler already keeps a bounded in-memory DecisionLedger;
this module makes the *signal stream itself* durable so a recorded run
can be replayed offline through a candidate policy (``replay.py``) —
the measurement half of the ROADMAP's learned-resource-brain item.

Format: schema-versioned JSONL, one record per line, four kinds —

- ``header``  — schema version, pid, wall+mono clock anchor;
- ``policy``  — the PolicyConfig the live loop ran (``dataclasses
  .asdict``), re-emitted after every rotation so each file is
  self-describing;
- ``snapshot`` — one SignalBus sample (seq + ``wall``/``mono``
  timestamp PAIR + the flat values dict);
- ``decision`` / ``outcome`` — ledger entries and their realized-effect
  backfills, keyed by ledger seq.

Every record carries a ``(wall, mono)`` timestamp pair: wall time is
what the clockless policy rules consume (and what humans read), the
monotonic stamp is what :func:`load_recording` ORDERS by — an NTP step
mid-run must not reorder a recording (satellite: no bare
``time.time()`` ordering anywhere in the replay path).

Durability borrows the fault-trace discipline (``fault/registry.py``):
each record is flushed and — by default — fsync'd as it is written, so
a SIGKILL'd run's recording replays up to the instant of death; a torn
final line is tolerated (and counted) by the reader. Rotation keeps the
recording bounded: past ``max_bytes`` the live file rotates to
``<path>.1`` (older generations shift up, the oldest beyond
``max_files`` is deleted) and the reader stitches the chain back
together oldest-first.

Subprocess workers arm from the environment the same way the fault
plane does: ``DLROVER_TPU_AUTOSCALE_RECORD=<path>`` (plus
``DLROVER_TPU_AUTOSCALE_RECORD_FSYNC=0`` to trade durability for
throughput), via :func:`recorder_from_env`.
"""

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from dlrover_tpu.autoscaler.signals import SignalSnapshot
from dlrover_tpu.common.log import logger

SCHEMA_VERSION = 1

RECORD_ENV = "DLROVER_TPU_AUTOSCALE_RECORD"
RECORD_FSYNC_ENV = "DLROVER_TPU_AUTOSCALE_RECORD_FSYNC"


class SignalRecorder:
    """Append-only JSONL writer for the autoscaler's signal/decision
    stream. Thread-safe; bounded by rotation; fsync-per-record by
    default so SIGKILL runs stay replayable."""

    def __init__(
        self,
        path: str,
        fsync: bool = True,
        max_bytes: int = 16 << 20,
        max_files: int = 3,
    ):
        self._path = path
        self._fsync = fsync
        self._max_bytes = max(int(max_bytes), 4096)
        self._max_files = max(int(max_files), 1)
        self._lock = threading.Lock()
        self._policy_record: Optional[Dict] = None
        self._records_written = 0
        self._rotations = 0
        self._closed = False
        parent = os.path.dirname(os.path.abspath(path))
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._f = open(path, "a")
        self._emit(self._header())

    # ---- record kinds ------------------------------------------------------

    def _header(self) -> Dict:
        return {
            "kind": "header",
            "v": SCHEMA_VERSION,
            "pid": os.getpid(),
            "wall": time.time(),
            "mono": time.monotonic(),
            # Rotation ordinal: when the oldest surviving file's header
            # carries rotation > 0, the stream's beginning was deleted
            # by the bound — the reader marks the recording truncated
            # (replay identity cannot be asserted from mid-stream).
            "rotation": self._rotations,
        }

    def record_policy(self, config: Dict):
        """The PolicyConfig the live loop runs — the replay identity
        invariant replays THIS config against the snapshots. Cached so
        rotation re-emits it into every file."""
        rec = {"kind": "policy", "v": SCHEMA_VERSION, "config": dict(config)}
        with self._lock:
            self._policy_record = rec
            self._write_locked(rec)

    def record_snapshot(self, snap: SignalSnapshot):
        self._emit({
            "kind": "snapshot",
            "v": SCHEMA_VERSION,
            "seq": snap.seq,
            "wall": snap.ts,
            "mono": snap.mono,
            "values": snap.values,
        })

    def record_decision(self, decision) -> None:
        """One ledger entry, AFTER actuation so ``outcome`` carries the
        actuation result (actuated/dry_run/advisory/error:<why>)."""
        rec = {"kind": "decision", "v": SCHEMA_VERSION}
        rec.update(decision.to_dict())
        self._emit(rec)

    def record_outcome(self, decision_seq: int, realized: Dict):
        self._emit({
            "kind": "outcome",
            "v": SCHEMA_VERSION,
            "decision_seq": decision_seq,
            "wall": time.time(),
            "mono": time.monotonic(),
            "realized": dict(realized),
        })

    # ---- plumbing ----------------------------------------------------------

    def _emit(self, rec: Dict):
        with self._lock:
            self._write_locked(rec)

    def _write_locked(self, rec: Dict):
        if self._closed:
            return
        line = json.dumps(rec, default=str)
        # Recording must never kill the loop: a failed rotation can
        # leave the handle closed (tell() then raises ValueError, not
        # OSError), so both are caught and a reopen is attempted —
        # degraded-but-writing beats a recorder that poisons every
        # subsequent tick.
        try:
            if self._f.closed:
                self._f = open(self._path, "a")
            if self._f.tell() + len(line) + 1 > self._max_bytes:
                self._rotate_locked()
            self._f.write(line + "\n")
            self._f.flush()
            if self._fsync:
                os.fsync(self._f.fileno())
            self._records_written += 1
        except (OSError, ValueError) as e:
            logger.warning("signal recorder write failed: %s", e)

    def _rotate_locked(self):
        self._f.close()
        try:
            # Shift generations up; the one past the bound is deleted.
            oldest = f"{self._path}.{self._max_files - 1}"
            if os.path.exists(oldest):
                os.unlink(oldest)
            for i in range(self._max_files - 2, 0, -1):
                src = f"{self._path}.{i}"
                if os.path.exists(src):
                    os.replace(src, f"{self._path}.{i + 1}")
            if self._max_files > 1:
                os.replace(self._path, f"{self._path}.1")
            else:
                os.unlink(self._path)
        finally:
            # Whatever the shuffle did, leave an OPEN handle behind: a
            # half-rotated chain still records (and retries rotation on
            # the next oversize write).
            self._f = open(self._path, "a")
        self._rotations += 1
        # Each file is self-describing: fresh header + the live policy.
        hdr = json.dumps(self._header(), default=str)
        self._f.write(hdr + "\n")
        if self._policy_record is not None:
            self._f.write(
                json.dumps(self._policy_record, default=str) + "\n"
            )
        self._f.flush()

    def stats(self) -> Dict:
        with self._lock:
            return {
                "path": self._path,
                "records_written": self._records_written,
                "rotations": self._rotations,
                "fsync": self._fsync,
                "max_bytes": self._max_bytes,
            }

    def close(self):
        with self._lock:
            if self._closed:
                return
            self._closed = True
            try:
                self._f.flush()
                if self._fsync:
                    os.fsync(self._f.fileno())
                self._f.close()
            except OSError:
                pass


def recorder_from_env() -> Optional[SignalRecorder]:
    """Arm a recorder from ``DLROVER_TPU_AUTOSCALE_RECORD`` — the
    subprocess-worker rigging, mirroring the fault plane's env arming.
    Returns None when the env var is unset."""
    path = os.getenv(RECORD_ENV, "")
    if not path:
        return None
    fsync = os.getenv(RECORD_FSYNC_ENV, "1") != "0"
    return SignalRecorder(path, fsync=fsync)


# ---------------------------------------------------------------------------
# Reader
# ---------------------------------------------------------------------------


@dataclass
class Recording:
    """A loaded recording: snapshots ordered by the MONOTONIC stamp
    (wall-clock steps cannot reorder them), the recorded policy config,
    the decision stream, and outcome backfills keyed by decision seq."""

    schema_version: int = SCHEMA_VERSION
    policy_config: Optional[Dict] = None
    snapshots: List[SignalSnapshot] = field(default_factory=list)
    decisions: List[Dict] = field(default_factory=list)
    outcomes: Dict[int, Dict] = field(default_factory=dict)
    files: List[str] = field(default_factory=list)
    corrupt_lines: int = 0
    headers: List[Dict] = field(default_factory=list)
    # True when the rotation bound deleted the stream's beginning:
    # policy state accrued in the deleted era is unknowable, so the
    # replay identity invariant cannot be asserted (ranking still can).
    truncated: bool = False
    # Earlier writer incarnations found in the same path (a restarted
    # master appends): the loader keeps only the NEWEST run — mixing
    # runs would interleave reset monotonic clocks and stale policy
    # state into one stream and fail identity with a bogus divergence.
    previous_runs: int = 0


def _recording_chain(path: str, max_files: int = 64) -> List[str]:
    """Rotation chain oldest-first: <path>.N ... <path>.1, <path>."""
    chain = []
    for i in range(max_files, 0, -1):
        gen = f"{path}.{i}"
        if os.path.exists(gen):
            chain.append(gen)
    if os.path.exists(path):
        chain.append(path)
    return chain


def load_recording(path: str) -> Recording:
    """Parse a recording (and its rotated generations). A torn final
    line — the SIGKILL case the fsync discipline exists for — is
    skipped and counted, never fatal; an unknown FUTURE schema version
    raises (old readers must not silently misparse new streams). A
    rotation-0 header marks a fresh writer incarnation (a restarted
    master appending to the same path): each one RESETS the stream so
    only the newest run is returned (``previous_runs`` counts the
    discarded ones) — runs must not interleave, their monotonic clocks
    restart from boot."""
    rec = Recording()
    rec.files = _recording_chain(path)
    if not rec.files:
        raise FileNotFoundError(f"no recording at {path}")
    for file_path in rec.files:
        with open(file_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except json.JSONDecodeError:
                    rec.corrupt_lines += 1
                    continue
                kind = obj.get("kind")
                version = int(obj.get("v", 0))
                if version > SCHEMA_VERSION:
                    raise ValueError(
                        f"recording schema v{version} is newer than "
                        f"this reader (v{SCHEMA_VERSION}): {file_path}"
                    )
                if kind == "header":
                    if (int(obj.get("rotation", 0)) == 0
                            and rec.headers):
                        # A fresh incarnation: drop everything the
                        # previous run wrote and start over — including
                        # its torn-line count, which must not indict
                        # the clean newest run.
                        rec.previous_runs += 1
                        rec.headers = []
                        rec.policy_config = None
                        rec.snapshots = []
                        rec.decisions = []
                        rec.outcomes = {}
                        rec.corrupt_lines = 0
                    rec.headers.append(obj)
                elif kind == "policy":
                    rec.policy_config = obj.get("config") or {}
                elif kind == "snapshot":
                    rec.snapshots.append(SignalSnapshot(
                        seq=int(obj.get("seq", 0)),
                        ts=float(obj.get("wall", 0.0)),
                        mono=float(obj.get("mono", 0.0)),
                        values=obj.get("values") or {},
                    ))
                elif kind == "outcome":
                    rec.outcomes[int(obj.get("decision_seq", 0))] = (
                        obj.get("realized") or {}
                    )
                elif kind == "decision":
                    rec.decisions.append(obj)
    if rec.headers:
        rec.truncated = min(
            int(h.get("rotation", 0)) for h in rec.headers
        ) > 0
    # Monotonic order is the replay order: a wall-clock step (NTP slew)
    # mid-run must not reorder the stream. Seq breaks mono ties.
    rec.snapshots.sort(key=lambda s: (s.mono, s.seq))
    rec.decisions.sort(
        key=lambda d: (float(d.get("mono", 0.0)), int(d.get("seq", 0)))
    )
    return rec
