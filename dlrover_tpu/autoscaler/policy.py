"""Deterministic rule policy: signal snapshots -> typed ScaleDecisions.

Deliberately simple and explainable (docs/DESIGN.md §30): no learned
models, just rules an SRE can read back from the decision ledger —

- **straggler eviction**: a rank the §29 straggler report flags
  (step-time EWMA ≥ ``straggler_score`` × fleet median) for
  ``straggler_confirm_ticks`` consecutive snapshots is evicted and
  replaced. Confirmation ticks are the hysteresis: one slow step (GC
  pause, page-in) must not cost a worker.
- **ckpt cadence**: once the fault plane has an *observed* MTBF, the
  Young/Daly interval (:func:`optimal_save_interval_s`) replaces the
  configured cadence — but only when it moves more than
  ``ckpt_retune_frac`` from the current value (dead band against
  cadence flapping as the MTBF estimate wanders).
- **training world**: shard backlog per worker above/below a band
  grows/shrinks the world within ``[min_world, max_world]``
  (``max_world == 0`` pins the world: world moves are opt-in because
  a rescale is never free).
- **serving fleet**: slot/queue utilization above ``fleet_util_grow``
  for ``fleet_confirm_ticks`` snapshots adds a replica; below
  ``fleet_util_shrink`` drains one. The gap between the two thresholds
  is the hysteresis band; a utilization that lives inside it changes
  nothing.

Every action kind has its own cooldown, measured against SNAPSHOT
timestamps (not wall reads), so the policy is clockless and replayable:
the same snapshot sequence always yields the same decision sequence —
which is what makes dry-run mode's ledger bit-comparable to a live
run's.
"""

import threading
from collections import deque
from dataclasses import asdict, dataclass, field, fields
from typing import Deque, Dict, List, Optional

from dlrover_tpu.autoscaler.signals import SignalSnapshot
from dlrover_tpu.flash_ckpt.autotune import optimal_save_interval_s

# Decision kinds (the typed actions the actuator layer binds).
EVICT_STRAGGLER = "evict_straggler"
GROW_WORLD = "grow_world"
SHRINK_WORLD = "shrink_world"
GROW_FLEET = "grow_fleet"
SHRINK_FLEET = "shrink_fleet"
SET_CKPT_INTERVAL = "set_ckpt_interval"
SEED_WORLD = "seed_world"          # brain prior at job start

ACTIONS = (
    EVICT_STRAGGLER,
    GROW_WORLD,
    SHRINK_WORLD,
    GROW_FLEET,
    SHRINK_FLEET,
    SET_CKPT_INTERVAL,
    SEED_WORLD,
)


@dataclass
class ScaleDecision:
    """One typed decision plus the evidence that triggered it.

    ``signals`` is a copy of the triggering snapshot's values — the
    ledger's no-unexplained-actions contract. ``outcome`` records what
    the loop did with it: ``"actuated"``, ``"dry_run"``, ``"advisory"``
    (no actuator bound — e.g. ckpt cadence on a master that only
    publishes the recommendation), or ``"error:<msg>"``.

    ``mono`` mirrors the triggering snapshot's monotonic stamp (replay
    ordering); ``realized`` is the outcome-attribution backfill the
    loop writes once the decision's attribution window closes — the
    measured effect (goodput delta, straggler-score drop, backlog
    drain, avoided-replay estimate), not the intent.
    """

    action: str
    target: object
    reason: str
    signals: Dict[str, object] = field(default_factory=dict)
    ts: float = 0.0
    seq: int = 0
    outcome: str = ""
    mono: float = 0.0
    realized: Optional[Dict[str, object]] = None

    def to_dict(self, include_signals: bool = True) -> Dict[str, object]:
        """``include_signals=False`` is the dashboard's compact mode:
        the triggering snapshot (potentially thousands of per-rank
        values) is replaced by its key count, never copied."""
        out = {
            "seq": self.seq,
            "ts": self.ts,
            "mono": self.mono,
            "action": self.action,
            "target": self.target,
            "reason": self.reason,
            "outcome": self.outcome,
        }
        if include_signals:
            out["signals"] = dict(self.signals)
        else:
            out["signals"] = {}
            out["signals_truncated"] = True
            out["signal_keys"] = len(self.signals)
        if self.realized is not None:
            out["realized"] = dict(self.realized)
        return out


class DecisionLedger:
    """Bounded, thread-safe record of every decision the loop took."""

    def __init__(self, maxlen: int = 512):
        self._lock = threading.Lock()
        self._entries: Deque[ScaleDecision] = deque(maxlen=max(maxlen, 1))
        self._seq = 0
        self._total = 0
        self._actuated = 0
        self._outcomes = 0
        self._outcome_misses = 0

    def append(self, decision: ScaleDecision) -> ScaleDecision:
        with self._lock:
            self._seq += 1
            decision.seq = self._seq
            self._entries.append(decision)
            self._total += 1
            if decision.outcome == "actuated":
                self._actuated += 1
        return decision

    def attach_outcome(self, seq: int, realized: Dict) -> bool:
        """Backfill the realized effect onto the ledger entry with this
        seq. An entry already evicted by the bound is a COUNTED no-op
        (False), never a KeyError — a long attribution window on a
        small ledger must not crash the loop."""
        with self._lock:
            for d in reversed(self._entries):
                if d.seq == seq:
                    d.realized = dict(realized)
                    self._outcomes += 1
                    return True
                if d.seq < seq:
                    break  # entries are seq-ascending; it's gone
            self._outcome_misses += 1
            return False

    def entries(self, last: Optional[int] = None,
                offset: int = 0) -> List[ScaleDecision]:
        """The newest ``last`` entries (all when falsy), after skipping
        the ``offset`` newest — the /api/autoscaler pagination window
        (offset pages BACKWARD through history)."""
        with self._lock:
            items = list(self._entries)
        if offset > 0:
            items = items[:-offset] if offset < len(items) else []
        return items[-last:] if last else items

    @property
    def decisions_total(self) -> int:
        with self._lock:
            return self._total

    @property
    def actuations_total(self) -> int:
        with self._lock:
            return self._actuated

    @property
    def outcomes_total(self) -> int:
        with self._lock:
            return self._outcomes

    @property
    def outcome_misses_total(self) -> int:
        with self._lock:
            return self._outcome_misses


@dataclass
class PolicyConfig:
    # straggler eviction
    straggler_score: float = 1.5
    straggler_confirm_ticks: int = 2
    evict_cooldown_s: float = 10.0
    # ckpt cadence (Young/Daly from observed MTBF)
    ckpt_retune_frac: float = 0.2
    ckpt_min_interval_s: float = 0.05
    ckpt_max_interval_s: float = 600.0
    ckpt_cooldown_s: float = 5.0
    default_save_block_s: float = 0.01
    # training world (pinned unless max_world > 0)
    min_world: int = 1
    max_world: int = 0
    # Legal mesh shapes: when given, grow/shrink target the NEXT legal
    # count instead of size±1 — the policy must never order a world
    # the rendezvous would refuse to form.
    legal_world_counts: Optional[List[int]] = None
    backlog_grow_per_worker: float = 256.0
    backlog_shrink_per_worker: float = 16.0
    world_cooldown_s: float = 60.0
    # serving fleet (pinned unless max_replicas > 0)
    min_replicas: int = 1
    max_replicas: int = 0
    fleet_util_grow: float = 0.85
    fleet_util_shrink: float = 0.30
    fleet_confirm_ticks: int = 2
    fleet_cooldown_s: float = 10.0

    def to_dict(self) -> Dict[str, object]:
        return asdict(self)

    @classmethod
    def from_dict(cls, data: Dict) -> "PolicyConfig":
        """Tolerant load for recordings: unknown keys (a newer writer's
        fields) are dropped so an old reader can still replay."""
        known = {f.name for f in fields(cls)}
        return cls(**{k: v for k, v in (data or {}).items()
                      if k in known})


class RulePolicy:
    """See module docstring. Stateful only in confirmation counters and
    per-action cooldown timestamps; all time math uses snapshot
    timestamps, so replaying snapshots replays decisions."""

    def __init__(self, config: Optional[PolicyConfig] = None):
        self.config = config or PolicyConfig()
        self._last_action_ts: Dict[str, float] = {}
        self._straggler_streak: Dict[int, int] = {}
        self._fleet_hi_streak = 0
        self._fleet_lo_streak = 0

    # ---- helpers -----------------------------------------------------------

    def _cooled(self, snap: SignalSnapshot, action: str,
                cooldown_s: float) -> bool:
        last = self._last_action_ts.get(action)
        return last is None or snap.ts - last >= cooldown_s

    def _fire(self, snap: SignalSnapshot, action: str, target, reason: str,
              out: List[ScaleDecision]):
        self._last_action_ts[action] = snap.ts
        out.append(ScaleDecision(
            action=action, target=target, reason=reason,
            signals=dict(snap.values), ts=snap.ts, mono=snap.mono,
        ))

    # ---- the rules ---------------------------------------------------------

    def decide(self, snap: SignalSnapshot) -> List[ScaleDecision]:
        out: List[ScaleDecision] = []
        self._straggler_rule(snap, out)
        self._ckpt_rule(snap, out)
        self._world_rule(snap, out)
        self._fleet_rule(snap, out)
        return out

    def _straggler_rule(self, snap: SignalSnapshot,
                        out: List[ScaleDecision]):
        scores = snap.get("perf.straggler_scores") or {}

        def score_of(rank):
            return float(scores.get(rank, scores.get(str(rank), 0.0)))

        # The monitor's report flags at ITS threshold (min-reports
        # gating included); this config's straggler_score re-filters on
        # top, so raising the bar here really raises it. (A bar BELOW
        # the monitor's default needs perf_source(threshold=...) too —
        # the monitor never reports ranks under its own threshold.)
        flagged = [
            int(r) for r in (snap.get("perf.straggler_ranks") or [])
            if score_of(r) >= self.config.straggler_score
        ]
        # Streaks survive only for ranks flagged THIS snapshot.
        self._straggler_streak = {
            r: self._straggler_streak.get(r, 0) + 1 for r in flagged
        }
        if not flagged:
            return
        # Worst offender first; one eviction per cooldown window.
        rank = max(flagged, key=score_of)
        streak = self._straggler_streak.get(rank, 0)
        if streak < self.config.straggler_confirm_ticks:
            return
        if not self._cooled(snap, EVICT_STRAGGLER,
                            self.config.evict_cooldown_s):
            return
        score = score_of(rank)
        self._fire(
            snap, EVICT_STRAGGLER, rank,
            f"rank {rank} step-time score {score:.2f} >= "
            f"{self.config.straggler_score} for {streak} consecutive "
            f"snapshots (median {snap.get('perf.median_step_s')}s)",
            out,
        )
        # The seat's next occupant starts with a clean streak: without
        # this, a replacement still flagged by a stale EWMA would be
        # evicted the moment the cooldown expires.
        self._straggler_streak.pop(rank, None)

    def _ckpt_rule(self, snap: SignalSnapshot, out: List[ScaleDecision]):
        mtbf = snap.get("fault.mtbf_s")
        current = snap.get("ckpt.interval_s")
        if mtbf is None or current is None or current <= 0:
            return
        save_block = snap.get(
            "ckpt.save_block_s", self.config.default_save_block_s
        )
        drain = snap.get("ckpt.drain_s", 0.0)
        target = optimal_save_interval_s(
            save_block, drain_s=drain, mtbf_s=mtbf,
            min_interval_s=self.config.ckpt_min_interval_s,
            max_interval_s=self.config.ckpt_max_interval_s,
        )
        # Dead band: MTBF estimates wander; cadence must not flap.
        if abs(target - current) / current <= self.config.ckpt_retune_frac:
            return
        if not self._cooled(snap, SET_CKPT_INTERVAL,
                            self.config.ckpt_cooldown_s):
            return
        self._fire(
            snap, SET_CKPT_INTERVAL, round(target, 4),
            f"observed MTBF {mtbf:.2f}s + save block {save_block:.4f}s "
            f"-> Young/Daly interval {target:.2f}s (was {current:.2f}s)",
            out,
        )

    def _next_world(self, size: int, up: bool) -> Optional[int]:
        """size±1, or the next LEGAL count in that direction when a
        mesh-shape list is configured; None = no legal move."""
        counts = self.config.legal_world_counts
        if not counts:
            target = size + 1 if up else size - 1
        else:
            ordered = sorted(set(counts))
            if up:
                bigger = [
                    c for c in ordered
                    if size < c <= self.config.max_world
                ]
                target = bigger[0] if bigger else None
            else:
                smaller = [
                    c for c in ordered
                    if self.config.min_world <= c < size
                ]
                target = smaller[-1] if smaller else None
        if target is None:
            return None
        if not self.config.min_world <= target <= self.config.max_world:
            return None
        return target

    def _world_rule(self, snap: SignalSnapshot, out: List[ScaleDecision]):
        if self.config.max_world <= 0:
            return  # world pinned: rescales are opt-in
        size = snap.get("world.size")
        todo = snap.get("data.todo")
        if not size or todo is None:
            return
        if not self._cooled(snap, GROW_WORLD, self.config.world_cooldown_s):
            return
        per_worker = todo / max(size, 1)
        if (per_worker > self.config.backlog_grow_per_worker
                and size < self.config.max_world
                and self._next_world(size, up=True) is not None):
            # One cooldown clock for both directions — a grow must not
            # be immediately answered by a shrink.
            self._last_action_ts[SHRINK_WORLD] = snap.ts
            self._fire(
                snap, GROW_WORLD, self._next_world(size, up=True),
                f"shard backlog {todo} = {per_worker:.0f}/worker > "
                f"{self.config.backlog_grow_per_worker:.0f} at world "
                f"{size}",
                out,
            )
        elif (per_worker < self.config.backlog_shrink_per_worker
                and size > self.config.min_world and todo > 0
                and self._next_world(size, up=False) is not None):
            self._last_action_ts[GROW_WORLD] = snap.ts
            self._fire(
                snap, SHRINK_WORLD, self._next_world(size, up=False),
                f"shard backlog {todo} = {per_worker:.1f}/worker < "
                f"{self.config.backlog_shrink_per_worker:.0f} at world "
                f"{size}",
                out,
            )

    def _fleet_rule(self, snap: SignalSnapshot, out: List[ScaleDecision]):
        if self.config.max_replicas <= 0:
            return  # fleet pinned
        replicas = snap.get("fleet.replicas")
        util = snap.get("fleet.slot_util")
        if replicas is None or util is None:
            return
        if util >= self.config.fleet_util_grow:
            self._fleet_hi_streak += 1
            self._fleet_lo_streak = 0
        elif util <= self.config.fleet_util_shrink:
            self._fleet_lo_streak += 1
            self._fleet_hi_streak = 0
        else:
            # Inside the hysteresis band: nothing changes.
            self._fleet_hi_streak = 0
            self._fleet_lo_streak = 0
            return
        confirm = self.config.fleet_confirm_ticks
        if (self._fleet_hi_streak >= confirm
                and replicas < self.config.max_replicas
                and self._cooled(snap, GROW_FLEET,
                                 self.config.fleet_cooldown_s)):
            self._last_action_ts[SHRINK_FLEET] = snap.ts
            self._fire(
                snap, GROW_FLEET, int(replicas) + 1,
                f"fleet utilization {util:.2f} >= "
                f"{self.config.fleet_util_grow} for "
                f"{self._fleet_hi_streak} snapshots at {replicas} "
                f"replicas (queue {snap.get('fleet.queue_depth')})",
                out,
            )
        elif (self._fleet_lo_streak >= confirm
                and replicas > self.config.min_replicas
                and self._cooled(snap, SHRINK_FLEET,
                                 self.config.fleet_cooldown_s)):
            self._last_action_ts[GROW_FLEET] = snap.ts
            self._fire(
                snap, SHRINK_FLEET, int(replicas) - 1,
                f"fleet utilization {util:.2f} <= "
                f"{self.config.fleet_util_shrink} for "
                f"{self._fleet_lo_streak} snapshots at {replicas} "
                f"replicas",
                out,
            )
