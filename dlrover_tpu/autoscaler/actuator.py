"""Actuator layer: bind ScaleDecisions to the subsystems that move.

Three concrete actuators cover the decision taxonomy:

- :class:`TrainWorldActuator` — training-world changes through the
  :class:`~dlrover_tpu.master.scaler.base_scaler.Scaler` ABC
  (``ScalePlan`` launch/remove, group resize) and, when wired, the §27
  rescale coordinator (``evict_worker`` cuts the scale-down plan that
  rolls the surviving world forward without a restart).
- :class:`FleetActuator` — serving-fleet sizing through the §28
  :class:`FleetRouter` (``add_replica`` / ``drain_replica``), replicas
  built by a caller-supplied factory.
- :class:`CadenceController` — the flash-ckpt cadence knob: a
  thread-safe holder the training loop polls (``interval_s()``) and
  the SET_CKPT_INTERVAL decision writes. Also a SignalBus source so
  the policy sees the cadence it is steering (``as_source``).

Each actuator is a plain object with decision-shaped methods; the
:class:`~dlrover_tpu.autoscaler.loop.AutoScaler` binds them by action
name. An unbound action is *advisory* — recorded in the ledger, acted
on by nobody — which is exactly how a master publishes a cadence
recommendation it has no channel to push.
"""

import threading
from typing import Callable, Dict, List, Optional

from dlrover_tpu.autoscaler.policy import (
    EVICT_STRAGGLER,
    GROW_FLEET,
    GROW_WORLD,
    SEED_WORLD,
    SET_CKPT_INTERVAL,
    SHRINK_FLEET,
    SHRINK_WORLD,
    ScaleDecision,
)
from dlrover_tpu.common.constants import NodeType
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.node import Node, NodeGroupResource
from dlrover_tpu.master.scaler.base_scaler import ScalePlan


class CadenceController:
    """The checkpoint-cadence knob, shared between the autoscaler (the
    writer) and whatever paces saves (the reader): the soak harness's
    sim trainer, or a real training loop polling ``interval_s()``
    between steps. Tracks the measured per-save blocking cost so the
    policy's Young/Daly math uses live numbers."""

    def __init__(self, interval_s: float,
                 save_block_s: float = 0.01,
                 drain_s: float = 0.0):
        self._lock = threading.Lock()
        self._interval_s = float(interval_s)
        self._save_block_s = float(save_block_s)
        self._drain_s = float(drain_s)
        self._retunes = 0

    def interval_s(self) -> float:
        with self._lock:
            return self._interval_s

    def set_interval_s(self, value: float):
        with self._lock:
            self._interval_s = max(float(value), 1e-4)
            self._retunes += 1

    def record_save_block(self, seconds: float):
        with self._lock:
            self._save_block_s = float(seconds)

    def record_drain(self, seconds: float):
        with self._lock:
            self._drain_s = float(seconds)

    @property
    def retunes(self) -> int:
        with self._lock:
            return self._retunes

    def as_source(self) -> Callable[[], Dict[str, object]]:
        def fn() -> Dict[str, object]:
            with self._lock:
                return {
                    "interval_s": self._interval_s,
                    "save_block_s": self._save_block_s,
                    "drain_s": self._drain_s,
                }
        return fn

    def apply(self, decision: ScaleDecision):
        self.set_interval_s(float(decision.target))


class TrainWorldActuator:
    """Training-world moves through a ``Scaler`` backend.

    ``nodes_fn`` returns the live worker :class:`Node` list (the sim
    scaler's ``alive_nodes``, or a job manager's);``node_id_fn``
    allocates fresh node ids. ``coordinator`` (optional) is the §27
    rescale coordinator: evictions tell it first so the surviving
    world re-plans instead of waiting out a barrier on a rank the
    scaler already removed.
    """

    def __init__(
        self,
        scaler,
        nodes_fn: Callable[[], List[Node]],
        node_id_fn: Callable[[], int],
        coordinator=None,
        node_type: str = NodeType.WORKER,
        on_evicted: Optional[Callable[[int], None]] = None,
    ):
        self._scaler = scaler
        self._nodes_fn = nodes_fn
        self._node_id_fn = node_id_fn
        self._coordinator = coordinator
        self._node_type = node_type
        # Typically PerfMonitor.reset_rank: the seat's next occupant
        # must not inherit the evictee's slow step-time EWMA.
        self._on_evicted = on_evicted

    @classmethod
    def for_sim(cls, sim_scaler, coordinator=None,
                on_evicted: Optional[Callable[[int], None]] = None
                ) -> "TrainWorldActuator":
        return cls(
            sim_scaler,
            nodes_fn=sim_scaler.alive_nodes,
            node_id_fn=sim_scaler.next_node_id,
            coordinator=coordinator,
            on_evicted=on_evicted,
        )

    def world_size(self) -> int:
        return len(self._nodes_fn())

    def as_source(self) -> Callable[[], Dict[str, object]]:
        def fn() -> Dict[str, object]:
            return {"size": self.world_size()}
        return fn

    def evict(self, decision: ScaleDecision):
        """Evict-and-replace: remove the flagged rank's node, launch a
        fresh one in the same seat count (world size is preserved; the
        *host* is what the decision condemns)."""
        rank = int(decision.target)
        victims = [
            n for n in self._nodes_fn()
            if n.rank_index == rank and n.type == self._node_type
        ]
        if not victims:
            raise ValueError(f"no live {self._node_type} with rank {rank}")
        victim = victims[0]
        if self._coordinator is not None:
            self._coordinator.evict_worker(rank, reason="straggler_evict")
        replacement = Node(
            self._node_type,
            self._node_id_fn(),
            rank_index=rank,
            config_resource=victim.config_resource,
        )
        plan = ScalePlan(
            launch_nodes=[replacement], remove_nodes=[victim]
        )
        self._scaler.scale(plan)
        if self._on_evicted is not None:
            self._on_evicted(rank)
        logger.info(
            "autoscaler evicted straggler rank %d (node %d -> node %d)",
            rank, victim.id, replacement.id,
        )

    def set_world(self, decision: ScaleDecision):
        target = int(decision.target)
        plan = ScalePlan()
        plan.node_group_resources[self._node_type] = NodeGroupResource(
            count=target
        )
        self._scaler.scale(plan)
        logger.info("autoscaler set %s world -> %d",
                    self._node_type, target)

    def bindings(self) -> Dict[str, Callable[[ScaleDecision], None]]:
        return {
            EVICT_STRAGGLER: self.evict,
            GROW_WORLD: self.set_world,
            SHRINK_WORLD: self.set_world,
            SEED_WORLD: self.set_world,
        }


class FleetActuator:
    """Serving-fleet sizing through the FleetRouter.

    ``replica_factory(replica_id) -> replica`` builds whatever replica
    flavor the deployment runs (thread, subprocess). Draining is
    last-added-first over the replicas THIS actuator added (a
    grow/shrink pair is a no-op fleet and the original replicas are
    never touched while an added one remains); with none of its own
    left it falls back to the router's lexicographically-last id.
    ``drain_replica`` live-migrates in-flight decodes off the victim
    (§36) before anything requeues from zero, so a shrink decision
    costs each in-flight request a migration pause, not a re-prefill."""

    def __init__(self, router, replica_factory: Callable[[str], object],
                 id_prefix: str = "as"):
        self._router = router
        self._factory = replica_factory
        self._prefix = id_prefix
        self._next = 0
        self._added: List[str] = []   # LIFO of ids this actuator grew

    def grow(self, decision: ScaleDecision):
        rid = f"{self._prefix}{self._next}"
        self._next += 1
        replica = self._factory(rid)
        self._router.add_replica(replica)
        self._added.append(rid)
        logger.info("autoscaler added fleet replica %s", rid)

    def shrink(self, decision: ScaleDecision):
        ids = self._router.replica_ids()
        if len(ids) <= 1:
            raise ValueError("refusing to drain the last fleet replica")
        present = set(ids)
        rid = None
        while self._added:
            candidate = self._added.pop()
            if candidate in present:   # router may have dropped it
                rid = candidate
                break
        if rid is None:
            rid = ids[-1]
        self._router.drain_replica(rid)
        logger.info("autoscaler drained fleet replica %s", rid)

    def bindings(self) -> Dict[str, Callable[[ScaleDecision], None]]:
        return {GROW_FLEET: self.grow, SHRINK_FLEET: self.shrink}
