"""Closed-loop autoscaler: observe goodput signals, decide, actuate.

The L1 "resource brain" control loop (docs/DESIGN.md §30): a
:class:`~dlrover_tpu.autoscaler.signals.SignalBus` samples the live
signal plane (per-rank step-time EWMAs + straggler scores, shard-queue
depths, serving fleet load, fault history + observed MTBF, running
goodput), a deterministic rule
:class:`~dlrover_tpu.autoscaler.policy.RulePolicy` (hysteresis bands,
per-action cooldowns) turns snapshots into typed
:class:`~dlrover_tpu.autoscaler.policy.ScaleDecision`\\ s, and the
:class:`~dlrover_tpu.autoscaler.loop.AutoScaler` loop actuates them —
rescale-coordinator evictions, :class:`ScalePlan`\\ s against a
``Scaler`` backend, serving-fleet add/drain, flash-ckpt cadence — with
every decision landing in a ledger alongside the exact signal snapshot
that triggered it. ``dry_run=True`` produces the same ledger with zero
actuations.
"""

from dlrover_tpu.autoscaler.actuator import (
    CadenceController,
    FleetActuator,
    TrainWorldActuator,
)
from dlrover_tpu.autoscaler.loop import AutoScaler, BrainPrior
from dlrover_tpu.autoscaler.recorder import (
    RECORD_ENV,
    Recording,
    SignalRecorder,
    load_recording,
    recorder_from_env,
)
from dlrover_tpu.autoscaler.replay import (
    CostModel,
    ReplayMismatch,
    assert_replay_identity,
    diff_ledgers,
    rank_policies,
    replay_policy,
    replay_recording,
    score_ledger,
)
from dlrover_tpu.autoscaler.policy import (
    ACTIONS,
    EVICT_STRAGGLER,
    GROW_FLEET,
    GROW_WORLD,
    SEED_WORLD,
    SET_CKPT_INTERVAL,
    SHRINK_FLEET,
    SHRINK_WORLD,
    DecisionLedger,
    PolicyConfig,
    RulePolicy,
    ScaleDecision,
)
from dlrover_tpu.autoscaler.signals import (
    FaultHistory,
    SignalBus,
    SignalSnapshot,
    control_plane_source,
    data_source,
    fault_source,
    fleet_source,
    kvpool_source,
    perf_source,
)

__all__ = [
    "AutoScaler",
    "BrainPrior",
    "SignalRecorder",
    "Recording",
    "load_recording",
    "recorder_from_env",
    "RECORD_ENV",
    "CostModel",
    "ReplayMismatch",
    "assert_replay_identity",
    "diff_ledgers",
    "rank_policies",
    "replay_policy",
    "replay_recording",
    "score_ledger",
    "SignalBus",
    "SignalSnapshot",
    "FaultHistory",
    "perf_source",
    "data_source",
    "fleet_source",
    "fault_source",
    "kvpool_source",
    "control_plane_source",
    "RulePolicy",
    "PolicyConfig",
    "ScaleDecision",
    "DecisionLedger",
    "ACTIONS",
    "EVICT_STRAGGLER",
    "GROW_WORLD",
    "SHRINK_WORLD",
    "GROW_FLEET",
    "SHRINK_FLEET",
    "SET_CKPT_INTERVAL",
    "SEED_WORLD",
    "TrainWorldActuator",
    "FleetActuator",
    "CadenceController",
]
