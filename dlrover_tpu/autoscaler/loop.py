"""AutoScaler: the observe -> decide -> act loop, with a ledger.

One tick = sample the SignalBus, run the policy, actuate (or record
without acting in dry-run / advisory cases), append every decision to
the ledger with its triggering snapshot. The loop can run as a daemon
thread on a cadence (the masters do this) or be ticked synchronously
(tests and the soak harness, which want deterministic pacing).

Decisions emit ``autoscaler_*`` metrics and — when tracing is armed —
one ``autoscaler.decision`` span each, carrying action/target/outcome,
so a scale action shows up in the same trace plane as the RPCs and
training steps it perturbs (§29).

The optional :class:`BrainPrior` wires the §-brain cross-job optimizer
in as a *prior*: at start the autoscaler may seed its initial
world-size target from ``/optimize`` (a SEED_WORLD decision, through
the same ledger/actuation path as everything else), and at stop it
reports the achieved goodput back to ``/persist_metrics`` so the next
job of this name starts smarter.
"""

import threading
import time
from typing import Callable, Dict, List, Optional

from dlrover_tpu.autoscaler.policy import (
    DecisionLedger,
    RulePolicy,
    ScaleDecision,
    SEED_WORLD,
)
from dlrover_tpu.autoscaler.signals import SignalBus, SignalSnapshot
from dlrover_tpu.common.log import logger


def _metrics(registry=None):
    from dlrover_tpu.observability.registry import default_registry

    reg = registry or default_registry()
    return {
        "ticks": reg.counter(
            "autoscaler_ticks_total",
            "autoscaler observe/decide/act iterations",
        ),
        "decisions": reg.counter(
            "autoscaler_decisions_total",
            "scale decisions emitted, by action",
            labelnames=("action",),
        ),
        "actuations": reg.counter(
            "autoscaler_actuations_total",
            "decisions actually actuated, by action",
            labelnames=("action",),
        ),
        "errors": reg.counter(
            "autoscaler_actuation_errors_total",
            "actuations that raised, by action",
            labelnames=("action",),
        ),
        "dry_run": reg.gauge(
            "autoscaler_dry_run",
            "1 when the loop is advisory-only (no actuations)",
        ),
        "ckpt_interval": reg.gauge(
            "autoscaler_ckpt_interval_s",
            "checkpoint cadence the autoscaler currently recommends",
        ),
    }


class AutoScaler:
    """The resource brain's control loop (docs/DESIGN.md §30)."""

    def __init__(
        self,
        bus: SignalBus,
        policy: Optional[RulePolicy] = None,
        actuators: Optional[
            Dict[str, Callable[[ScaleDecision], None]]
        ] = None,
        interval_s: float = 5.0,
        dry_run: bool = False,
        ledger_size: int = 512,
        clock: Callable[[], float] = time.time,
        registry=None,
        brain_prior: Optional["BrainPrior"] = None,
        job_name: str = "",
    ):
        self.bus = bus
        self.policy = policy or RulePolicy()
        self._actuators = dict(actuators or {})
        self.interval_s = interval_s
        self.dry_run = dry_run
        self.ledger = DecisionLedger(ledger_size)
        self._clock = clock
        self._m = _metrics(registry)
        self._m["dry_run"].set(1.0 if dry_run else 0.0)
        self._brain = brain_prior
        self._job_name = job_name
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._seeded = False
        self._completion_reported = False

    # ---- wiring ------------------------------------------------------------

    def bind(self, action: str, fn: Callable[[ScaleDecision], None]):
        self._actuators[action] = fn
        return self

    def bind_all(self, bindings: Dict[str, Callable[[ScaleDecision], None]]):
        self._actuators.update(bindings)
        return self

    # ---- one iteration -----------------------------------------------------

    def tick(self) -> List[ScaleDecision]:
        """Sample -> decide -> actuate/record. Synchronous drivers (the
        soak harness, tests) call this directly; the daemon thread calls
        it on the cadence."""
        self._m["ticks"].inc()
        snap = self.bus.sample()
        if not self._seeded:
            self._seeded = True
            self._seed_from_brain(snap)
        decisions = self.policy.decide(snap)
        for decision in decisions:
            self._handle(decision)
        return decisions

    def _handle(self, decision: ScaleDecision):
        self._m["decisions"].inc(action=decision.action)
        actuator = self._actuators.get(decision.action)
        span = None
        from dlrover_tpu.observability import tracing

        tracer = tracing.active_tracer()
        if tracer is not None:
            span = tracer.start_span(
                "autoscaler.decision",
                attrs={
                    "action": decision.action,
                    "target": str(decision.target),
                    "dry_run": self.dry_run,
                },
            )
        if self.dry_run:
            decision.outcome = "dry_run"
        elif actuator is None:
            decision.outcome = "advisory"
        else:
            try:
                actuator(decision)
                decision.outcome = "actuated"
                self._m["actuations"].inc(action=decision.action)
            except Exception as e:  # noqa: BLE001 — a failed actuation
                # must not kill the loop; the ledger records the miss.
                decision.outcome = f"error:{type(e).__name__}: {e}"[:200]
                self._m["errors"].inc(action=decision.action)
                logger.warning(
                    "autoscaler actuation failed (%s -> %r): %s",
                    decision.action, decision.target, e,
                )
        if decision.action == "set_ckpt_interval":
            # Published even in dry-run/advisory mode: the gauge IS the
            # recommendation channel for deployments with no push path.
            self._m["ckpt_interval"].set(float(decision.target))
        self.ledger.append(decision)
        if span is not None:
            span.set_attr("outcome", decision.outcome)
            span.set_attr("reason", decision.reason[:200])
            span.end(
                status="ok"
                if not decision.outcome.startswith("error") else "error"
            )
        logger.info(
            "autoscaler decision #%d: %s -> %r (%s) [%s]",
            decision.seq, decision.action, decision.target,
            decision.reason, decision.outcome,
        )

    def _seed_from_brain(self, snap: SignalSnapshot):
        if self._brain is None:
            return
        suggestion = self._brain.initial_world()
        if not suggestion:
            return
        count = int(suggestion.get("worker_count", 0))
        current = snap.get("world.size")
        if count <= 0 or current is None:
            return
        # The prior's suggestion obeys the same legality as every other
        # world move: snap DOWN to the nearest legal mesh shape and
        # clamp to the configured bounds — a brain trained on another
        # cluster must not order a world this rendezvous refuses.
        cfg = self.policy.config
        if cfg.legal_world_counts:
            legal = [
                c for c in sorted(set(cfg.legal_world_counts))
                if c <= count
            ]
            if not legal:
                return
            count = legal[-1]
        if cfg.max_world > 0:
            count = min(count, cfg.max_world)
        count = max(count, cfg.min_world)
        if count == current:
            return
        self._handle(ScaleDecision(
            action=SEED_WORLD,
            target=count,
            reason=(
                f"brain prior: {suggestion.get('optimizer', '?')} "
                f"optimizer suggests {count} workers from "
                f"{suggestion.get('evidence_samples', 0)} past samples "
                f"(current {current})"
            ),
            signals=dict(snap.values),
            ts=snap.ts,
        ))

    # ---- lifecycle ---------------------------------------------------------

    def start(self):
        if self._thread is not None:
            return
        self._stopped.clear()
        self._thread = threading.Thread(
            target=self._loop, name="autoscaler", daemon=True
        )
        self._thread.start()
        logger.info(
            "autoscaler loop started (interval %.1fs%s)",
            self.interval_s, ", DRY RUN" if self.dry_run else "",
        )

    def _loop(self):
        while not self._stopped.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the loop must survive
                logger.exception("autoscaler tick failed")

    def stop(self, success: bool = True):
        self._stopped.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self._report_completion(success)

    def _report_completion(self, success: bool):
        if self._brain is None or self._completion_reported:
            return
        self._completion_reported = True
        snap = self.bus.latest()
        self._brain.report_outcome(
            goodput=float((snap.get("perf.goodput") if snap else 0.0)
                          or 0.0),
            worker_count=int((snap.get("world.size") if snap else 0)
                             or 0),
            speed=float((snap.get("perf.speed") if snap else 0.0) or 0.0),
            success=success,
        )

    # ---- dashboard surface -------------------------------------------------

    def api_state(self, last: int = 50) -> Dict[str, object]:
        """The ``/api/autoscaler`` payload: live signals, the recent
        ledger, and the dry-run diff (decisions the loop took vs
        actuations it performed — in dry-run the gap IS the diff)."""
        snap = self.bus.latest()
        decisions = self.ledger.entries(last=last)
        return {
            "enabled": True,
            "dry_run": self.dry_run,
            "interval_s": self.interval_s,
            "sources": self.bus.source_names(),
            "signals": (
                {"seq": snap.seq, "ts": snap.ts, "values": snap.values}
                if snap is not None else None
            ),
            "decisions": [d.to_dict() for d in decisions],
            "decisions_total": self.ledger.decisions_total,
            "actuations_total": self.ledger.actuations_total,
            "dry_run_diff": {
                "decisions_total": self.ledger.decisions_total,
                "actuations_total": self.ledger.actuations_total,
                "suppressed": (
                    self.ledger.decisions_total
                    - self.ledger.actuations_total
                ),
            },
        }


class BrainPrior:
    """Cross-job prior over the brain service (§-brain): ask
    ``/optimize`` for a starting world size, report the achieved
    goodput back on completion. Every failure degrades to None/no-op —
    an unreachable brain must never gate a job."""

    def __init__(self, brain_addr: str, job_name: str,
                 timeout_s: float = 5.0):
        self._addr = brain_addr
        self._job_name = job_name
        self._timeout = timeout_s

    def _post(self, path: str, payload: Dict) -> Optional[Dict]:
        from dlrover_tpu.brain.client import _post

        return _post(self._addr, path, payload, timeout=self._timeout)

    def initial_world(self) -> Optional[Dict]:
        try:
            result = self._post(
                "/optimize", {"job_name": self._job_name}
            )
        except Exception:  # noqa: BLE001 — prior only, never gate
            logger.warning("brain prior unreachable; no seed")
            return None
        plan = (result or {}).get("plan")
        if not isinstance(plan, dict) or not plan.get("worker_count"):
            return None
        return plan

    def report_outcome(self, goodput: float, worker_count: int,
                       speed: float = 0.0, success: bool = True):
        """Achieved-goodput report-back: a runtime sample (so the
        optimizer's per-count evidence grows) plus a completion record."""
        try:
            self._post("/persist_metrics", {
                "kind": "runtime",
                "record": {
                    "job_name": self._job_name,
                    "speed": speed,
                    "goodput": goodput,
                    "worker_count": worker_count,
                },
            })
            self._post("/persist_metrics", {
                "kind": "completion",
                "record": {
                    "job_name": self._job_name,
                    "success": success,
                    "goodput": goodput,
                    "worker_count": worker_count,
                },
            })
        except Exception:  # noqa: BLE001
            logger.warning("brain outcome report failed")
