"""AutoScaler: the observe -> decide -> act loop, with a ledger.

One tick = sample the SignalBus, run the policy, actuate (or record
without acting in dry-run / advisory cases), append every decision to
the ledger with its triggering snapshot. The loop can run as a daemon
thread on a cadence (the masters do this) or be ticked synchronously
(tests and the soak harness, which want deterministic pacing).

Decisions emit ``autoscaler_*`` metrics and — when tracing is armed —
one ``autoscaler.decision`` span each, carrying action/target/outcome,
so a scale action shows up in the same trace plane as the RPCs and
training steps it perturbs (§29).

The optional :class:`BrainPrior` wires the §-brain cross-job optimizer
in as a *prior*: at start the autoscaler may seed its initial
world-size target from ``/optimize`` (a SEED_WORLD decision, through
the same ledger/actuation path as everything else), and at stop it
reports the achieved goodput back to ``/persist_metrics`` so the next
job of this name starts smarter.
"""

import threading
import time
from typing import Callable, Dict, List, Optional

from dlrover_tpu.autoscaler.policy import (
    DecisionLedger,
    EVICT_STRAGGLER,
    GROW_FLEET,
    GROW_WORLD,
    RulePolicy,
    ScaleDecision,
    SEED_WORLD,
    SET_CKPT_INTERVAL,
    SHRINK_FLEET,
    SHRINK_WORLD,
)
from dlrover_tpu.autoscaler.recorder import (
    SignalRecorder,
    recorder_from_env,
)
from dlrover_tpu.autoscaler.signals import SignalBus, SignalSnapshot
from dlrover_tpu.common.log import logger


def _metrics(registry=None):
    from dlrover_tpu.observability.registry import default_registry

    reg = registry or default_registry()
    return {
        "ticks": reg.counter(
            "autoscaler_ticks_total",
            "autoscaler observe/decide/act iterations",
        ),
        "decisions": reg.counter(
            "autoscaler_decisions_total",
            "scale decisions emitted, by action",
            labelnames=("action",),
        ),
        "actuations": reg.counter(
            "autoscaler_actuations_total",
            "decisions actually actuated, by action",
            labelnames=("action",),
        ),
        "errors": reg.counter(
            "autoscaler_actuation_errors_total",
            "actuations that raised, by action",
            labelnames=("action",),
        ),
        "dry_run": reg.gauge(
            "autoscaler_dry_run",
            "1 when the loop is advisory-only (no actuations)",
        ),
        "ckpt_interval": reg.gauge(
            "autoscaler_ckpt_interval_s",
            "checkpoint cadence the autoscaler currently recommends",
        ),
        # Outcome-attribution families: realized effects backfilled
        # onto ledger entries after each decision's attribution window.
        "outcome_total": reg.counter(
            "autoscaler_decision_outcome_total",
            "decision outcomes attributed, by action and verdict",
            labelnames=("action", "verdict"),
        ),
        "outcome_goodput_delta": reg.gauge(
            "autoscaler_decision_outcome_goodput_delta",
            "goodput change over the newest attributed window, by action",
            labelnames=("action",),
        ),
        "outcome_effect": reg.gauge(
            "autoscaler_decision_outcome_effect",
            "action-specific primary effect of the newest attributed "
            "decision (score drop, backlog drain/s, net saved s/h)",
            labelnames=("action",),
        ),
        "outcome_missed": reg.counter(
            "autoscaler_decision_outcome_missed_total",
            "outcome backfills whose ledger entry was already evicted",
        ),
        "outcome_pending": reg.gauge(
            "autoscaler_decision_outcome_pending",
            "actuated decisions still inside their attribution window",
        ),
    }


class AutoScaler:
    """The resource brain's control loop (docs/DESIGN.md §30)."""

    def __init__(
        self,
        bus: SignalBus,
        policy: Optional[RulePolicy] = None,
        actuators: Optional[
            Dict[str, Callable[[ScaleDecision], None]]
        ] = None,
        interval_s: float = 5.0,
        dry_run: bool = False,
        ledger_size: int = 512,
        clock: Callable[[], float] = time.time,
        registry=None,
        brain_prior: Optional["BrainPrior"] = None,
        job_name: str = "",
        recorder: Optional[SignalRecorder] = None,
        attribution_window_s: Optional[float] = None,
    ):
        self.bus = bus
        self.policy = policy or RulePolicy()
        self._actuators = dict(actuators or {})
        self.interval_s = interval_s
        self.dry_run = dry_run
        self.ledger = DecisionLedger(ledger_size)
        self._clock = clock
        self._m = _metrics(registry)
        self._m["dry_run"].set(1.0 if dry_run else 0.0)
        self._brain = brain_prior
        self._job_name = job_name
        # Durable signal recording (§34): explicit recorder, or armed
        # from DLROVER_TPU_AUTOSCALE_RECORD the way subprocess workers
        # arm the fault plane. The policy config is recorded up front —
        # the replay identity invariant replays exactly this config.
        self.recorder = recorder if recorder is not None \
            else recorder_from_env()
        if self.recorder is not None:
            self.recorder.record_policy(self.policy.config.to_dict())
        # Outcome attribution: after an actuated decision the loop
        # watches this many seconds of SNAPSHOT time (clockless — same
        # timestamps the policy rules use) and backfills the realized
        # effect onto the ledger entry. Default: three decision
        # intervals, enough for the actuation to show in the signals.
        self.attribution_window_s = (
            attribution_window_s if attribution_window_s is not None
            else max(3.0 * interval_s, 1e-6)
        )
        self._pending_outcomes: List[ScaleDecision] = []
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._seeded = False
        self._completion_reported = False

    # ---- wiring ------------------------------------------------------------

    def bind(self, action: str, fn: Callable[[ScaleDecision], None]):
        self._actuators[action] = fn
        return self

    def bind_all(self, bindings: Dict[str, Callable[[ScaleDecision], None]]):
        self._actuators.update(bindings)
        return self

    # ---- one iteration -----------------------------------------------------

    def tick(self) -> List[ScaleDecision]:
        """Sample -> decide -> actuate/record. Synchronous drivers (the
        soak harness, tests) call this directly; the daemon thread calls
        it on the cadence."""
        self._m["ticks"].inc()
        snap = self.bus.sample()
        if self.recorder is not None:
            self.recorder.record_snapshot(snap)
        # Outcomes first: an attribution window that closes THIS tick
        # is measured against this snapshot, before any new decision
        # perturbs the signals again.
        self._resolve_outcomes(snap)
        if not self._seeded:
            self._seeded = True
            self._seed_from_brain(snap)
        decisions = self.policy.decide(snap)
        for decision in decisions:
            self._handle(decision)
        return decisions

    def _handle(self, decision: ScaleDecision):
        self._m["decisions"].inc(action=decision.action)
        actuator = self._actuators.get(decision.action)
        span = None
        from dlrover_tpu.observability import tracing

        tracer = tracing.active_tracer()
        if tracer is not None:
            span = tracer.start_span(
                "autoscaler.decision",
                attrs={
                    "action": decision.action,
                    "target": str(decision.target),
                    "dry_run": self.dry_run,
                },
            )
        if self.dry_run:
            decision.outcome = "dry_run"
        elif actuator is None:
            decision.outcome = "advisory"
        else:
            try:
                actuator(decision)
                decision.outcome = "actuated"
                self._m["actuations"].inc(action=decision.action)
            except Exception as e:  # noqa: BLE001 — a failed actuation
                # must not kill the loop; the ledger records the miss.
                decision.outcome = f"error:{type(e).__name__}: {e}"[:200]
                self._m["errors"].inc(action=decision.action)
                logger.warning(
                    "autoscaler actuation failed (%s -> %r): %s",
                    decision.action, decision.target, e,
                )
        if decision.action == "set_ckpt_interval":
            # Published even in dry-run/advisory mode: the gauge IS the
            # recommendation channel for deployments with no push path.
            self._m["ckpt_interval"].set(float(decision.target))
        self.ledger.append(decision)
        if self.recorder is not None:
            # After actuation, so the record carries the result.
            self.recorder.record_decision(decision)
        if decision.outcome == "actuated":
            self._pending_outcomes.append(decision)
            self._m["outcome_pending"].set(
                float(len(self._pending_outcomes))
            )
        if span is not None:
            span.set_attr("outcome", decision.outcome)
            span.set_attr("reason", decision.reason[:200])
            span.end(
                status="ok"
                if not decision.outcome.startswith("error") else "error"
            )
        logger.info(
            "autoscaler decision #%d: %s -> %r (%s) [%s]",
            decision.seq, decision.action, decision.target,
            decision.reason, decision.outcome,
        )

    # ---- outcome attribution (§34) -----------------------------------------

    def _resolve_outcomes(self, snap: SignalSnapshot,
                          force: bool = False):
        """Close every attribution window that has elapsed by SNAPSHOT
        time and backfill the realized effect onto the ledger entry
        (plus the recorder and the outcome metric families). ``force``
        closes everything — the stop() path, where a truncated window
        beats an unannotated decision."""
        still_open: List[ScaleDecision] = []
        for decision in self._pending_outcomes:
            window = self._window_s(decision, snap)
            if not force and window < self.attribution_window_s:
                still_open.append(decision)
                continue
            realized = self._realized_effect(decision, snap)
            if force and window < self.attribution_window_s:
                realized["window_truncated"] = True
            if not self.ledger.attach_outcome(decision.seq, realized):
                self._m["outcome_missed"].inc()
            if self.recorder is not None:
                self.recorder.record_outcome(decision.seq, realized)
            verdict = str(realized.get("verdict", "neutral"))
            self._m["outcome_total"].inc(
                action=decision.action, verdict=verdict
            )
            if realized.get("goodput_delta") is not None:
                self._m["outcome_goodput_delta"].set(
                    float(realized["goodput_delta"]),
                    action=decision.action,
                )
            if realized.get("effect") is not None:
                self._m["outcome_effect"].set(
                    float(realized["effect"]), action=decision.action
                )
        self._pending_outcomes = still_open
        self._m["outcome_pending"].set(float(len(still_open)))

    @staticmethod
    def _window_s(decision: ScaleDecision,
                  snap: SignalSnapshot) -> float:
        """Elapsed snapshot time since the decision — on the MONOTONIC
        stamp pair when both carry one (a wall-clock step mid-window
        must not close it early or hold it open), wall otherwise."""
        if decision.mono and snap.mono:
            return snap.mono - decision.mono
        return snap.ts - decision.ts

    def _realized_effect(self, decision: ScaleDecision,
                         snap: SignalSnapshot) -> Dict[str, object]:
        """Measure what actually happened across the window: the
        decision's own triggering snapshot is the before, ``snap`` the
        after. ``effect`` is the action-specific primary number the
        verdict is read from (positive = the decision helped)."""
        before = decision.signals
        after = snap.values

        def b(key, default=None):
            return before.get(key, default)

        def a(key, default=None):
            return after.get(key, default)

        window = max(self._window_s(decision, snap), 1e-9)
        out: Dict[str, object] = {
            "window_s": round(window, 6),
            "measured_at_seq": snap.seq,
        }
        gp_b, gp_a = b("perf.goodput"), a("perf.goodput")
        if gp_b is not None and gp_a is not None:
            out["goodput_before"] = round(float(gp_b), 6)
            out["goodput_after"] = round(float(gp_a), 6)
            out["goodput_delta"] = round(float(gp_a) - float(gp_b), 6)
        effect: Optional[float] = None
        if decision.action == EVICT_STRAGGLER:
            rank = decision.target

            def score_in(values):
                scores = values.get("perf.straggler_scores") or {}
                return float(scores.get(
                    rank, scores.get(str(rank), 1.0)
                ))

            sb, sa = score_in(before), score_in(after)
            out["straggler_score_before"] = round(sb, 4)
            out["straggler_score_after"] = round(sa, 4)
            flagged_after = [
                int(r) for r in (a("perf.straggler_ranks") or [])
            ]
            out["straggler_cleared"] = int(rank) not in flagged_after
            effect = sb - sa
        elif decision.action in (GROW_FLEET, SHRINK_FLEET):
            qb = float(b("fleet.queue_depth", 0.0) or 0.0)
            qa = float(a("fleet.queue_depth", 0.0) or 0.0)
            out["queue_before"] = round(qb, 2)
            out["queue_after"] = round(qa, 2)
            # Positive drain = backlog shrank over the window; a shrink
            # that makes the queue grow reads as a regression too.
            out["backlog_drain_per_s"] = round((qb - qa) / window, 4)
            ub, ua = b("fleet.slot_util"), a("fleet.slot_util")
            if ub is not None and ua is not None:
                out["util_before"] = round(float(ub), 4)
                out["util_after"] = round(float(ua), 4)
            effect = (qb - qa) / window if (qb or qa) else None
        elif decision.action in (GROW_WORLD, SHRINK_WORLD, SEED_WORLD):
            size_b = float(b("world.size", 0) or 0)
            size_a = float(a("world.size", 0) or 0)
            todo_b = b("data.todo")
            todo_a = a("data.todo")
            out["world_before"] = int(size_b)
            out["world_after"] = int(size_a)
            out["world_converged"] = (
                int(size_a) == int(decision.target)
            )
            if todo_b is not None and todo_a is not None:
                pb = float(todo_b) / max(size_b, 1.0)
                pa = float(todo_a) / max(size_a, 1.0)
                out["backlog_per_worker_before"] = round(pb, 2)
                out["backlog_per_worker_after"] = round(pa, 2)
                effect = pb - pa
        elif decision.action == SET_CKPT_INTERVAL:
            old = b("ckpt.interval_s")
            new = float(decision.target)
            mtbf = a("fault.mtbf_s", b("fault.mtbf_s"))
            save_block = float(b("ckpt.save_block_s", 0.0) or 0.0)
            if old and mtbf:
                old, mtbf = float(old), float(mtbf)
                # Young/Daly accounting, per hour of runtime: expected
                # replay per failure is interval/2, failures arrive at
                # 3600/MTBF per hour; the retune also changes the save
                # overhead (3600/interval saves × blocking cost).
                failures_per_h = 3600.0 / mtbf
                avoided = (old - new) / 2.0 * failures_per_h
                extra_saves = save_block * 3600.0 * (
                    1.0 / max(new, 1e-9) - 1.0 / max(old, 1e-9)
                )
                out["avoided_replay_s_per_hour"] = round(avoided, 4)
                out["extra_save_s_per_hour"] = round(extra_saves, 4)
                effect = avoided - extra_saves
                out["est_net_saved_s_per_hour"] = round(effect, 4)
        if effect is None and out.get("goodput_delta") is not None:
            effect = float(out["goodput_delta"])
        if effect is not None:
            out["effect"] = round(effect, 6)
            eps = 1e-6
            out["verdict"] = (
                "improved" if effect > eps
                else "regressed" if effect < -eps else "neutral"
            )
        else:
            out["verdict"] = "neutral"
        return out

    def _seed_from_brain(self, snap: SignalSnapshot):
        if self._brain is None:
            return
        suggestion = self._brain.initial_world()
        if not suggestion:
            return
        count = int(suggestion.get("worker_count", 0))
        current = snap.get("world.size")
        if count <= 0 or current is None:
            return
        # The prior's suggestion obeys the same legality as every other
        # world move: snap DOWN to the nearest legal mesh shape and
        # clamp to the configured bounds — a brain trained on another
        # cluster must not order a world this rendezvous refuses.
        cfg = self.policy.config
        if cfg.legal_world_counts:
            legal = [
                c for c in sorted(set(cfg.legal_world_counts))
                if c <= count
            ]
            if not legal:
                return
            count = legal[-1]
        if cfg.max_world > 0:
            count = min(count, cfg.max_world)
        count = max(count, cfg.min_world)
        if count == current:
            return
        self._handle(ScaleDecision(
            action=SEED_WORLD,
            target=count,
            reason=(
                f"brain prior: {suggestion.get('optimizer', '?')} "
                f"optimizer suggests {count} workers from "
                f"{suggestion.get('evidence_samples', 0)} past samples "
                f"(current {current})"
            ),
            signals=dict(snap.values),
            ts=snap.ts,
            mono=snap.mono,
        ))

    # ---- lifecycle ---------------------------------------------------------

    def start(self):
        if self._thread is not None:
            return
        self._stopped.clear()
        self._thread = threading.Thread(
            target=self._loop, name="autoscaler", daemon=True
        )
        self._thread.start()
        logger.info(
            "autoscaler loop started (interval %.1fs%s)",
            self.interval_s, ", DRY RUN" if self.dry_run else "",
        )

    def _loop(self):
        while not self._stopped.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the loop must survive
                logger.exception("autoscaler tick failed")

    def stop(self, success: bool = True):
        self._stopped.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        # A decision whose window hasn't elapsed still gets its outcome
        # measured against the last snapshot (marked truncated): an
        # annotation gap at shutdown would read as "effect unknown".
        if self._pending_outcomes:
            snap = self.bus.latest()
            if snap is not None:
                self._resolve_outcomes(snap, force=True)
        self._report_completion(success)
        if self.recorder is not None:
            self.recorder.close()

    def _report_completion(self, success: bool):
        if self._brain is None or self._completion_reported:
            return
        self._completion_reported = True
        snap = self.bus.latest()
        self._brain.report_outcome(
            goodput=float((snap.get("perf.goodput") if snap else 0.0)
                          or 0.0),
            worker_count=int((snap.get("world.size") if snap else 0)
                             or 0),
            speed=float((snap.get("perf.speed") if snap else 0.0) or 0.0),
            success=success,
        )

    # ---- dashboard surface -------------------------------------------------

    def api_state(self, last: int = 50, offset: int = 0,
                  compact: bool = False) -> Dict[str, object]:
        """The ``/api/autoscaler`` payload: live signals, the recent
        ledger, and the dry-run diff (decisions the loop took vs
        actuations it performed — in dry-run the gap IS the diff).

        ``last``/``offset`` page backward through the ledger and
        ``compact`` drops the per-decision triggering snapshots
        (``signals_truncated``) — a 512-entry ledger over a large
        world serializes to multi-MB otherwise."""
        snap = self.bus.latest()
        decision_dicts = [
            d.to_dict(include_signals=not compact)
            for d in self.ledger.entries(last=last, offset=offset)
        ]
        return {
            "enabled": True,
            "dry_run": self.dry_run,
            "interval_s": self.interval_s,
            "sources": self.bus.source_names(),
            "signals": (
                {"seq": snap.seq, "ts": snap.ts, "values": snap.values}
                if snap is not None else None
            ),
            "decisions": decision_dicts,
            "decisions_total": self.ledger.decisions_total,
            "actuations_total": self.ledger.actuations_total,
            "ledger_window": {
                "last": last,
                "offset": offset,
                "returned": len(decision_dicts),
                "compact": compact,
            },
            "outcomes": {
                "attached": self.ledger.outcomes_total,
                "missed": self.ledger.outcome_misses_total,
                "pending": len(self._pending_outcomes),
                "window_s": self.attribution_window_s,
            },
            "recording": (
                self.recorder.stats()
                if self.recorder is not None else None
            ),
            "dry_run_diff": {
                "decisions_total": self.ledger.decisions_total,
                "actuations_total": self.ledger.actuations_total,
                "suppressed": (
                    self.ledger.decisions_total
                    - self.ledger.actuations_total
                ),
            },
        }


class BrainPrior:
    """Cross-job prior over the brain service (§-brain): ask
    ``/optimize`` for a starting world size, report the achieved
    goodput back on completion. Every failure degrades to None/no-op —
    an unreachable brain must never gate a job."""

    def __init__(self, brain_addr: str, job_name: str,
                 timeout_s: float = 5.0):
        self._addr = brain_addr
        self._job_name = job_name
        self._timeout = timeout_s

    def _post(self, path: str, payload: Dict) -> Optional[Dict]:
        from dlrover_tpu.brain.client import _post

        return _post(self._addr, path, payload, timeout=self._timeout)

    def initial_world(self) -> Optional[Dict]:
        try:
            result = self._post(
                "/optimize", {"job_name": self._job_name}
            )
        except Exception:  # noqa: BLE001 — prior only, never gate
            logger.warning("brain prior unreachable; no seed")
            return None
        plan = (result or {}).get("plan")
        if not isinstance(plan, dict) or not plan.get("worker_count"):
            return None
        return plan

    def report_outcome(self, goodput: float, worker_count: int,
                       speed: float = 0.0, success: bool = True):
        """Achieved-goodput report-back: a runtime sample (so the
        optimizer's per-count evidence grows) plus a completion record."""
        try:
            self._post("/persist_metrics", {
                "kind": "runtime",
                "record": {
                    "job_name": self._job_name,
                    "speed": speed,
                    "goodput": goodput,
                    "worker_count": worker_count,
                },
            })
            self._post("/persist_metrics", {
                "kind": "completion",
                "record": {
                    "job_name": self._job_name,
                    "success": success,
                    "goodput": goodput,
                    "worker_count": worker_count,
                },
            })
        except Exception:  # noqa: BLE001
            logger.warning("brain outcome report failed")
