"""SignalBus: the autoscaler's read side of the observability plane.

One bus holds named *sources* — zero-arg callables returning a flat
``{key: value}`` dict — and :meth:`SignalBus.sample` merges them into a
:class:`SignalSnapshot` under ``"<source>.<key>"`` names. Everything the
last eight PRs built to *observe* the job plugs in here as a source:

- :func:`perf_source` — goodput fraction, running speed, global step,
  and the per-rank step-time straggler report (PerfMonitor, §29);
- :func:`data_source` — shard-queue depths (TaskManager, §24);
- :func:`fleet_source` — serving queue depth / in-flight / dispatchable
  replicas / TTFT p99 from the fleet metric families (§28);
- :func:`fault_source` — failure count + observed MTBF from a
  :class:`FaultHistory` fed by node-failure events (§26).

A source that raises does not poison the snapshot: its error lands
under ``"<source>.error"`` and the other sources still sample — the
brain must keep seeing with one eye shut.

Snapshots are immutable evidence: the policy engine copies the
triggering snapshot into every decision it emits, so the ledger never
contains an unexplained action.
"""

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional

from dlrover_tpu.common.log import logger
from dlrover_tpu.flash_ckpt.autotune import MtbfTracker


@dataclass
class SignalSnapshot:
    """One sampled view of the job. ``values`` maps flat
    ``"<source>.<key>"`` names to scalars (or small lists/dicts for
    e.g. straggler scores).

    ``ts`` is the wall clock (what the clockless policy rules consume
    and what humans read in the ledger); ``mono`` is its monotonic
    twin, stamped at the same instant — the recorder persists the pair
    and the replay reader ORDERS by ``mono``, so an NTP step mid-run
    cannot reorder a recording."""

    seq: int
    ts: float
    values: Dict[str, object] = field(default_factory=dict)
    mono: float = 0.0

    def get(self, key: str, default=None):
        return self.values.get(key, default)


class SignalBus:
    """Named signal sources merged into timestamped snapshots.

    ``clock`` is injectable (tests and the soak harness drive it); a
    bounded history ring keeps the last ``history`` snapshots for the
    dashboard's sparkline-style views.
    """

    def __init__(self, clock: Callable[[], float] = time.time,
                 history: int = 128,
                 mono_clock: Optional[Callable[[], float]] = None):
        self._lock = threading.Lock()
        self._clock = clock
        # Every snapshot stamps a (wall, mono) PAIR. With the real wall
        # clock the monotonic twin is time.monotonic; an injected fake
        # clock drives both (tests advance one clock, both stamps move
        # together — and replay ordering stays coherent).
        if mono_clock is None:
            mono_clock = time.monotonic if clock is time.time else clock
        self._mono_clock = mono_clock
        self._sources: Dict[str, Callable[[], Dict[str, object]]] = {}
        self._history: Deque[SignalSnapshot] = deque(maxlen=max(history, 1))
        self._seq = 0

    def add_source(self, name: str,
                   fn: Callable[[], Dict[str, object]]) -> "SignalBus":
        with self._lock:
            self._sources[name] = fn
        return self

    def remove_source(self, name: str):
        with self._lock:
            self._sources.pop(name, None)

    def source_names(self) -> List[str]:
        with self._lock:
            return sorted(self._sources)

    def sample(self) -> SignalSnapshot:
        with self._lock:
            sources = list(self._sources.items())
            self._seq += 1
            seq = self._seq
        values: Dict[str, object] = {}
        for name, fn in sources:
            try:
                for key, value in (fn() or {}).items():
                    values[f"{name}.{key}"] = value
            except Exception as e:  # noqa: BLE001 — one eye shut, keep seeing
                values[f"{name}.error"] = f"{type(e).__name__}: {e}"[:160]
                logger.warning("signal source %r failed: %s", name, e)
        snap = SignalSnapshot(
            seq=seq, ts=self._clock(), mono=self._mono_clock(),
            values=values,
        )
        with self._lock:
            self._history.append(snap)
        return snap

    def latest(self) -> Optional[SignalSnapshot]:
        with self._lock:
            return self._history[-1] if self._history else None

    def history(self) -> List[SignalSnapshot]:
        with self._lock:
            return list(self._history)


# ---------------------------------------------------------------------------
# Fault history: failure arrivals -> observed MTBF
# ---------------------------------------------------------------------------


class FaultHistory:
    """Observed failure arrivals, the ckpt-cadence rule's input.

    Fed by the master's node-failure path (``record_failure``) or by a
    soak harness; exposes failures_total, the age of the newest failure
    and — once ``min_failures`` arrivals are in the window — the
    observed mean time between failures (:class:`MtbfTracker`).

    The default clock is MONOTONIC (audit satellite): every consumer
    here is a time *difference* (inter-arrival gaps, failure age), and
    a wall-clock step between two failures would corrupt the observed
    MTBF the ckpt-cadence rule retunes from. Injected fake clocks
    behave as before.
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic,
                 window: int = 32, min_failures: int = 2):
        self._lock = threading.Lock()
        self._clock = clock
        self._tracker = MtbfTracker(window=window,
                                    min_failures=min_failures)
        self._total = 0
        self._last_ts: Optional[float] = None

    def record_failure(self, ts: Optional[float] = None):
        ts = self._clock() if ts is None else float(ts)
        with self._lock:
            self._total += 1
            self._last_ts = ts
            self._tracker.record_failure(ts)

    @property
    def failures_total(self) -> int:
        with self._lock:
            return self._total

    def observed_mtbf_s(self) -> Optional[float]:
        with self._lock:
            return self._tracker.observed_mtbf_s()

    def last_failure_age_s(self) -> Optional[float]:
        with self._lock:
            if self._last_ts is None:
                return None
            return max(self._clock() - self._last_ts, 0.0)


# ---------------------------------------------------------------------------
# Built-in sources over the existing observability plane
# ---------------------------------------------------------------------------


def perf_source(
    perf_monitor, threshold: Optional[float] = None
) -> Callable[[], Dict[str, object]]:
    """Goodput/speed/step + the §29 straggler report from a
    :class:`~dlrover_tpu.master.monitor.perf_monitor.PerfMonitor`.
    ``threshold`` overrides the monitor's flagging bar — pass the
    policy's ``straggler_score`` when it is BELOW the monitor default
    (the policy re-filters upward on its own, but cannot see ranks the
    monitor never reports)."""

    def fn() -> Dict[str, object]:
        report = perf_monitor.straggler_report(threshold=threshold)
        return {
            "goodput": perf_monitor.goodput(),
            "speed": perf_monitor.running_speed(),
            "global_step": perf_monitor.global_step,
            "straggler_ranks": list(report["stragglers"]),
            "straggler_scores": {
                rank: info["score"]
                for rank, info in report["ranks"].items()
            },
            "median_step_s": report["median_step_time_s"],
        }

    return fn


def data_source(task_manager) -> Callable[[], Dict[str, object]]:
    """Aggregate shard-queue depths across every dataset the
    TaskManager owns (todo = undispatched backlog, doing = leased)."""

    def fn() -> Dict[str, object]:
        todo = doing = 0
        with task_manager._lock:  # noqa: SLF001 — read-only depth view
            datasets = dict(task_manager._datasets)  # noqa: SLF001
        for mgr in datasets.values():
            todo += len(mgr.todo)
            doing += len(mgr.doing)
        return {"todo": todo, "doing": doing}

    return fn


def fleet_source(registry=None) -> Callable[[], Dict[str, object]]:
    """Serving-fleet load from the §28 metric families: router queue
    depth, in-flight attempts, breaker-admitted replica count, TTFT
    p99. Families absent (no router in this process) read as empty."""

    def fn() -> Dict[str, object]:
        from dlrover_tpu.observability.registry import default_registry

        reg = registry or default_registry()
        out: Dict[str, object] = {}
        for key, family in (
            ("queue_depth", "fleet_queue_depth"),
            ("inflight", "fleet_inflight"),
            ("replicas", "fleet_replicas_dispatchable"),
        ):
            fam = reg.get(family)
            if fam is not None:
                out[key] = fam.value()
        ttft = reg.get("fleet_ttft_seconds")
        if ttft is not None:
            p99 = ttft.quantile(0.99)
            if p99 is not None:
                out["ttft_p99_s"] = round(p99, 6)
        slots = reg.get("serving_slots_total")
        active = reg.get("serving_active_slots")
        if slots is not None and active is not None:
            total = slots.value()
            if total > 0:
                out["slot_util"] = round(active.value() / total, 4)
        return out

    return fn


def kvpool_source(engine) -> Callable[[], Dict[str, object]]:
    """Paged-KV memory pressure + SLO-class queue depths from a
    :class:`~dlrover_tpu.serving.kvpool.PagedServingEngine` (§31).
    The autoscaler's memory eye: ``blocks_free_frac`` falling while
    per-class queues grow says the fleet is BLOCK-bound, not
    replica-bound — grow capacity (or shed batch-class admission)
    before TTFT collapses."""

    def fn() -> Dict[str, object]:
        stats = engine.kv_stats()
        total = max(stats.get("total", 0), 1)
        out: Dict[str, object] = {
            "blocks_total": stats.get("total", 0),
            "blocks_free": stats.get("free", 0),
            "blocks_used": stats.get("used", 0),
            "blocks_cached": stats.get("cached", 0),
            "blocks_free_frac": round(
                stats.get("free", 0) / total, 4
            ),
            "bytes_in_use": stats.get("bytes_in_use", 0),
            "prefix_hit_rate": stats.get("prefix_hit_rate", 0.0),
        }
        for name, depth in (
            engine.scheduler.queue_depth_by_class().items()
        ):
            out[f"queue_depth.{name}"] = depth
        return out

    return fn


def control_plane_source(state_fn) -> Callable[[], Dict[str, object]]:
    """The master's own saturation (§32) as an autoscaler signal:
    ``state_fn`` is the servicer's ``control_plane_state``. A policy
    watching ``shed_level``/``inflight`` rising with world size can
    stop admitting scale-up before the control plane — not the
    accelerators — becomes the binding constraint."""

    def fn() -> Dict[str, object]:
        state = state_fn()
        overload = state.get("overload", {})
        rpc = state.get("rpc", {})
        out: Dict[str, object] = {
            "shed_level": overload.get("level", 0),
            "handler_ewma_s": overload.get("handler_ewma_s") or 0.0,
            "load_factor": overload.get("load_factor", 0.0),
            "inflight": rpc.get("inflight", 0),
            "inflight_high_water": rpc.get("inflight_high_water", 0),
            "rpcs_total": rpc.get("rpcs_total", 0),
            "cpu_seconds_total": rpc.get("cpu_seconds_total", 0.0),
        }
        for cls, count in (overload.get("shed_total") or {}).items():
            out[f"shed_total.{cls}"] = count
        return out

    return fn


def fault_source(history: FaultHistory) -> Callable[[], Dict[str, object]]:
    """Failure count + observed MTBF (omitted until measurable)."""

    def fn() -> Dict[str, object]:
        out: Dict[str, object] = {
            "failures_total": history.failures_total,
        }
        mtbf = history.observed_mtbf_s()
        if mtbf is not None:
            out["mtbf_s"] = round(mtbf, 4)
        age = history.last_failure_age_s()
        if age is not None:
            out["last_failure_age_s"] = round(age, 4)
        return out

    return fn
