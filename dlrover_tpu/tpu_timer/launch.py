"""Profiler launch wrapper: run any training command under the
zero-cooperation XLA capture listener.

Parity: reference ``xpu_timer_launch`` (py_xpu_timer's entry wrapper
around LD_PRELOAD) — the ergonomic path for scripts NOT started by the
elastic agent (the agent injects the same environment itself,
agent/training.py). The wrapped command needs no code changes: the
injection dir's sitecustomize arms the capture listener at interpreter
startup, the native daemon serves /metrics and /timeline, and captures
can be triggered any time via the trigger file
(xla_capture.request_xla_capture).

    python -m dlrover_tpu.tpu_timer.launch -- python train.py --steps 100
    python -m dlrover_tpu.tpu_timer.launch --interval 30 --window 0.5 \
        -- python -m mypkg.train

Everything after ``--`` is exec'd verbatim (this process is replaced:
signals, exit code, and the controlling terminal all pass through).
"""

import argparse
import os
import sys


def build_env(
    interval_s: float = 60.0,
    window_s: float = 1.0,
    env: dict = None,
) -> dict:
    """The environment the agent injects, reproduced for standalone
    runs: capture flag + cadence, injection dir + package root on
    PYTHONPATH (shared with tests so the two paths cannot diverge)."""
    env = dict(os.environ if env is None else env)
    pkg_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    inject_dir = os.path.join(
        pkg_root, "dlrover_tpu", "tpu_timer", "_inject"
    )
    parts = [inject_dir, pkg_root]
    existing = env.get("PYTHONPATH", "")
    if existing:
        parts.append(existing)
    env["PYTHONPATH"] = os.pathsep.join(parts)
    env["DLROVER_TPU_TIMER_XLA"] = "1"
    env["DLROVER_TPU_TIMER_XLA_INTERVAL"] = str(interval_s)
    env["DLROVER_TPU_TIMER_XLA_WINDOW"] = str(window_s)
    return env


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--" in argv:
        split = argv.index("--")
        own, cmd = argv[:split], argv[split + 1:]
    else:
        own, cmd = [], argv
    ap = argparse.ArgumentParser(
        description="run a command under the XLA capture listener"
    )
    ap.add_argument("--interval", type=float, default=60.0,
                    help="periodic capture interval, seconds")
    ap.add_argument("--window", type=float, default=1.0,
                    help="capture window length, seconds")
    ns = ap.parse_args(own)
    if not cmd:
        ap.error("no command given (usage: ... -- python train.py)")
    env = build_env(ns.interval, ns.window)
    os.execvpe(cmd[0], cmd, env)  # no return


if __name__ == "__main__":
    raise SystemExit(main())
