"""Python-side tracing: GC pauses, arbitrary functions, stack dumps.

Parity: reference xpu_timer/python/py_tracing_*.cc (dynamic injection
tracing of Python functions — GC, dataloader) and the hang→stack-dump
daemon flow (server/hosting_service). CPython exposes what the reference
needed dlopen tricks for: ``gc.callbacks`` for collector pauses,
decorators for targeted functions, and ``faulthandler`` for all-thread
stack dumps on signal — which is how a wedged worker gets post-mortemed:
the agent sends SIGUSR2 before restarting it, and the traceback of every
thread (including the one stuck in a collective) lands in the worker
log.
"""

import faulthandler
import functools
import gc
import signal
import sys
import time
from typing import Optional

from dlrover_tpu.common.log import logger
from dlrover_tpu.tpu_timer.bridge import SpanKind, active_timer

_gc_start_ns = 0
_gc_installed = False


def _gc_callback(phase, info):
    global _gc_start_ns
    timer = active_timer()
    if timer is None:
        return
    if phase == "start":
        _gc_start_ns = timer.now_ns()
    elif phase == "stop" and _gc_start_ns:
        timer.record(
            f"py_gc_gen{info.get('generation', '?')}",
            SpanKind.CUSTOM,
            _gc_start_ns,
            timer.now_ns() - _gc_start_ns,
        )
        _gc_start_ns = 0


def trace_gc():
    """Record every collector pause as a span (GC stalls show up in the
    step-time tail; the reference traces them for the same reason)."""
    global _gc_installed
    if not _gc_installed:
        gc.callbacks.append(_gc_callback)
        _gc_installed = True


def untrace_gc():
    global _gc_installed
    if _gc_callback in gc.callbacks:
        gc.callbacks.remove(_gc_callback)
    _gc_installed = False


def traced(name: Optional[str] = None, kind: int = SpanKind.DATA):
    """Decorator: record every call of ``fn`` as a span (dataloader
    fetches, tokenization, host-side preprocessing...)."""

    def wrap(fn):
        span_name = name or f"py_{fn.__qualname__}"

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            timer = active_timer()
            if timer is None:
                # Profiler not running: zero-cost pass-through (never
                # trigger the native build from a hot data path).
                return fn(*args, **kwargs)
            start = timer.now_ns()
            try:
                return fn(*args, **kwargs)
            finally:
                timer.record(
                    span_name, kind, start, timer.now_ns() - start
                )

        return inner

    return wrap


# ---------------------------------------------------------------------------
# Stack dumps (hang post-mortem)
# ---------------------------------------------------------------------------

STACK_DUMP_SIGNAL = signal.SIGUSR2


def install_stack_dump_handler(fileobj=None):
    """Dump all-thread tracebacks on SIGUSR2 (to stderr by default —
    which the agent redirects into the worker log)."""
    try:
        faulthandler.register(
            STACK_DUMP_SIGNAL, file=fileobj or sys.stderr, all_threads=True
        )
    except (AttributeError, ValueError, OSError):
        logger.warning("stack dump handler not installed", exc_info=True)


def dump_stacks(fileobj=None):
    """Immediate all-thread dump (in-process watchdogs).

    CPython's faulthandler silently caps the dump at 100 threads — in
    a process with many daemon threads (servers, agents, pools) the
    CALLING thread can be among the omitted, which defeats the usual
    "where am I stuck" question. Emit the current stack explicitly
    first in faulthandler-compatible format (the stacks analysis tool
    parses it) once the count EXCEEDS the cap — below it every thread
    is included and a copy would double-count the caller in the stack
    histograms. (Threads spawned between the check and the dump can
    still race past the cap; the guard trades that sliver for
    duplicate-free histograms in the common case.)"""
    f = fileobj or sys.stderr
    if len(sys._current_frames()) > 100:
        # Only when the cap actually binds: below it faulthandler
        # includes every thread and an explicit copy would double-count
        # the caller in the stack histograms. Over the cap, a possible
        # duplicate beats a possible omission. Header matches the
        # analysis tool's thread regex (hex id required) so the
        # explicit stack is parsed, not dropped.
        import threading

        f.write(
            f"Current thread 0x{threading.get_ident():x} "
            "(most recent call first):\n"
        )
        frame = sys._getframe(1)
        while frame is not None:
            code = frame.f_code
            f.write(
                f'  File "{code.co_filename}", line {frame.f_lineno} '
                f"in {code.co_name}\n"
            )
            frame = frame.f_back
        f.write("\n")
        f.flush()
    faulthandler.dump_traceback(file=f, all_threads=True)
