"""Out-of-process NATIVE stack capture for hung workers.

Parity: reference xpu_timer's per-node daemon orchestrates gdb/py-spy
dumps of arbitrary training processes
(xpu_timer/server/hosting_service_server_client.cc; RPC surface
xpu_timer/protos/hosting_service.proto:14-250). Neither tool ships in
this image, so the capability is native: ``stack_sampler`` (built from
native/tpu_timer/stack_sampler.cc on first use, like libtpu_timer.so)
ptrace-attaches to every thread of the target and unwinds its
user-space stack with libunwind-ptrace. That shows the C/C++ frames a
faulthandler dump cannot: on TPU the common hang is a worker wedged
inside libtpu/XLA, where the Python dump is one opaque line and the
diagnosis lives in the native frames (VERDICT r4 #4).

The agent calls :func:`sample_native_stacks` on a worker it is about to
post-mortem-restart (agent/training._stop_workers) and appends the
output to the worker's log, right next to the SIGUSR2 faulthandler
dump; ``analysis.py stacks`` folds both into one histogram.
"""

import fcntl
import os
import re
import subprocess
import tempfile
import time
from typing import List, Optional

from dlrover_tpu.common.log import logger

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "native",
    "tpu_timer",
)
_SAMPLER_PATH = os.path.join(_NATIVE_DIR, "stack_sampler")


def ensure_built(timeout: float = 120.0) -> str:
    """Build stack_sampler on first use (one g++ invocation), with the
    same cross-process build lock as the timer runtime.

    Everything here is BOUNDED: this runs on the agent's hang-recovery
    path (_stop_workers post-mortem), where an unbounded flock or make
    would let the hang diagnostic hang the recovery itself. A lock held
    past the deadline or a wedged compiler raises (TimeoutError /
    CalledProcessError) and the caller degrades to the Python-only
    dump."""
    if os.path.exists(_SAMPLER_PATH):
        return _SAMPLER_PATH
    lock_path = os.path.join(
        tempfile.gettempdir(), "dlrover_tpu_timer_build.lock"
    )
    deadline = time.time() + timeout
    with open(lock_path, "w") as lock:
        while True:
            try:
                fcntl.flock(lock, fcntl.LOCK_EX | fcntl.LOCK_NB)
                break
            except OSError:
                if time.time() > deadline:
                    raise TimeoutError(
                        f"build lock {lock_path} held past {timeout}s"
                    )
                time.sleep(0.2)
        try:
            if not os.path.exists(_SAMPLER_PATH):
                logger.info("building stack_sampler (first use)")
                subprocess.run(
                    ["make", "-C", _NATIVE_DIR, "stack_sampler"],
                    check=True,
                    capture_output=True,
                    timeout=max(deadline - time.time(), 10.0),
                )
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)
    return _SAMPLER_PATH


def sample_native_stacks(
    pid: int, max_frames: int = 64, timeout: float = 20.0
) -> Optional[str]:
    """Native stacks of every thread of ``pid``, or None.

    The target is attached/walked/detached per thread (a few ms stop
    each — the py-spy disturbance model). Returns the sampler's text
    ("Native thread <tid> (most recent call first): / #N 0x... sym+off"
    blocks), or None when the tool can't run (no ptrace permission,
    target gone, build failure) — hang handling must degrade to the
    Python-only dump, never raise."""
    try:
        tool = ensure_built()
    except (
        OSError,
        subprocess.CalledProcessError,
        subprocess.TimeoutExpired,
    ) as e:
        logger.warning("stack_sampler unavailable: %s", e)
        return None
    try:
        out = subprocess.run(
            [tool, str(pid), str(max_frames)],
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        logger.warning("stack_sampler failed for pid %s: %s", pid, e)
        return None
    if out.returncode != 0 or not out.stdout.strip():
        logger.warning(
            "stack_sampler pid %s rc=%s stderr=%s",
            pid, out.returncode, out.stderr[-400:],
        )
        return None
    return out.stdout


_NATIVE_THREAD_RE = re.compile(r"^Native thread (\d+)")
_NATIVE_FRAME_RE = re.compile(
    r"^\s+#\d+ 0x[0-9a-f]+ (?P<sym>.+?)(\+0x[0-9a-f]+)?$"
)


def parse_native_dumps(text: str) -> List[List[str]]:
    """Per-thread native stacks (outermost-first symbol lists) from
    sampler output embedded in log text — the native twin of
    ``analysis.parse_faulthandler_dumps``."""
    stacks: List[List[str]] = []
    current: List[str] = []
    in_stack = False
    for line in text.splitlines():
        if _NATIVE_THREAD_RE.match(line.strip()):
            if current:
                stacks.append(current)
            current = []
            in_stack = True
            continue
        m = _NATIVE_FRAME_RE.match(line)
        if m and in_stack:
            current.append(m.group("sym"))
        elif in_stack and not line.strip():
            if current:
                stacks.append(current)
                current = []
            in_stack = False
    if current:
        stacks.append(current)
    # Sampler prints innermost-first; flamegraph wants outermost-first.
    return [list(reversed(s)) for s in stacks]


def main(argv=None) -> int:
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        description="native stack capture (ptrace + libunwind)"
    )
    ap.add_argument("pid", type=int)
    ap.add_argument("--max-frames", type=int, default=64)
    ns = ap.parse_args(argv)
    text = sample_native_stacks(ns.pid, max_frames=ns.max_frames)
    if text is None:
        print("native stack capture failed", file=sys.stderr)
        return 1
    sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
