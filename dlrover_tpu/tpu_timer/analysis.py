"""Profiler analysis tooling.

Parity: reference xpu_timer/py_xpu_timer (~2.1k LoC: perfetto timeline
generation, matmul analysis, stack viewer) — the TPU-native equivalents
over this repo's artifacts:

- ``timeline``: aggregate a (chrome-format) native timeline into
  per-name statistics, kernel/collective shares, and device-busy
  fraction inside xla capture windows.
- ``stacks``: the stack viewer — parse faulthandler all-thread dumps
  out of worker logs (SIGUSR2 post-mortems) and fold them into
  collapsed-stack counts (flamegraph input) plus a top-frame histogram
  that answers "where were the workers stuck".
- ``matmul`` (python -m dlrover_tpu.tpu_timer.analysis matmul): sweep
  MXU-shaped GEMMs on the local device and report achieved TFLOP/s and
  efficiency vs peak — the host-qualification table the reference's
  matmul analysis produces for GPUs.
- ``merge``: cross-rank timeline merge (reference
  py_xpu_timer/parse_perfetto.py + gen_trace_timeline.py) — align N
  ranks' chrome traces onto one clock (offsets estimated from matched
  collective END times, which a blocking collective makes simultaneous
  across ranks up to skew), emit a single multi-process trace, and
  flag the STRAGGLER rank per collective (the rank arriving last is
  the one everyone else waited for).

Usage::

    python -m dlrover_tpu.tpu_timer.analysis timeline trace.json
    python -m dlrover_tpu.tpu_timer.analysis stacks worker-*.log
    python -m dlrover_tpu.tpu_timer.analysis matmul --sizes 2048,4096
    python -m dlrover_tpu.tpu_timer.analysis merge rank0.json rank1.json \
        --out merged.json
"""

import argparse
import collections
import json
import re
import sys
import time
from typing import Dict, Iterable, List, Tuple

# ---------------------------------------------------------------------------
# Timeline analysis
# ---------------------------------------------------------------------------


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[idx]


def summarize_timeline(trace: dict) -> dict:
    """Aggregate a chrome-trace dict into per-name and per-category
    statistics."""
    events = trace.get("traceEvents", [])
    by_name: Dict[str, List[Tuple[float, float]]] = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        name = str(e.get("name", ""))
        by_name.setdefault(name, []).append(
            (float(e.get("ts", 0.0)), float(e.get("dur", 0.0)))
        )

    names = {}
    for name, spans in by_name.items():
        durs = sorted(d for _, d in spans)
        names[name] = {
            "count": len(spans),
            "total_us": round(sum(durs), 1),
            "avg_us": round(sum(durs) / len(durs), 1),
            "p50_us": round(_percentile(durs, 0.5), 1),
            "p99_us": round(_percentile(durs, 0.99), 1),
        }

    def cat_total(pred) -> float:
        return sum(
            s["total_us"] for n, s in names.items() if pred(n)
        )

    kernels_us = cat_total(lambda n: n.startswith("xla/"))
    coll_re = re.compile(
        r"all[-_]?reduce|all[-_]?gather|reduce[-_]?scatter|ppermute"
        r"|all[-_]?to[-_]?all|collective",
        re.IGNORECASE,
    )
    collectives_us = cat_total(
        lambda n: n.startswith("xla/") and coll_re.search(n)
    )

    # Device-busy fraction inside xla capture windows: the union of
    # device-kernel intervals over the union of capture spans.
    windows = [
        (ts, ts + d) for ts, d in by_name.get("xla_capture", [])
    ]
    busy = 0.0
    window_total = sum(e - s for s, e in windows)
    if windows:
        kernel_spans = sorted(
            (ts, ts + d)
            for n, spans in by_name.items()
            if n.startswith("xla/")
            for ts, d in spans
        )
        merged: List[List[float]] = []
        for s, e in kernel_spans:
            if merged and s <= merged[-1][1]:
                merged[-1][1] = max(merged[-1][1], e)
            else:
                merged.append([s, e])
        busy = sum(e - s for s, e in merged)

    return {
        "names": dict(
            sorted(
                names.items(),
                key=lambda kv: -kv[1]["total_us"],
            )
        ),
        "device_kernel_us": round(kernels_us, 1),
        "collective_us": round(collectives_us, 1),
        "collective_share": round(
            collectives_us / kernels_us, 4
        ) if kernels_us else 0.0,
        "capture_window_us": round(window_total, 1),
        "device_busy_fraction": round(busy / window_total, 4)
        if window_total
        else 0.0,
    }


def diff_timelines(
    base: dict, other: dict, min_total_us: float = 1.0
) -> dict:
    """Run-over-run trace diff: per-name total/avg deltas between two
    timeline JSONs, worst regressions first.

    Parity: reference py_xpu_timer's timeline tooling covers per-run
    analysis; cross-RUN comparison ("the step got 8ms slower — which
    op?") was the remaining breadth gap (VERDICT r4 Missing #2). Names
    present in only one run are reported with the other side at 0 —
    exactly the "op appeared/disappeared after my change" signal a
    kernel A/B needs."""
    sa, sb = summarize_timeline(base), summarize_timeline(other)
    rows = []
    for name in set(sa["names"]) | set(sb["names"]):
        a = sa["names"].get(name, {})
        b = sb["names"].get(name, {})
        ta = a.get("total_us", 0.0)
        tb = b.get("total_us", 0.0)
        if max(ta, tb) < min_total_us:
            continue
        rows.append({
            "name": name,
            "base_total_us": ta,
            "other_total_us": tb,
            "delta_us": round(tb - ta, 1),
            "ratio": round(tb / ta, 3) if ta else None,
            "base_avg_us": a.get("avg_us", 0.0),
            "other_avg_us": b.get("avg_us", 0.0),
        })
    rows.sort(key=lambda r: -abs(r["delta_us"]))
    return {
        "base_device_kernel_us": sa["device_kernel_us"],
        "other_device_kernel_us": sb["device_kernel_us"],
        "device_kernel_delta_us": round(
            sb["device_kernel_us"] - sa["device_kernel_us"], 1
        ),
        "base_collective_share": sa["collective_share"],
        "other_collective_share": sb["collective_share"],
        "rows": rows,
    }


# ---------------------------------------------------------------------------
# Stack viewer (faulthandler dumps in worker logs)
# ---------------------------------------------------------------------------

_THREAD_RE = re.compile(r"^(Current thread|Thread) (0x[0-9a-f]+)")
_FRAME_RE = re.compile(r'^\s+File "(?P<file>[^"]+)", line (?P<line>\d+) in (?P<fn>.+)$')


def parse_faulthandler_dumps(text: str) -> List[List[str]]:
    """Extract per-thread stacks (outermost-first frame lists) from log
    text containing faulthandler all-thread dumps."""
    stacks: List[List[str]] = []
    current: List[str] = []
    in_stack = False
    for line in text.splitlines():
        if _THREAD_RE.match(line.strip()):
            if current:
                stacks.append(current)
            current = []
            in_stack = True
            continue
        m = _FRAME_RE.match(line)
        if m and in_stack:
            frame = f"{m.group('fn')} ({m.group('file').rsplit('/', 1)[-1]}:{m.group('line')})"  # noqa: E501
            current.append(frame)
        elif in_stack and line.strip() == "":
            if current:
                stacks.append(current)
                current = []
            in_stack = False
    if current:
        stacks.append(current)
    # faulthandler prints innermost-first ("most recent call first");
    # flamegraph convention is outermost-first.
    return [list(reversed(s)) for s in stacks]


def fold_stacks(stacks: Iterable[List[str]]) -> Dict[str, int]:
    """Collapsed-stack counts: 'outer;...;inner' -> occurrences
    (flamegraph.pl / speedscope input)."""
    folded: Dict[str, int] = collections.Counter()
    for stack in stacks:
        if stack:
            folded[";".join(stack)] += 1
    return dict(folded)


def top_frames(stacks: Iterable[List[str]], k: int = 10) -> List[Tuple[str, int]]:
    """Histogram of innermost frames: where the threads actually were."""
    counter: collections.Counter = collections.Counter()
    for stack in stacks:
        if stack:
            counter[stack[-1]] += 1
    return counter.most_common(k)


# ---------------------------------------------------------------------------
# Cross-rank timeline merge + straggler attribution
# ---------------------------------------------------------------------------

_COLL_RE = re.compile(
    r"all[-_]?reduce|all[-_]?gather|reduce[-_]?scatter|ppermute"
    r"|all[-_]?to[-_]?all|collective",
    re.IGNORECASE,
)


def _collective_spans(trace: dict) -> Dict[str, List[Tuple[float, float]]]:
    """name -> [(start, end)] in ts order, for collective-looking device
    events."""
    out: Dict[str, List[Tuple[float, float]]] = {}
    for e in trace.get("traceEvents", []):
        if e.get("ph") != "X":
            continue
        name = str(e.get("name", ""))
        if name.startswith("xla/") and _COLL_RE.search(name):
            ts = float(e.get("ts", 0.0))
            out.setdefault(name, []).append((ts, ts + float(e.get("dur", 0.0))))
    for spans in out.values():
        spans.sort()
    return out


def estimate_clock_offsets(
    traces: Dict[int, dict]
) -> Dict[int, float]:
    """Per-rank clock offset (us, subtract to land on rank-0's clock).

    A blocking collective ENDS on every participant at (nearly) the
    same wall instant — the k-th instance of a given collective name is
    the same logical operation on every rank, so the median difference
    of its end times vs rank 0 estimates the clock skew. Host clocks in
    one job are NTP-close but not trace-identical; without this the
    merged timeline misattributes waits to whichever host booted last.
    """
    ranks = sorted(traces)
    base = _collective_spans(traces[ranks[0]])
    offsets = {ranks[0]: 0.0}
    for r in ranks[1:]:
        mine = _collective_spans(traces[r])
        diffs: List[float] = []
        for name, spans0 in base.items():
            spans_r = mine.get(name, [])
            for k in range(min(len(spans0), len(spans_r))):
                diffs.append(spans_r[k][1] - spans0[k][1])
        diffs.sort()
        offsets[r] = diffs[len(diffs) // 2] if diffs else 0.0
    return offsets


def merge_rank_traces(traces: Dict[int, dict]) -> Tuple[dict, dict]:
    """(merged chrome trace, straggler report) from per-rank traces.

    The merged trace keeps every event with pid=rank (plus
    process_name metadata rows), all on rank-0's clock. The report
    gives, per collective name: mean/max arrival spread (latest start −
    earliest start ≈ time the fast ranks wasted waiting) and how often
    each rank was the last to arrive."""
    offsets = estimate_clock_offsets(traces)
    merged_events: List[dict] = []
    for r, trace in sorted(traces.items()):
        merged_events.append({
            "ph": "M", "pid": r, "name": "process_name",
            "args": {"name": f"rank {r}"},
        })
        off = offsets[r]
        for e in trace.get("traceEvents", []):
            e2 = dict(e)
            e2["pid"] = r
            if "ts" in e2:
                e2["ts"] = float(e2["ts"]) - off
            merged_events.append(e2)

    # Straggler attribution over matched collective instances.
    spans = {
        r: _collective_spans(t) for r, t in traces.items()
    }
    report: Dict[str, dict] = {}
    names = set().union(*(s.keys() for s in spans.values())) if spans else set()
    for name in sorted(names):
        per_rank = {
            r: s.get(name, []) for r, s in spans.items()
        }
        n_inst = min((len(v) for v in per_rank.values()), default=0)
        if n_inst == 0 or len(per_rank) < 2:
            continue
        spreads: List[float] = []
        last_count: collections.Counter = collections.Counter()
        for k in range(n_inst):
            starts = {
                r: per_rank[r][k][0] - offsets[r] for r in per_rank
            }
            latest = max(starts, key=starts.get)
            spreads.append(starts[latest] - min(starts.values()))
            last_count[latest] += 1
        straggler, times = last_count.most_common(1)[0]
        report[name] = {
            "instances": n_inst,
            "mean_wait_us": round(sum(spreads) / len(spreads), 1),
            "max_wait_us": round(max(spreads), 1),
            "straggler_rank": straggler,
            "straggler_share": round(times / n_inst, 3),
            "last_arrival_counts": dict(last_count),
        }
    return (
        {"traceEvents": merged_events, "clock_offsets_us": {
            str(r): round(v, 1) for r, v in offsets.items()
        }},
        report,
    )


# ---------------------------------------------------------------------------
# Matmul analysis
# ---------------------------------------------------------------------------

_PEAK_BF16 = {
    "TPU v5 lite": 197e12,
    "TPU v5": 459e12,
    "TPU v4": 275e12,
    "TPU v6": 918e12,
}


def matmul_analysis(sizes: List[int], iters: int = 100) -> List[dict]:
    """Achieved bf16 GEMM TFLOP/s per size vs device peak. Timing uses
    a carry-chained in-jit scan (hoisting-proof) with a host fetch as
    the barrier, so it is valid even over high-RTT device transports."""
    import jax
    import jax.numpy as jnp

    kind = jax.devices()[0].device_kind
    peak = next(
        (
            v
            for k, v in sorted(
                _PEAK_BF16.items(), key=lambda kv: -len(kv[0])
            )
            if kind.startswith(k)
        ),
        0.0,
    )
    rows = []
    for n in sizes:
        a = jax.random.normal(jax.random.key(0), (n, n), jnp.bfloat16)

        def scan_fn(a):
            def body(carry, _):
                out = carry @ carry
                s = jnp.sum(out.astype(jnp.float32))
                carry = carry + (s * 1e-30).astype(carry.dtype)
                return carry, s

            _, outs = jax.lax.scan(body, a, None, length=iters)
            return outs[-1]

        f = jax.jit(scan_fn)
        float(f(a))  # compile
        t0 = time.time()
        float(f(a))
        total = time.time() - t0
        per_iter = total / iters
        tflops = 2 * n**3 / per_iter / 1e12
        rows.append(
            {
                "size": n,
                "ms": round(per_iter * 1e3, 3),
                "tflops": round(tflops, 3),
                "efficiency_pct": round(100 * tflops * 1e12 / peak, 1)
                if peak
                else None,
                "device": kind,
            }
        )
    return rows


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="tpu_timer analysis")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_tl = sub.add_parser("timeline", help="aggregate a timeline JSON")
    p_tl.add_argument("trace")
    p_tl.add_argument("--top", type=int, default=15)

    p_st = sub.add_parser("stacks", help="stack viewer over worker logs")
    p_st.add_argument("logs", nargs="+")
    p_st.add_argument("--folded", action="store_true",
                      help="print collapsed stacks (flamegraph input)")

    p_mm = sub.add_parser("matmul", help="MXU GEMM efficiency sweep")
    p_mm.add_argument("--sizes", default="1024,2048,4096,8192")
    p_mm.add_argument("--iters", type=int, default=100)

    p_mg = sub.add_parser(
        "merge", help="merge N ranks' traces; flag stragglers"
    )
    p_mg.add_argument("traces", nargs="+",
                      help="per-rank trace JSONs, rank = position")
    p_mg.add_argument("--out", default="merged_trace.json")

    p_df = sub.add_parser(
        "diff", help="run-over-run timeline diff (regressions first)"
    )
    p_df.add_argument("base")
    p_df.add_argument("other")
    p_df.add_argument("--top", type=int, default=15)

    args = parser.parse_args(argv)

    if args.cmd == "timeline":
        with open(args.trace) as f:
            report = summarize_timeline(json.load(f))
        top = dict(list(report["names"].items())[: args.top])
        report["names"] = top
        print(json.dumps(report, indent=2))
        return 0

    if args.cmd == "stacks":
        from dlrover_tpu.tpu_timer.native_stack import parse_native_dumps

        stacks: List[List[str]] = []
        for path in args.logs:
            with open(path, errors="replace") as f:
                text = f.read()
            stacks.extend(parse_faulthandler_dumps(text))
            # Native stacks the agent captured out-of-process (ptrace +
            # libunwind) live in the same logs; fold them into the same
            # histogram so a libtpu/XLA hang names its C++ frame.
            stacks.extend(parse_native_dumps(text))
        if not stacks:
            print("no stack dumps found", file=sys.stderr)
            return 1
        if args.folded:
            for stack, count in sorted(fold_stacks(stacks).items()):
                print(f"{stack} {count}")
        else:
            print(f"{len(stacks)} thread stacks")
            for frame, count in top_frames(stacks):
                print(f"{count:6d}  {frame}")
        return 0

    if args.cmd == "matmul":
        sizes = [int(s) for s in args.sizes.split(",") if s]
        for row in matmul_analysis(sizes, args.iters):
            print(json.dumps(row))
        return 0

    if args.cmd == "merge":
        traces = {}
        for rank, path in enumerate(args.traces):
            with open(path) as f:
                traces[rank] = json.load(f)
        merged, report = merge_rank_traces(traces)
        with open(args.out, "w") as f:
            json.dump(merged, f)
        print(f"merged {len(traces)} ranks -> {args.out} "
              f"(offsets us: {merged['clock_offsets_us']})")
        for name, row in sorted(
            report.items(), key=lambda kv: -kv[1]["mean_wait_us"]
        ):
            print(
                f"  {name}: straggler rank {row['straggler_rank']} "
                f"({row['straggler_share']:.0%} of "
                f"{row['instances']} instances), mean wait "
                f"{row['mean_wait_us']}us max {row['max_wait_us']}us"
            )
        return 0

    if args.cmd == "diff":
        with open(args.base) as f:
            base = json.load(f)
        with open(args.other) as f:
            other = json.load(f)
        report = diff_timelines(base, other)
        report["rows"] = report["rows"][: args.top]
        print(json.dumps(report, indent=2))
        return 0
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
