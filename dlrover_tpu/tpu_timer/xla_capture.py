"""XLA/PJRT-level trace acquisition feeding the native tpu_timer ring.

Parity: reference xpu_timer/nvidia/hook.cc:53-580 (dlsym interception of
CUDA kernel launches + NCCL collectives) + common/manager.h:106-195
(event poller). On TPU there is nothing to dlsym — the runtime's own
profiler (PJRT/libtpu, surfaced as ``jax.profiler``) is the kernel-level
source of truth. This listener periodically (or on agent request via a
trigger file) captures a short device trace, parses the chrome-trace the
runtime emits, and records every device-plane event — named XLA
executables, fusions, collectives — into the native ring: per-kernel
visibility with NO cooperation from the training script beyond runtime
init (``_maybe_start_tpu_timer``), the same contract as LD_PRELOADing
the reference's hook library.

Sub-step hang detection rides the existing native watchdog: each
capture runs inside a native ``xla_capture`` span, and a capture that
stalls — profiler teardown blocks behind a wedged device/collective —
exceeds the hang timeout so the C++ watchdog fires even though Python
never returned from the step.
"""

import glob
import gzip
import json
import os
import re
import shutil
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

from dlrover_tpu.common.constants import NodeEnv
from dlrover_tpu.common.log import logger
from dlrover_tpu.tpu_timer.bridge import SpanKind, get_timer

# Runtime-level host events worth recording even off-TPU (PJRT client,
# XLA modules/thunks); device-plane events are always recorded.
_RUNTIME_NAME_RE = re.compile(
    r"jit_|PjRt|Xla|XLA|thunk|fusion|convolution|dot_general"
    r"|all[-_]?reduce|all[-_]?gather|reduce[-_]?scatter"
    r"|all[-_]?to[-_]?all|collective|ppermute",
    re.IGNORECASE,
)
_COLLECTIVE_RE = re.compile(
    r"all[-_]?reduce|all[-_]?gather|reduce[-_]?scatter"
    r"|all[-_]?to[-_]?all|collective|ppermute",
    re.IGNORECASE,
)


def trigger_path(local_rank: int) -> str:
    """Touch this file to request an immediate capture (the agent-side
    knob; no signal or RPC into the training process needed)."""
    job = os.getenv(NodeEnv.JOB_NAME, "job")
    return os.path.join(
        tempfile.gettempdir(),
        f"dlrover_tpu_timer_{job}_{local_rank}.capture",
    )


def request_xla_capture(local_rank: int = 0):
    with open(trigger_path(local_rank), "w") as f:
        f.write(str(time.time()))


def parse_chrome_trace(path: str) -> List[Tuple[str, bool, float, float]]:
    """(name, is_device_plane, start_us, dur_us) for complete events."""
    with gzip.open(path, "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", [])
    plane: Dict[int, str] = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            plane[e["pid"]] = e.get("args", {}).get("name", "")
    out = []
    for e in events:
        if e.get("ph") != "X":
            continue
        name = str(e.get("name", ""))
        if name.startswith("$"):
            continue  # python frames: the py_tracing layer covers those
        is_device = plane.get(e.get("pid"), "").startswith("/device:")
        out.append(
            (name, is_device, float(e.get("ts", 0)), float(e.get("dur", 0)))
        )
    return out


def _capture_trace(parse_fn, capture_s: float):
    """Open a trace session for ``capture_s``, close it ON ANY EXIT (a
    leaked active session breaks every later capture in the process),
    and apply ``parse_fn`` to the newest trace file. The single home of
    the session/teardown invariant for both capture entry points."""
    import jax

    tmpdir = tempfile.mkdtemp(prefix="dlrover_tpu_xla_cap_")
    try:
        jax.profiler.start_trace(tmpdir)
        try:
            time.sleep(capture_s)
        finally:
            jax.profiler.stop_trace()
        traces = sorted(
            glob.glob(
                os.path.join(
                    tmpdir, "plugins", "profile", "*", "*.trace.json.gz"
                )
            )
        )
        if not traces:
            return []
        return parse_fn(traces[-1])
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)


def capture_device_events(
    capture_s: float = 1.0, keep_host_runtime: bool = True
) -> List[Tuple[str, bool, float, float]]:
    """Capture a trace window and return its runtime/device events.

    The profiler samples whatever the process is executing on device
    during the window — this thread only opens/closes the session.
    """

    def parse(path):
        events = parse_chrome_trace(path)
        if keep_host_runtime:
            return [
                ev
                for ev in events
                if ev[1] or _RUNTIME_NAME_RE.search(ev[0])
            ]
        return [ev for ev in events if ev[1]]

    return _capture_trace(parse, capture_s)


def parse_op_profile(path: str) -> List[Dict]:
    """Per-op device events WITH compiler metadata, for attribution.

    Each "XLA Ops"-plane complete event becomes
    ``{name, scope (tf_op: the jax name-stack path), category
    (hlo_category), dur_us, flops (model_flops), bytes
    (bytes_accessed)}``. The jax name stack is what ``jax.named_scope``
    blocks in the model land in — forward ops carry e.g.
    ``jit(step)/attn/dot_general`` and their backward transposes keep
    the same scope token, so substring bucketing attributes fwd+bwd
    together (bucket_by_scope)."""
    with gzip.open(path, "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", [])
    plane: Dict[int, str] = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            plane[e["pid"]] = e.get("args", {}).get("name", "")
    out = []
    for e in events:
        if e.get("ph") != "X":
            continue
        if not plane.get(e.get("pid"), "").startswith("/device:"):
            continue
        args = e.get("args", {}) or {}
        if "tf_op" not in args and "hlo_category" not in args:
            continue  # module-level envelope events, copies to host, …
        if str(args.get("hlo_category", "")) in (
            "while", "conditional", "call", "fusion envelope",
        ):
            # Control-flow ENVELOPE spans contain their body ops, which
            # the trace also reports individually — keeping both would
            # double-count every scan body (measured: the grad-accum +
            # layer-scan whiles alone are ~62% of raw span time).
            continue
        out.append({
            "name": str(e.get("name", ""))[:120],
            "scope": str(args.get("tf_op", "")),
            "category": str(args.get("hlo_category", "")),
            "dur_us": float(e.get("dur", 0)),
            "flops": float(args.get("model_flops", 0) or 0),
            "bytes": float(args.get("bytes_accessed", 0) or 0),
        })
    return out


def capture_op_profile(capture_s: float = 1.0) -> List[Dict]:
    """Capture a trace window and return the per-op profile
    (parse_op_profile rows) of whatever ran on device during it."""
    return _capture_trace(parse_op_profile, capture_s)


def bucket_by_scope(
    ops: List[Dict], buckets: Dict[str, Tuple[str, ...]]
) -> Dict[str, float]:
    """Share of device-busy time per scope bucket.

    ``buckets`` maps bucket name -> substrings matched (first hit wins,
    in dict order) against each op's jax name-stack path; unmatched time
    lands in "other". Returns fractional shares summing to ~1.0 (empty
    input: {}).
    """
    totals = {name: 0.0 for name in buckets}
    totals["other"] = 0.0
    for op in ops:
        scope = op.get("scope", "") or op.get("name", "")
        for name, keys in buckets.items():
            if any(k in scope for k in keys):
                totals[name] += op["dur_us"]
                break
        else:
            totals["other"] += op["dur_us"]
    grand = sum(totals.values())
    if grand <= 0:
        return {}
    return {k: v / grand for k, v in totals.items()}


def _base_name(name: str) -> str:
    """jit_matmul(12345...) -> jit_matmul — aggregate across executions."""
    return name.split("(", 1)[0].strip()[:120]


def record_events(
    events: List[Tuple[str, bool, float, float]],
    capture_start_ns: int,
    min_dur_us: float = 1.0,
    max_events: int = 4096,
) -> int:
    """Feed captured events into the native ring/histograms. Event
    timestamps are µs relative to the trace session; they are mapped
    onto the native clock via the capture-start anchor."""
    timer = get_timer()
    recorded = 0
    for name, is_device, ts_us, dur_us in events:
        if dur_us < min_dur_us:
            continue
        if recorded >= max_events:
            logger.info(
                "xla capture truncated at %d events (of %d)",
                max_events,
                len(events),
            )
            break
        kind = (
            SpanKind.COLLECTIVE
            if _COLLECTIVE_RE.search(name)
            else SpanKind.CUSTOM
        )
        prefix = "xla/" if is_device else "xla_host/"
        timer.record(
            prefix + _base_name(name),
            kind,
            capture_start_ns + int(ts_us * 1000),
            int(dur_us * 1000),
        )
        recorded += 1
    timer.set_gauge("xla_capture_events", float(recorded))
    return recorded


class XlaCaptureListener:
    """Background acquisition thread living inside the worker process
    (installed by runtime init when DLROVER_TPU_TIMER_XLA=1)."""

    def __init__(
        self,
        local_rank: int = 0,
        interval_s: float = 60.0,
        capture_s: float = 1.0,
    ):
        self._trigger = trigger_path(local_rank)
        self._interval_s = interval_s
        self._capture_s = capture_s
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.captures = 0

    def start(self):
        self._thread = threading.Thread(
            target=self._loop, name="xla-capture", daemon=True
        )
        self._thread.start()
        # A capture in flight while the interpreter tears down aborts
        # the process from C++ ("FATAL: exception not rethrown" in the
        # profiler session) — drain cleanly at exit.
        import atexit

        atexit.register(self.stop)

    def stop(self):
        self._stopped.set()
        if self._thread is not None:
            self._thread.join(timeout=self._capture_s + 30)

    def capture_once(self):
        timer = get_timer()
        start_ns = timer.now_ns()
        # The native watchdog turns a stalled capture (wedged device)
        # into a hang report even though Python never returns.
        with timer.span("xla_capture", SpanKind.CUSTOM):
            events = capture_device_events(self._capture_s)
        n = record_events(events, start_ns)
        self.captures += 1
        logger.info(
            "xla capture #%d: %d runtime events recorded",
            self.captures,
            n,
        )

    def _loop(self):
        next_auto = time.time() + self._interval_s
        while not self._stopped.is_set():
            triggered = os.path.exists(self._trigger)
            if triggered or time.time() >= next_auto:
                if triggered:
                    try:
                        os.unlink(self._trigger)
                    except OSError:
                        pass
                try:
                    self.capture_once()
                except Exception:
                    logger.warning("xla capture failed", exc_info=True)
                next_auto = time.time() + self._interval_s
            self._stopped.wait(0.5)


_started_listener: Optional[XlaCaptureListener] = None


def maybe_start_listener(local_rank: int = 0) -> Optional[XlaCaptureListener]:
    """Idempotent per process: an instrumented script under the agent's
    sitecustomize injection would otherwise arm TWO listeners (startup +
    runtime init) whose overlapping jax.profiler windows collide."""
    global _started_listener
    from dlrover_tpu.common.env_utils import get_env_bool

    if not get_env_bool("DLROVER_TPU_TIMER_XLA"):
        return None
    if _started_listener is not None:
        return _started_listener
    interval = float(os.getenv("DLROVER_TPU_TIMER_XLA_INTERVAL", "60"))
    window = float(os.getenv("DLROVER_TPU_TIMER_XLA_WINDOW", "1.0"))
    listener = XlaCaptureListener(local_rank, interval, window)
    _started_listener = listener
    listener.start()
    logger.info(
        "xla capture listener on (every %.0fs, %.1fs windows)",
        interval,
        window,
    )
    return listener
