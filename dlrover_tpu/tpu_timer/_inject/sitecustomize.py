"""Zero-cooperation profiler injection (reference xpu_timer LD_PRELOAD
contract, nvidia/hook.cc: the profiled script needs no code changes).

The agent prepends this directory to a worker's PYTHONPATH when
DLROVER_TPU_TIMER_XLA is enabled; Python imports `sitecustomize` at
interpreter startup, which arms the XLA capture listener even when the
train script never imports dlrover_tpu. Any sitecustomize that this one
shadows (e.g. a platform's TPU-plugin bootstrap) is chain-loaded first
so nothing else on the machine changes.
"""

import os
import sys

_d = os.path.dirname(os.path.abspath(__file__))

# Chain-load the sitecustomize we shadowed, if any: drop our dir from
# sys.path, find the next one, and exec it under a distinct module name.
try:
    sys.path.remove(_d)
except ValueError:
    pass
try:
    # PathFinder search, NOT importlib.util.find_spec: the latter would
    # return THIS in-progress module's spec from sys.modules and the
    # chain-load would silently never happen.
    import importlib.machinery
    import importlib.util

    _spec = importlib.machinery.PathFinder.find_spec(
        "sitecustomize", sys.path
    )
    if _spec is not None and _spec.origin and (
        os.path.dirname(os.path.abspath(_spec.origin)) != _d
    ):
        _mod = importlib.util.module_from_spec(_spec)
        sys.modules["_dlrover_tpu_chained_sitecustomize"] = _mod
        _spec.loader.exec_module(_mod)
except Exception:  # noqa: BLE001 - never break interpreter startup
    pass

try:
    from dlrover_tpu.common.env_utils import get_env_bool

    if get_env_bool("DLROVER_TPU_TIMER_XLA"):
        from dlrover_tpu.tpu_timer.xla_capture import maybe_start_listener

        maybe_start_listener(
            int(os.getenv("DLROVER_TPU_LOCAL_RANK", "0") or 0)
        )
except Exception:  # noqa: BLE001 - profiling must never kill a job
    pass
