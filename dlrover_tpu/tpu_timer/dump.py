"""Timeline/metrics fetch CLI.

Parity: reference py_xpu_timer tools (gen_trace_timeline.py, dump
driver) — the daemon already serves a chrome-trace JSON, so the tool is
a fetch-and-save:

    python -m dlrover_tpu.tpu_timer.dump --port 18889 --out trace.json
    python -m dlrover_tpu.tpu_timer.dump --port 18889 --metrics
    python -m dlrover_tpu.tpu_timer.dump --port 18889 --out - \\
        | python tools/merge_timeline.py --trace - --out merged.json

``--retries``/backoff covers the race where the daemon is still
starting (worker boot) or restarting; ``--out -`` streams to stdout for
piping into the merge tool. Saved timelines get a ``clock_sync`` anchor
(epoch minus CLOCK_MONOTONIC at fetch time, both clocks read on the
daemon's own host) so the merger can land the monotonic trace
timestamps on the job-wide epoch clock.

Open the JSON in chrome://tracing or https://ui.perfetto.dev.
"""

import argparse
import http.client
import json
import sys
import time


def fetch(port: int, path: str, host: str = "127.0.0.1") -> bytes:
    conn = http.client.HTTPConnection(host, port, timeout=30)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        if resp.status != 200:
            raise RuntimeError(f"GET {path} -> {resp.status}")
        return resp.read()
    finally:
        conn.close()


def fetch_with_retries(
    port: int,
    path: str,
    host: str = "127.0.0.1",
    retries: int = 0,
    backoff_s: float = 0.5,
) -> bytes:
    """Fetch, retrying a daemon that is still coming up; exponential
    backoff capped at 8s per wait."""
    err: Exception = RuntimeError("no attempt made")
    for attempt in range(retries + 1):
        if attempt:
            wait = min(backoff_s * (2 ** (attempt - 1)), 8.0)
            print(
                f"fetch attempt {attempt} failed ({err}); retrying in "
                f"{wait:.1f}s",
                file=sys.stderr,
            )
            time.sleep(wait)
        try:
            return fetch(port, path, host)
        except (OSError, RuntimeError) as e:
            err = e
    raise err


_LOCAL_HOSTS = ("127.0.0.1", "localhost", "::1")


def annotate_clock_sync(data: bytes, host: str = "127.0.0.1") -> bytes:
    """Embed the epoch<->monotonic offset into a timeline JSON. The
    daemon stamps events with CLOCK_MONOTONIC (seconds since ITS host
    booted), so the anchor is only valid when this tool runs on the
    daemon's own host — a remote fetch would mix two machines' boot
    epochs and silently misplace the rank on the merged timeline, so
    remote traces are left unanchored (the merge tool then does
    best-effort placement and says so). Non-JSON data passes through
    untouched."""
    if host not in _LOCAL_HOSTS:
        return data
    try:
        trace = json.loads(data)
    except ValueError:
        return data
    if not isinstance(trace, dict):
        return data
    trace["clock_sync"] = {
        "epoch_minus_mono_us": (time.time() - time.monotonic()) * 1e6,
        "fetched_at": time.time(),
    }
    return json.dumps(trace).encode()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="tpu_timer dump tool")
    parser.add_argument("--host", type=str, default="127.0.0.1")
    parser.add_argument("--port", type=int, default=18889)
    parser.add_argument(
        "--out",
        type=str,
        default="tpu_timer_trace.json",
        help="output path, or '-' to stream to stdout",
    )
    parser.add_argument(
        "--metrics",
        action="store_true",
        help="print Prometheus metrics instead of saving the timeline",
    )
    parser.add_argument(
        "--retries",
        type=int,
        default=0,
        help="retry a daemon that is still starting (with backoff)",
    )
    parser.add_argument(
        "--backoff",
        type=float,
        default=0.5,
        help="initial retry backoff seconds (doubles per attempt)",
    )
    args = parser.parse_args(argv)
    try:
        if args.metrics:
            sys.stdout.write(
                fetch_with_retries(
                    args.port,
                    "/metrics",
                    args.host,
                    retries=args.retries,
                    backoff_s=args.backoff,
                ).decode()
            )
            return 0
        data = annotate_clock_sync(
            fetch_with_retries(
                args.port,
                "/timeline",
                args.host,
                retries=args.retries,
                backoff_s=args.backoff,
            ),
            host=args.host,
        )
        if args.out == "-":
            sys.stdout.buffer.write(data)
            sys.stdout.buffer.flush()
            return 0
        with open(args.out, "wb") as f:
            f.write(data)
        print(f"timeline saved to {args.out} ({len(data)} bytes)")
        return 0
    except (OSError, RuntimeError) as e:
        print(f"fetch failed: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    raise SystemExit(main())
