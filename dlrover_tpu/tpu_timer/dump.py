"""Timeline/metrics fetch CLI.

Parity: reference py_xpu_timer tools (gen_trace_timeline.py, dump
driver) — the daemon already serves a chrome-trace JSON, so the tool is
a fetch-and-save:

    python -m dlrover_tpu.tpu_timer.dump --port 18889 --out trace.json
    python -m dlrover_tpu.tpu_timer.dump --port 18889 --metrics

Open the JSON in chrome://tracing or https://ui.perfetto.dev.
"""

import argparse
import http.client
import sys


def fetch(port: int, path: str, host: str = "127.0.0.1") -> bytes:
    conn = http.client.HTTPConnection(host, port, timeout=30)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        if resp.status != 200:
            raise RuntimeError(f"GET {path} -> {resp.status}")
        return resp.read()
    finally:
        conn.close()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="tpu_timer dump tool")
    parser.add_argument("--host", type=str, default="127.0.0.1")
    parser.add_argument("--port", type=int, default=18889)
    parser.add_argument("--out", type=str, default="tpu_timer_trace.json")
    parser.add_argument(
        "--metrics",
        action="store_true",
        help="print Prometheus metrics instead of saving the timeline",
    )
    args = parser.parse_args(argv)
    try:
        if args.metrics:
            sys.stdout.write(
                fetch(args.port, "/metrics", args.host).decode()
            )
            return 0
        data = fetch(args.port, "/timeline", args.host)
        with open(args.out, "wb") as f:
            f.write(data)
        print(f"timeline saved to {args.out} ({len(data)} bytes)")
        return 0
    except (OSError, RuntimeError) as e:
        print(f"fetch failed: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    raise SystemExit(main())
