from dlrover_tpu.tpu_timer.bridge import (  # noqa: F401
    SpanKind,
    TpuTimer,
    get_timer,
)
