"""ctypes bridge to the native tpu_timer runtime (libtpu_timer.so).

Parity: reference xpu_timer's py side (py_xpu_timer) + the
LD_PRELOAD hook layer (nvidia/hook.cc). On TPU there is no dlsym-able
NCCL: spans are fed explicitly from Python at the natural sync points
(jitted step dispatch, XLA compiles, checkpoint phases, collective
probes), while everything that must survive a wedged Python runtime —
trace ring, aggregation, Prometheus daemon, hang watchdog — is native.

The library is built on first use if missing (one g++ invocation, no
third-party deps) and cached next to the sources.
"""

import ctypes
import fcntl
import os
import subprocess
import tempfile
import threading
from contextlib import contextmanager
from typing import Optional

from dlrover_tpu.common.constants import NodeEnv
from dlrover_tpu.common.log import logger

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "native",
    "tpu_timer",
)
_SO_PATH = os.path.join(_NATIVE_DIR, "libtpu_timer.so")


class SpanKind:
    STEP = 0
    COMPILE = 1
    CHECKPOINT = 2
    COLLECTIVE = 3
    DATA = 4
    CUSTOM = 9


def port_file_path(local_rank: int) -> str:
    """Where a worker publishes its daemon's actually-bound port (the
    launcher-side collector re-reads it before each scrape)."""
    job = os.getenv(NodeEnv.JOB_NAME, "job")
    return os.path.join(
        tempfile.gettempdir(), f"dlrover_tpu_timer_{job}_{local_rank}.port"
    )


def publish_port(local_rank: int, port: int):
    path = port_file_path(local_rank)
    tmp = f"{path}.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(str(port))
    os.rename(tmp, path)


def _ensure_built() -> str:
    if os.path.exists(_SO_PATH):
        return _SO_PATH
    # Serialize concurrent first-use builds across worker processes: make
    # writes the .so in place, and a sibling must not dlopen a half-
    # written ELF.
    lock_path = os.path.join(
        tempfile.gettempdir(), "dlrover_tpu_timer_build.lock"
    )
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            if not os.path.exists(_SO_PATH):
                logger.info("building libtpu_timer.so (first use)")
                subprocess.run(
                    ["make", "-C", _NATIVE_DIR],
                    check=True,
                    capture_output=True,
                )
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)
    return _SO_PATH


def _load_lib() -> ctypes.CDLL:
    lib = ctypes.CDLL(_ensure_built())
    lib.tt_init.argtypes = [ctypes.c_int64]
    lib.tt_start_server.argtypes = [ctypes.c_int]
    lib.tt_start_server.restype = ctypes.c_int
    lib.tt_begin.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.tt_begin.restype = ctypes.c_int64
    lib.tt_end.argtypes = [ctypes.c_int64, ctypes.c_double]
    lib.tt_record.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_double,
    ]
    lib.tt_set_gauge.argtypes = [ctypes.c_char_p, ctypes.c_double]
    lib.tt_counter_add.argtypes = [ctypes.c_char_p, ctypes.c_double]
    lib.tt_hang_count.restype = ctypes.c_int
    lib.tt_now_ns.restype = ctypes.c_int64
    lib.tt_dump_timeline.argtypes = [ctypes.c_char_p]
    lib.tt_metrics_text.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.tt_metrics_text.restype = ctypes.c_int
    return lib


class TpuTimer:
    """Process-wide profiler handle (native singleton underneath)."""

    _instance: Optional["TpuTimer"] = None
    _lock = threading.Lock()

    def __init__(self, hang_timeout_s: float = 600.0):
        self._lib = _load_lib()
        self._lib.tt_init(int(hang_timeout_s * 1000))
        self.port = 0

    @classmethod
    def get(cls) -> "TpuTimer":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    # ---- daemon -------------------------------------------------------------

    def start_server(self, port: int = 0) -> int:
        """Start the metrics/timeline HTTP daemon; returns the bound port
        (reference xpu_timer daemon :18889)."""
        self.port = self._lib.tt_start_server(port)
        if self.port:
            logger.info("tpu_timer daemon on port %d", self.port)
        return self.port

    # ---- spans --------------------------------------------------------------

    @contextmanager
    def span(self, name: str, kind: int = SpanKind.CUSTOM, flops: float = 0.0):
        sid = self._lib.tt_begin(name.encode(), kind)
        try:
            yield
        finally:
            self._lib.tt_end(sid, flops)

    def record(
        self,
        name: str,
        kind: int,
        start_ns: int,
        dur_ns: int,
        flops: float = 0.0,
    ):
        self._lib.tt_record(name.encode(), kind, start_ns, dur_ns, flops)

    def timed_step(self, step_fn, name: str = "train_step",
                   flops_per_step: float = 0.0):
        """Wrap a jitted step: blocks on the result so the span covers
        device execution (the TPU analogue of CUDA-event timing)."""
        import jax

        def wrapped(*args, **kwargs):
            sid = self._lib.tt_begin(name.encode(), SpanKind.STEP)
            try:
                out = step_fn(*args, **kwargs)
                out = jax.block_until_ready(out)
                return out
            finally:
                self._lib.tt_end(sid, flops_per_step)

        return wrapped

    # ---- metrics ------------------------------------------------------------

    def set_gauge(self, name: str, value: float):
        self._lib.tt_set_gauge(name.encode(), value)

    def counter_add(self, name: str, delta: float = 1.0):
        self._lib.tt_counter_add(name.encode(), delta)

    def hang_count(self) -> int:
        return self._lib.tt_hang_count()

    def now_ns(self) -> int:
        return self._lib.tt_now_ns()

    def metrics_text(self) -> str:
        cap = 1 << 16
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.tt_metrics_text(buf, cap)
            if n >= 0:
                return buf.value.decode()
            cap = -n + 1

    def dump_timeline(self, path: str) -> bool:
        return self._lib.tt_dump_timeline(path.encode()) == 0


def get_timer() -> TpuTimer:
    return TpuTimer.get()


def active_timer() -> Optional[TpuTimer]:
    """The timer IF something already initialized it, else None — for
    callers (tracing decorators, GC hooks) that must never trigger the
    first-use native build as a side effect."""
    return TpuTimer._instance
