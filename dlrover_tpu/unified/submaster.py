"""Per-role SubMasters.

Parity: reference dlrover/python/unified/backend/elastic/master.py:54
(per-role SubMaster actors with ``check_child``) — each role's workers
are owned by a SubMaster that launches them, health-checks them through
the backend's ``check_child`` hook, and applies the role's failover
policy (gang restart within its restart budget). The PrimeManager
orchestrates SubMasters and keeps only job-level concerns (job
failover, persistence, success).

The ElasticSubMaster adds membership awareness for elastic roles: a
worker lost mid-run triggers a GANG restart of the role (JAX worlds are
re-formed whole, matching the elastic agent's re-mesh semantics) rather
than a single-process respawn.
"""

from typing import Dict, List, Optional

from dlrover_tpu.common.log import logger
from dlrover_tpu.unified.backend import Backend, WorkerHandle
from dlrover_tpu.unified.config import RoleConfig
from dlrover_tpu.unified.graph import Vertex


class RoleStatus:
    RUNNING = "running"
    SUCCEEDED = "succeeded"
    FAILED = "failed"


class SubMaster:
    def __init__(
        self,
        role: RoleConfig,
        vertices: List[Vertex],
        backend: Backend,
        job_name: str,
    ):
        self.role = role
        self.vertices = vertices
        self.backend = backend
        self.job_name = job_name
        self.restarts = 0
        self.handles: Dict[str, WorkerHandle] = {}
        self._done: Dict[str, int] = {}

    # ---- lifecycle ---------------------------------------------------------

    def launch_all(self):
        for vertex in self.vertices:
            self._launch(vertex)

    def _launch(self, vertex: Vertex):
        self.handles[vertex.name] = self.backend.start_worker(
            vertex, self.role, self.job_name
        )

    def reattach_or_launch(self, records: Dict[str, Dict]):
        """Self-failover path: adopt live workers from a previous
        manager incarnation; relaunch only the missing/dead-without-
        trace ones. Running workers are NOT disturbed."""
        for vertex in self.vertices:
            record = records.get(vertex.name)
            handle = (
                self.backend.reattach(vertex, record) if record else None
            )
            if handle is not None:
                self.handles[vertex.name] = handle
            else:
                logger.info(
                    "no live worker to adopt for %s; launching fresh",
                    vertex.name,
                )
                self._launch(vertex)

    def stop_all(self):
        for handle in self.handles.values():
            try:
                self.backend.stop_worker(handle)
            except Exception:
                logger.warning("worker stop failed", exc_info=True)

    # ---- supervision -------------------------------------------------------

    def check_children(self) -> Optional[str]:
        """Poll every child (through the backend's check_child hook).
        Returns a RoleStatus transition or None while healthy/running.
        Restarts within budget are handled HERE; an exhausted budget
        reports FAILED for the manager's failover policy to resolve."""
        failures: Dict[str, int] = {}
        for name, handle in list(self.handles.items()):
            if name in self._done:
                continue
            code = self.backend.check_child(handle)
            if code is None:
                continue
            if code == 0:
                self._done[name] = 0
            else:
                failures[name] = code
        if failures:
            if self.role.failover_level == "ignore":
                for name in failures:
                    logger.info(
                        "ignoring failed worker %s (failover=ignore)", name
                    )
                    self._done[name] = failures[name]
            elif self.role.failover_level == "job":
                return RoleStatus.FAILED  # escalate: manager restarts job
            else:
                if self.restarts >= self.role.max_restarts:
                    logger.error(
                        "role %s exhausted %d restarts",
                        self.role.name,
                        self.role.max_restarts,
                    )
                    return RoleStatus.FAILED
                self.restarts += 1
                self.gang_restart()
                return None
        if len(self._done) == len(self.handles):
            return RoleStatus.SUCCEEDED
        return None

    def gang_restart(self):
        """Stop + relaunch the WHOLE role: elastic JAX worlds re-form
        whole (a lone respawned process would rejoin a dead world)."""
        logger.info(
            "gang restart of role %s (#%d)", self.role.name, self.restarts
        )
        self.stop_all()
        self._done.clear()
        for vertex in self.vertices:
            self._launch(vertex)

    def worker_records(self) -> Dict[str, Dict]:
        return {
            name: handle.record()
            for name, handle in self.handles.items()
        }

    @property
    def escalates_to_job(self) -> bool:
        return self.role.failover_level == "job"


class ElasticSubMaster(SubMaster):
    """SubMaster for elastic data-parallel roles: a membership change
    ALWAYS re-forms the world whole (gang), never a solo respawn — a
    lone respawned process would rejoin a dead JAX world. This is the
    subprocess analogue of the reference's elastic SubMaster which
    re-runs its embedded rendezvous."""

    def reattach_or_launch(self, records: Dict[str, Dict]):
        """Self-failover: adopt the role only if every member is still
        alive OR finished cleanly (exit 0 is completed work, not a lost
        member); one FAILED/vanished member means the world is gone, so
        the adopted survivors are stopped and the whole role
        relaunches."""
        adopted: Dict[str, WorkerHandle] = {}
        done: Dict[str, int] = {}
        whole = True
        for vertex in self.vertices:
            record = records.get(vertex.name)
            handle = (
                self.backend.reattach(vertex, record) if record else None
            )
            if handle is None:
                whole = False
                continue
            code = self.backend.poll(handle)
            if code == 0:
                done[vertex.name] = 0
            elif code is not None:
                whole = False
            adopted[vertex.name] = handle
        if whole and len(adopted) == len(self.vertices):
            self.handles = adopted
            self._done.update(done)
            return
        logger.info(
            "elastic role %s lost members while the master was down; "
            "gang-relaunching the whole world",
            self.role.name,
        )
        for handle in adopted.values():
            try:
                self.backend.stop_worker(handle)
            except Exception:
                logger.warning("worker stop failed", exc_info=True)
        self.handles.clear()
        self._done.clear()
        self.launch_all()


def create_submaster(
    role: RoleConfig,
    vertices: List[Vertex],
    backend: Backend,
    job_name: str,
) -> SubMaster:
    if role.sub_master == "elastic":
        return ElasticSubMaster(role, vertices, backend, job_name)
    return SubMaster(role, vertices, backend, job_name)
