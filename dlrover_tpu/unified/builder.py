"""Fluent DLJobBuilder DSL.

Parity: reference dlrover/python/unified/api/builder/base.py:154-631
(DLJobBuilder: .train()/.role()/.with_collocation()/.nnodes()...). The
builder accumulates role specs and produces a validated DLJobConfig.

Example::

    job = (
        DLJobBuilder("ppo")
        .nnodes(2)
        .role("trainer").run("my.train").total(4).per_group(2).add()
        .role("rollout").run("my.rollout").total(4).add()
        .with_collocation("trainer", "rollout")
        .build()
    )
"""

from typing import Dict, List

from dlrover_tpu.unified.config import DLJobConfig, RoleConfig


class RoleBuilder:
    def __init__(self, parent: "DLJobBuilder", name: str):
        self._parent = parent
        self._role = RoleConfig(name=name, entrypoint="")

    def run(self, entrypoint: str) -> "RoleBuilder":
        self._role.entrypoint = entrypoint
        return self

    def total(self, n: int) -> "RoleBuilder":
        self._role.total = n
        return self

    def per_group(self, n: int) -> "RoleBuilder":
        self._role.per_group = n
        return self

    def env(self, key: str, value: str) -> "RoleBuilder":
        self._role.envs[key] = value
        return self

    def args(self, *args: str) -> "RoleBuilder":
        self._role.args = list(args)
        return self

    def resource(self, **kwargs: float) -> "RoleBuilder":
        self._role.resource.update(kwargs)
        return self

    def failover(self, level: str) -> "RoleBuilder":
        self._role.failover_level = level
        return self

    def max_restarts(self, n: int) -> "RoleBuilder":
        self._role.max_restarts = n
        return self

    def elastic(self) -> "RoleBuilder":
        """Mark as an elastic data-parallel role (gang world
        re-formation on membership change; ElasticSubMaster)."""
        self._role.sub_master = "elastic"
        return self

    def add(self) -> "DLJobBuilder":
        self._parent._roles.append(self._role)
        return self._parent


class DLJobBuilder:
    def __init__(self, job_name: str = "unified-job"):
        self._job_name = job_name
        self._roles: List[RoleConfig] = []
        self._collocations: List[List[str]] = []
        self._node_num = 1
        self._global_envs: Dict[str, str] = {}
        self._state_path = ""

    def nnodes(self, n: int) -> "DLJobBuilder":
        self._node_num = n
        return self

    def role(self, name: str) -> RoleBuilder:
        return RoleBuilder(self, name)

    def train(self, entrypoint: str) -> RoleBuilder:
        """Shorthand: the conventional 'trainer' role."""
        return self.role("trainer").run(entrypoint)

    # ---- RL role sugar (reference api/builder/rl.py) -----------------

    def actor(self, entrypoint: str) -> RoleBuilder:
        return self.role("actor").run(entrypoint)

    def rollout(self, entrypoint: str) -> RoleBuilder:
        return self.role("rollout").run(entrypoint)

    def reward(self, entrypoint: str) -> RoleBuilder:
        return self.role("reward").run(entrypoint)

    def critic(self, entrypoint: str) -> RoleBuilder:
        return self.role("critic").run(entrypoint)

    def reference(self, entrypoint: str) -> RoleBuilder:
        return self.role("reference").run(entrypoint)

    def with_collocation(self, *role_names: str) -> "DLJobBuilder":
        self._collocations.append(list(role_names))
        return self

    def global_env(self, key: str, value: str) -> "DLJobBuilder":
        self._global_envs[key] = value
        return self

    def master_state(self, path: str) -> "DLJobBuilder":
        self._state_path = path
        return self

    def build(self) -> DLJobConfig:
        config = DLJobConfig(
            job_name=self._job_name,
            roles=list(self._roles),
            collocations=list(self._collocations),
            node_num=self._node_num,
            global_envs=dict(self._global_envs),
            master_state_path=self._state_path,
        )
        config.validate()
        return config
