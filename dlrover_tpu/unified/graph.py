"""Execution graph: roles -> scheduled worker vertices.

Parity: reference dlrover/python/unified/controller/schedule/graph.py:312
(DLExecutionGraph) + scheduler.py gang placement. Each vertex is one
worker process of a role; vertices of collocated roles that share a
group index land in the same placement bundle (the STRICT_PACK analogue
— on the local backend a bundle is just a shared host slot).
"""

from dataclasses import dataclass, field
from typing import Dict, List

from dlrover_tpu.unified.config import DLJobConfig


@dataclass
class Vertex:
    role: str
    rank: int  # rank within the role
    world_size: int  # role total
    group_index: int  # which group (bundle) this vertex belongs to
    bundle_id: int = -1
    node_slot: int = -1  # assigned by unified/scheduler.py
    envs: Dict[str, str] = field(default_factory=dict)

    @property
    def name(self) -> str:
        return f"{self.role}-{self.rank}"


@dataclass
class ExecutionGraph:
    vertices: List[Vertex] = field(default_factory=list)
    bundles: Dict[int, List[Vertex]] = field(default_factory=dict)

    def by_role(self, role: str) -> List[Vertex]:
        return [v for v in self.vertices if v.role == role]


def build_execution_graph(config: DLJobConfig) -> ExecutionGraph:
    graph = ExecutionGraph()
    # Map each role to its collocation group (roles not mentioned get
    # their own).
    colloc_of: Dict[str, int] = {}
    for i, group in enumerate(config.collocations):
        for name in group:
            colloc_of[name] = i
    next_solo = len(config.collocations)
    for role in config.roles:
        if role.name not in colloc_of:
            colloc_of[role.name] = next_solo
            next_solo += 1

    # Bundles: (collocation group, group_index) -> bundle id. Collocated
    # roles must have the same number of groups for PACK to make sense.
    bundle_ids: Dict[tuple, int] = {}

    def bundle_for(role_name: str, group_index: int) -> int:
        key = (colloc_of[role_name], group_index)
        if key not in bundle_ids:
            bundle_ids[key] = len(bundle_ids)
        return bundle_ids[key]

    for role in config.roles:
        for rank in range(role.total):
            group_index = rank // role.per_group
            vertex = Vertex(
                role=role.name,
                rank=rank,
                world_size=role.total,
                group_index=group_index,
                envs={**config.global_envs, **role.envs},
            )
            vertex.bundle_id = bundle_for(role.name, group_index)
            graph.vertices.append(vertex)
            graph.bundles.setdefault(vertex.bundle_id, []).append(vertex)
    return graph
