"""Worker backends for the unified layer.

Parity: reference dlrover/python/unified/backend (ElasticWorker /
BaseWorker Ray actors). Ray is not a baked-in dependency, so the
first-class backend runs each vertex as a local subprocess with role
coordinates injected via env — the same contract a Ray-actor backend
implements when ``ray`` is importable (gated in RayBackend.available()).
"""

import abc
import os
import signal
import subprocess
import sys
from dataclasses import dataclass
from typing import Optional

from dlrover_tpu.common.log import logger
from dlrover_tpu.unified.config import RoleConfig
from dlrover_tpu.unified.graph import Vertex


class UnifiedEnv:
    ROLE = "DLROVER_TPU_ROLE"
    ROLE_RANK = "DLROVER_TPU_ROLE_RANK"
    ROLE_WORLD_SIZE = "DLROVER_TPU_ROLE_WORLD_SIZE"
    GROUP_INDEX = "DLROVER_TPU_GROUP_INDEX"
    BUNDLE_ID = "DLROVER_TPU_BUNDLE_ID"
    JOB_NAME = "DLROVER_TPU_JOB_NAME"


@dataclass
class WorkerHandle:
    vertex: Vertex
    process: subprocess.Popen


class Backend(abc.ABC):
    @abc.abstractmethod
    def start_worker(
        self, vertex: Vertex, role: RoleConfig, job_name: str
    ) -> WorkerHandle:
        ...

    @abc.abstractmethod
    def poll(self, handle: WorkerHandle) -> Optional[int]:
        """None while running, else the exit code."""

    @abc.abstractmethod
    def stop_worker(self, handle: WorkerHandle, timeout: float = 10.0):
        ...


class LocalProcessBackend(Backend):
    def start_worker(
        self, vertex: Vertex, role: RoleConfig, job_name: str
    ) -> WorkerHandle:
        env = dict(os.environ)
        env.update(vertex.envs)
        env.update(
            {
                UnifiedEnv.ROLE: vertex.role,
                UnifiedEnv.ROLE_RANK: str(vertex.rank),
                UnifiedEnv.ROLE_WORLD_SIZE: str(vertex.world_size),
                UnifiedEnv.GROUP_INDEX: str(vertex.group_index),
                UnifiedEnv.BUNDLE_ID: str(vertex.bundle_id),
                UnifiedEnv.JOB_NAME: job_name,
            }
        )
        if ":" in role.entrypoint:
            module, fn = role.entrypoint.split(":", 1)
            code = f"import {module}; {module}.{fn}()"
            cmd = [sys.executable, "-c", code]
        else:
            cmd = [sys.executable, "-m", role.entrypoint]
        cmd += role.args
        proc = subprocess.Popen(cmd, env=env, start_new_session=True)
        logger.info(
            "started %s pid=%d (%s)", vertex.name, proc.pid, role.entrypoint
        )
        return WorkerHandle(vertex=vertex, process=proc)

    def poll(self, handle: WorkerHandle) -> Optional[int]:
        return handle.process.poll()

    def stop_worker(self, handle: WorkerHandle, timeout: float = 10.0):
        if handle.process.poll() is not None:
            return
        try:
            os.killpg(handle.process.pid, signal.SIGTERM)
        except ProcessLookupError:
            return
        try:
            handle.process.wait(timeout)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(handle.process.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            handle.process.wait()


class RayBackend(Backend):
    """Ray-actor backend; only constructible when ray is installed."""

    @staticmethod
    def available() -> bool:
        try:
            import ray  # noqa: F401

            return True
        except ImportError:
            return False

    def __init__(self):
        if not self.available():
            raise ImportError(
                "ray is not installed; use LocalProcessBackend"
            )
        raise NotImplementedError(
            "RayBackend is a deployment-time extension point; the "
            "process contract matches LocalProcessBackend"
        )

    def start_worker(self, vertex, role, job_name):
        raise NotImplementedError

    def poll(self, handle):
        raise NotImplementedError

    def stop_worker(self, handle, timeout=10.0):
        raise NotImplementedError
