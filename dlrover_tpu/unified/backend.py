"""Worker backends for the unified layer.

Parity: reference dlrover/python/unified/backend (ElasticWorker /
BaseWorker Ray actors). Ray is not a baked-in dependency, so the
first-class backend runs each vertex as a local subprocess with role
coordinates injected via env; RayBackend implements the same contract
with Ray actors scheduled into STRICT_PACK placement groups when
``ray`` is importable.

Self-failover support: every started worker writes its exit code to an
rc-file, and handles serialize to plain records (pid + rc path). A new
manager incarnation re-attaches to a live pid it did not spawn — the
process keeps running through the manager restart — and still learns
the true exit code afterwards from the rc-file.
"""

import abc
import os
import shlex
import signal
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass
from typing import Dict, Optional

from dlrover_tpu.common.log import logger
from dlrover_tpu.unified.config import RoleConfig
from dlrover_tpu.unified.graph import Vertex


class UnifiedEnv:
    ROLE = "DLROVER_TPU_ROLE"
    ROLE_RANK = "DLROVER_TPU_ROLE_RANK"
    ROLE_WORLD_SIZE = "DLROVER_TPU_ROLE_WORLD_SIZE"
    GROUP_INDEX = "DLROVER_TPU_GROUP_INDEX"
    BUNDLE_ID = "DLROVER_TPU_BUNDLE_ID"
    NODE_SLOT = "DLROVER_TPU_NODE_SLOT"
    JOB_NAME = "DLROVER_TPU_JOB_NAME"
    # Which backend launched this worker — the runtime data plane
    # (unified/rpc.py) picks its registry implementation from it.
    BACKEND = "DLROVER_TPU_UNIFIED_BACKEND"
    # Per-job shared secret for the runtime data plane (unified/rpc.py):
    # the manager resolves/creates it once and injects it into every
    # worker so auth works cross-node (Ray) without a shared filesystem.
    # Aliased from rpc.py (which reads it) so the two can't drift.
    from dlrover_tpu.unified.rpc import RUNTIME_TOKEN_ENV as RUNTIME_TOKEN


@dataclass
class WorkerHandle:
    vertex: Vertex
    process: Optional[subprocess.Popen] = None
    pid: int = -1
    rc_path: str = ""
    # Ray-backend fields
    actor: object = None
    future: object = None

    start_ticks: int = -1  # /proc starttime: guards pid recycling
    actor_name: str = ""   # Ray backend: named detached actor handle

    def record(self) -> Dict:
        """Serializable facts a future manager needs to re-attach."""
        return {
            "role": self.vertex.role,
            "rank": self.vertex.rank,
            "pid": self.pid,
            "rc_path": self.rc_path,
            "start_ticks": self.start_ticks,
            "actor_name": self.actor_name,
        }


def worker_cmd(role: RoleConfig) -> list:
    if ":" in role.entrypoint:
        module, fn = role.entrypoint.split(":", 1)
        code = f"import {module}; {module}.{fn}()"
        cmd = [sys.executable, "-c", code]
    else:
        cmd = [sys.executable, "-m", role.entrypoint]
    return cmd + role.args


def worker_envs(
    vertex: Vertex, job_name: str, backend: str = "local"
) -> Dict[str, str]:
    from dlrover_tpu.unified.rpc import resolve_runtime_token

    return {
        UnifiedEnv.ROLE: vertex.role,
        UnifiedEnv.ROLE_RANK: str(vertex.rank),
        UnifiedEnv.ROLE_WORLD_SIZE: str(vertex.world_size),
        UnifiedEnv.GROUP_INDEX: str(vertex.group_index),
        UnifiedEnv.BUNDLE_ID: str(vertex.bundle_id),
        UnifiedEnv.NODE_SLOT: str(vertex.node_slot),
        UnifiedEnv.JOB_NAME: job_name,
        UnifiedEnv.BACKEND: backend,
        UnifiedEnv.RUNTIME_TOKEN: resolve_runtime_token(job_name),
    }


class Backend(abc.ABC):
    @abc.abstractmethod
    def start_worker(
        self, vertex: Vertex, role: RoleConfig, job_name: str
    ) -> WorkerHandle:
        ...

    @abc.abstractmethod
    def poll(self, handle: WorkerHandle) -> Optional[int]:
        """None while running, else the exit code."""

    @abc.abstractmethod
    def stop_worker(self, handle: WorkerHandle, timeout: float = 10.0):
        ...

    def check_child(self, handle: WorkerHandle) -> Optional[int]:
        """Health hook beyond process liveness (reference SubMaster
        check_child); backends may override with deeper probes."""
        return self.poll(handle)

    def reattach(self, vertex: Vertex, record: Dict) -> Optional[WorkerHandle]:
        """Adopt a worker a previous manager incarnation started.
        Returns None when the backend cannot re-attach."""
        return None


class LocalProcessBackend(Backend):
    def __init__(self, rc_dir: str = ""):
        self._rc_dir = rc_dir or tempfile.mkdtemp(
            prefix="dlrover_tpu_unified_rc_"
        )

    def _rc_path(self, vertex: Vertex, job_name: str) -> str:
        return os.path.join(
            self._rc_dir, f"{job_name}-{vertex.name}-{os.getpid()}.rc"
        )

    def start_worker(
        self, vertex: Vertex, role: RoleConfig, job_name: str
    ) -> WorkerHandle:
        env = dict(os.environ)
        env.update(vertex.envs)
        env.update(worker_envs(vertex, job_name, backend="local"))
        rc_path = self._rc_path(vertex, job_name)
        try:
            os.unlink(rc_path)
        except FileNotFoundError:
            pass
        # Wrap the command so the exit code lands in the rc-file: a
        # re-attached manager (not the process's parent) can still read
        # the true exit status after the worker dies.
        inner = " ".join(shlex.quote(c) for c in worker_cmd(role))
        cmd = [
            "/bin/sh",
            "-c",
            f'{inner}; rc=$?; echo "$rc" > {shlex.quote(rc_path)}.tmp && '
            f"mv {shlex.quote(rc_path)}.tmp {shlex.quote(rc_path)}; "
            f"exit $rc",
        ]
        proc = subprocess.Popen(cmd, env=env, start_new_session=True)
        logger.info(
            "started %s pid=%d (%s)", vertex.name, proc.pid, role.entrypoint
        )
        return WorkerHandle(
            vertex=vertex,
            process=proc,
            pid=proc.pid,
            rc_path=rc_path,
            start_ticks=self._proc_start_ticks(proc.pid),
        )

    @staticmethod
    def _proc_start_ticks(pid: int) -> int:
        """Kernel start time of the process: (pid, start_ticks) is a
        unique process identity, immune to pid recycling."""
        try:
            with open(f"/proc/{pid}/stat") as f:
                return int(f.read().rsplit(")", 1)[1].split()[19])
        except (OSError, IndexError, ValueError):
            return -1

    @staticmethod
    def _pid_alive(pid: int) -> bool:
        """Liveness that does NOT count zombies: a dead-but-unreaped
        wrapper (its parent master crashed or hasn't waited) must read
        as exited."""
        try:
            with open(f"/proc/{pid}/stat") as f:
                state = f.read().rsplit(")", 1)[1].split()[0]
            return state != "Z"
        except (OSError, IndexError):
            try:
                os.kill(pid, 0)
                return True
            except (ProcessLookupError, PermissionError):
                return False

    def poll(self, handle: WorkerHandle) -> Optional[int]:
        if handle.process is not None:
            return handle.process.poll()
        # Re-attached: not our child. The rc-file is authoritative — it
        # existing means the worker exited, whatever now occupies the
        # pid (recycling) — then liveness via /proc.
        if handle.rc_path and os.path.exists(handle.rc_path):
            return self._read_rc(handle)
        if self._pid_alive(handle.pid):
            return None
        return self._read_rc(handle)

    def _read_rc(self, handle: WorkerHandle) -> int:
        try:
            with open(handle.rc_path) as f:
                return int(f.read().strip() or "1")
        except (OSError, ValueError):
            # Died without writing (SIGKILL of the wrapper): failure.
            return 1

    def stop_worker(self, handle: WorkerHandle, timeout: float = 10.0):
        if self.poll(handle) is not None:
            return
        try:
            os.killpg(handle.pid, signal.SIGTERM)
        except ProcessLookupError:
            return
        if handle.process is not None:
            try:
                handle.process.wait(timeout)
                return
            except subprocess.TimeoutExpired:
                pass
        else:
            deadline = time.time() + timeout
            while time.time() < deadline:
                if self.poll(handle) is not None:
                    return
                time.sleep(0.1)
        try:
            os.killpg(handle.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        if handle.process is not None:
            handle.process.wait()

    def reattach(self, vertex: Vertex, record: Dict) -> Optional[WorkerHandle]:
        pid = record.get("pid", -1)
        rc_path = record.get("rc_path", "")
        if pid <= 0:
            return None
        handle = WorkerHandle(
            vertex=vertex, process=None, pid=pid, rc_path=rc_path
        )
        # The rc-file is authoritative: if it exists the worker already
        # exited, whatever now occupies the pid.
        if rc_path and os.path.exists(rc_path):
            return handle
        if self._pid_alive(pid):
            # Guard against a recycled pid: the kernel start time must
            # match the one recorded at spawn.
            recorded = record.get("start_ticks", -1)
            if recorded >= 0 and self._proc_start_ticks(pid) != recorded:
                logger.warning(
                    "pid %d was recycled (start time mismatch); not "
                    "adopting it for %s",
                    pid,
                    vertex.name,
                )
                return None
            logger.info("re-attached %s pid=%d", vertex.name, pid)
            handle.start_ticks = recorded
            return handle
        return None


class UnifiedWorkerActor:
    """Body of the detached Ray worker actor (wrapped by ``ray.remote``
    at backend init). Detached + named so a restarted PrimeManager
    re-attaches with ``ray.get_actor`` instead of starting a duplicate;
    ``start`` is idempotent for the same reason."""

    def __init__(self):
        import threading

        self._proc = None
        self._lock = threading.Lock()

    def start(self, cmd, env):
        with self._lock:
            if self._proc is not None:
                return False  # re-attach must not respawn
            merged = dict(os.environ)
            merged.update(env)
            self._proc = subprocess.Popen(
                cmd, env=merged, start_new_session=True
            )
            return True

    def poll(self):
        with self._lock:
            if self._proc is None:
                return None
            return self._proc.poll()

    def stop(self, timeout=10.0):
        with self._lock:
            proc = self._proc
        if proc is None or proc.poll() is not None:
            return
        try:
            os.killpg(proc.pid, signal.SIGTERM)
            proc.wait(timeout)
        except Exception:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except Exception:
                pass


class RayBackend(Backend):
    """Ray backend: one NAMED DETACHED actor per vertex, scheduled into
    the STRICT_PACK placement group of its node slot (reference
    unified/controller/schedule/scheduler.py + backend actors). The
    detached-actor identity is what makes manager self-failover work on
    Ray: a new manager re-attaches with ``ray.get_actor`` and the worker
    process is never disturbed. Constructible only when ``ray`` is
    installed."""

    @staticmethod
    def available() -> bool:
        try:
            import ray  # noqa: F401

            return True
        except ImportError:
            return False

    def __init__(self, placement=None):
        if not self.available():
            raise ImportError(
                "ray is not installed; use LocalProcessBackend"
            )
        import ray

        self._ray = ray
        if not ray.is_initialized():
            ray.init(ignore_reinit_error=True)
        self._actor_cls = ray.remote(UnifiedWorkerActor)
        self._placement = placement
        self._groups: Dict[int, object] = {}
        self._inconclusive: Dict[str, int] = {}

    def _group_for(self, vertex: Vertex):
        """One placement group per node slot with one bundle per
        collocation bundle, sized from the scheduler's per-bundle
        aggregates (STRICT_PACK keeps collocated roles on one node)."""
        if self._placement is None or vertex.node_slot < 0:
            return None, None
        slot = vertex.node_slot
        if slot not in self._groups:
            slot_info = self._placement.slots[slot]
            bundle_res = []
            for bundle_id in slot_info.bundles or [0]:
                res = slot_info.bundle_resources.get(bundle_id, {})
                bundle_res.append({"CPU": max(res.get("cpu", 1), 1)})
            pg = self._ray.util.placement_group(
                bundle_res, strategy="STRICT_PACK"
            )
            self._ray.get(pg.ready())
            self._groups[slot] = pg
        pg = self._groups[slot]
        slot_info = self._placement.slots[slot]
        bundle_index = slot_info.bundles.index(vertex.bundle_id)
        return pg, bundle_index

    def _actor_name(self, vertex: Vertex, job_name: str) -> str:
        return f"{job_name}-{vertex.name}"

    def start_worker(self, vertex, role, job_name):
        ray = self._ray
        name = self._actor_name(vertex, job_name)
        env = dict(vertex.envs)
        env.update(worker_envs(vertex, job_name, backend="ray"))
        options = {
            "name": name,
            "lifetime": "detached",
            "get_if_exists": True,
            "num_cpus": role.resource.get("cpu", 1),
        }
        pg, bundle_index = self._group_for(vertex)
        if pg is not None:
            options["scheduling_strategy"] = (
                ray.util.scheduling_strategies.PlacementGroupSchedulingStrategy(  # noqa: E501
                    placement_group=pg,
                    placement_group_bundle_index=bundle_index,
                )
            )
        actor = self._actor_cls.options(**options).remote()
        ray.get(actor.start.remote(worker_cmd(role), env))
        logger.info("started ray worker actor %s", name)
        return WorkerHandle(vertex=vertex, actor=actor, actor_name=name)

    # Consecutive inconclusive polls tolerated before a wedged-but-
    # alive actor is declared failed anyway.
    MAX_INCONCLUSIVE_POLLS = 10

    def poll(self, handle):
        try:
            code = self._ray.get(handle.actor.poll.remote(), timeout=30)
            self._inconclusive.pop(handle.actor_name, None)
            return code
        except self._ray.exceptions.RayActorError:
            logger.warning(
                "ray actor %s is dead; reporting failed", handle.actor_name
            )
            # Actor names are reused across relaunches: the replacement
            # must start with a clean miss budget.
            self._inconclusive.pop(handle.actor_name, None)
            return 1
        except Exception:
            # Transient control-plane trouble (GetTimeoutError, brief
            # GCS unavailability) must NOT read as a worker failure — a
            # false positive gang-restarts a healthy role. But a
            # PERMANENTLY unreachable/wedged actor must not hang the
            # job either: a consecutive-miss budget breaks the tie.
            misses = self._inconclusive.get(handle.actor_name, 0) + 1
            self._inconclusive[handle.actor_name] = misses
            if misses >= self.MAX_INCONCLUSIVE_POLLS:
                logger.error(
                    "ray actor %s unreachable for %d consecutive polls; "
                    "reporting failed",
                    handle.actor_name,
                    misses,
                )
                self._inconclusive.pop(handle.actor_name, None)
                return 1
            logger.warning(
                "ray actor %s poll inconclusive (%d/%d); retrying",
                handle.actor_name,
                misses,
                self.MAX_INCONCLUSIVE_POLLS,
            )
            return None

    def stop_worker(self, handle, timeout: float = 10.0):
        self._inconclusive.pop(handle.actor_name, None)
        try:
            self._ray.get(
                handle.actor.stop.remote(timeout), timeout=timeout + 30
            )
            self._ray.kill(handle.actor)
        except Exception:
            logger.warning("ray actor stop failed", exc_info=True)

    def reattach(self, vertex, record):
        name = record.get("actor_name", "")
        if not name:
            return None
        try:
            actor = self._ray.get_actor(name)
        except Exception:
            return None
        logger.info("re-attached ray worker actor %s", name)
        return WorkerHandle(vertex=vertex, actor=actor, actor_name=name)


def create_backend(name: str = "auto", **kwargs) -> Backend:
    """auto -> Ray when installed, else local subprocesses."""
    if name == "ray" or (name == "auto" and RayBackend.available()):
        return RayBackend(**kwargs)
    kwargs.pop("placement", None)
    return LocalProcessBackend(**kwargs)
