"""Master runtime-state persistence for self-failover.

Parity: reference dlrover/python/unified/controller/state_backend.py
(in-memory / Ray-internal-KV) — here: in-memory and atomic-file JSON.
A restarted PrimeMaster reloads the job stage and per-role restart
counts so failover budgets survive the master itself dying.
"""

import json
import os
from typing import Dict, Optional


class MasterStateBackend:
    def save(self, state: Dict):
        raise NotImplementedError

    def load(self) -> Optional[Dict]:
        raise NotImplementedError

    def clear(self):
        raise NotImplementedError


class InMemoryStateBackend(MasterStateBackend):
    def __init__(self):
        self._state: Optional[Dict] = None

    def save(self, state: Dict):
        self._state = json.loads(json.dumps(state))

    def load(self) -> Optional[Dict]:
        return self._state

    def clear(self):
        self._state = None


class FileStateBackend(MasterStateBackend):
    def __init__(self, path: str):
        self._path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def save(self, state: Dict):
        tmp = f"{self._path}.tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.rename(tmp, self._path)

    def load(self) -> Optional[Dict]:
        try:
            with open(self._path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def clear(self):
        try:
            os.remove(self._path)
        except FileNotFoundError:
            pass


def build_state_backend(path: str = "") -> MasterStateBackend:
    return FileStateBackend(path) if path else InMemoryStateBackend()
