"""Unified job configuration model.

Parity: reference dlrover/python/unified/common (pydantic DLConfig /
WorkloadDesc, workload_desc.py) — plain validated dataclasses instead of
pydantic: the surface is small and dependency-light.

A job is a set of ROLES (trainer, actor, rollout, reward, ...); each
role runs ``total`` processes grouped ``per_group`` per node-slot, with
a python entrypoint (module or function path) and resource needs.
"""

from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class RoleConfig:
    name: str
    entrypoint: str  # "pkg.module" (run as python -m) or "pkg.module:fn"
    total: int = 1
    per_group: int = 1
    envs: Dict[str, str] = field(default_factory=dict)
    args: List[str] = field(default_factory=list)
    resource: Dict[str, float] = field(default_factory=dict)
    # Failover: "role" restarts this role's group on failure; "job"
    # restarts every role; "ignore" lets the process die.
    failover_level: str = "role"
    max_restarts: int = 3
    # SubMaster flavor: "default" supervises processes; "elastic" marks
    # an elastic data-parallel role (gang world re-formation semantics).
    sub_master: str = "default"

    def validate(self):
        if not self.name:
            raise ValueError("role name required")
        if not self.entrypoint:
            raise ValueError(f"role {self.name}: entrypoint required")
        if self.total < 1:
            raise ValueError(f"role {self.name}: total must be >= 1")
        if self.per_group < 1 or self.total % self.per_group != 0:
            raise ValueError(
                f"role {self.name}: total ({self.total}) must be a "
                f"multiple of per_group ({self.per_group})"
            )
        if self.failover_level not in ("role", "job", "ignore"):
            raise ValueError(
                f"role {self.name}: bad failover level "
                f"{self.failover_level!r}"
            )
        if self.sub_master not in ("default", "elastic"):
            raise ValueError(
                f"role {self.name}: bad sub_master {self.sub_master!r}"
            )


@dataclass
class DLJobConfig:
    job_name: str = "unified-job"
    roles: List[RoleConfig] = field(default_factory=list)
    # Roles sharing a collocation group are packed onto the same
    # node-slot (reference with_collocation / STRICT_PACK placement).
    collocations: List[List[str]] = field(default_factory=list)
    node_num: int = 1
    global_envs: Dict[str, str] = field(default_factory=dict)
    master_state_path: str = ""

    def role(self, name: str) -> Optional[RoleConfig]:
        for r in self.roles:
            if r.name == name:
                return r
        return None

    def validate(self):
        if not self.roles:
            raise ValueError("job needs at least one role")
        names = [r.name for r in self.roles]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate role names: {names}")
        for r in self.roles:
            r.validate()
        for group in self.collocations:
            for name in group:
                if self.role(name) is None:
                    raise ValueError(
                        f"collocation references unknown role {name!r}"
                    )
