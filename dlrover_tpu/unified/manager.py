"""PrimeManager: the unified job's state machine + failover.

Parity: reference dlrover/python/unified/controller/manager.py:88-797
(PrimeManager: INIT/READY/RUNNING/STOPPING FSM; prepare -> create
actors -> start; per-role / job-level failover; state persisted to a
MasterStateBackend for master self-failover).
"""

import threading
import time
from typing import Dict, Optional

from dlrover_tpu.common.log import logger
from dlrover_tpu.unified.backend import Backend, LocalProcessBackend, WorkerHandle
from dlrover_tpu.unified.config import DLJobConfig
from dlrover_tpu.unified.graph import ExecutionGraph, build_execution_graph
from dlrover_tpu.unified.state_backend import (
    MasterStateBackend,
    build_state_backend,
)


class JobStage:
    INIT = "INIT"
    READY = "READY"
    RUNNING = "RUNNING"
    STOPPING = "STOPPING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"


class PrimeManager:
    def __init__(
        self,
        config: DLJobConfig,
        backend: Optional[Backend] = None,
        state_backend: Optional[MasterStateBackend] = None,
        monitor_interval_s: float = 0.5,
    ):
        config.validate()
        self.config = config
        self.backend = backend or LocalProcessBackend()
        self.state_backend = state_backend or build_state_backend(
            config.master_state_path
        )
        self.graph: ExecutionGraph = build_execution_graph(config)
        self.stage = JobStage.INIT
        self._handles: Dict[str, WorkerHandle] = {}
        self._role_restarts: Dict[str, int] = {
            r.name: 0 for r in config.roles
        }
        self._job_restarts = 0
        self._monitor_interval_s = monitor_interval_s
        self._stopped = threading.Event()
        self._lock = threading.Lock()
        self._restore_state()

    # ---- persistence --------------------------------------------------------

    def _persist(self):
        self.state_backend.save(
            {
                "stage": self.stage,
                "role_restarts": self._role_restarts,
                "job_restarts": self._job_restarts,
            }
        )

    def _restore_state(self):
        state = self.state_backend.load()
        if state:
            self._role_restarts.update(state.get("role_restarts", {}))
            self._job_restarts = state.get("job_restarts", 0)
            logger.info(
                "restored manager state: restarts=%s", self._role_restarts
            )

    # ---- lifecycle ----------------------------------------------------------

    def prepare(self):
        """INIT -> READY (graph built, backend warm)."""
        if self.stage != JobStage.INIT:
            return
        self.stage = JobStage.READY
        self._persist()

    def start(self):
        """READY -> RUNNING: launch every vertex."""
        if self.stage not in (JobStage.INIT, JobStage.READY):
            raise RuntimeError(f"cannot start from stage {self.stage}")
        self.prepare()
        with self._lock:
            for vertex in self.graph.vertices:
                self._launch(vertex)
        self.stage = JobStage.RUNNING
        self._persist()
        logger.info(
            "unified job %s running: %d workers across %d roles",
            self.config.job_name,
            len(self.graph.vertices),
            len(self.config.roles),
        )

    def _launch(self, vertex):
        role = self.config.role(vertex.role)
        self._handles[vertex.name] = self.backend.start_worker(
            vertex, role, self.config.job_name
        )

    # ---- supervision --------------------------------------------------------

    def wait(self, timeout: Optional[float] = None) -> str:
        """Supervise until the job finishes; returns the final stage."""
        deadline = time.time() + timeout if timeout else None
        while not self._stopped.is_set():
            if deadline and time.time() > deadline:
                break
            done = self._tick()
            if done:
                break
            time.sleep(self._monitor_interval_s)
        return self.stage

    def _tick(self) -> bool:
        with self._lock:
            exited: Dict[str, int] = {}
            for name, handle in self._handles.items():
                code = self.backend.poll(handle)
                if code is not None:
                    exited[name] = code
            failures = {n: c for n, c in exited.items() if c != 0}
            if failures:
                return self._handle_failures(failures)
            if len(exited) == len(self._handles):
                self.stage = JobStage.SUCCEEDED
                self._persist()
                return True
            return False

    def _handle_failures(self, failures: Dict[str, int]) -> bool:
        failed_roles = sorted(
            {self._vertex_of(n).role for n in failures}
        )
        logger.warning(
            "unified workers failed: %s (roles %s)",
            failures,
            failed_roles,
        )
        # Strongest failover level among the failed roles wins.
        levels = {
            self.config.role(r).failover_level for r in failed_roles
        }
        if "job" in levels:
            return self._job_failover()
        for role_name in failed_roles:
            role = self.config.role(role_name)
            if role.failover_level == "ignore":
                # Drop the dead handles: an ignored role's crash must not
                # keep re-entering failure handling or block success.
                for name in list(failures):
                    if self._vertex_of(name).role == role_name:
                        logger.info(
                            "ignoring failed worker %s (failover=ignore)",
                            name,
                        )
                        del self._handles[name]
                continue
            if self._role_restarts[role_name] >= role.max_restarts:
                logger.error(
                    "role %s exhausted %d restarts; failing job",
                    role_name,
                    role.max_restarts,
                )
                self._fail()
                return True
            self._role_restarts[role_name] += 1
            self._restart_role(role_name)
        self._persist()
        if not self._handles:
            # Every worker was an ignored failure: nothing left to run.
            self.stage = JobStage.SUCCEEDED
            self._persist()
            return True
        return False

    def _restart_role(self, role_name: str):
        """Stop + relaunch every vertex of the role (gang restart, the
        reference's per-role failover)."""
        logger.info("restarting role %s (gang)", role_name)
        for vertex in self.graph.by_role(role_name):
            handle = self._handles.get(vertex.name)
            if handle is not None:
                self.backend.stop_worker(handle)
            self._launch(vertex)

    def _job_failover(self) -> bool:
        role_budget = max(r.max_restarts for r in self.config.roles)
        if self._job_restarts >= role_budget:
            logger.error("job-level restarts exhausted; failing")
            self._fail()
            return True
        self._job_restarts += 1
        logger.warning(
            "job-level failover #%d: restarting all roles",
            self._job_restarts,
        )
        for handle in self._handles.values():
            self.backend.stop_worker(handle)
        for vertex in self.graph.vertices:
            self._launch(vertex)
        self._persist()
        return False

    def _fail(self):
        self.stage = JobStage.FAILED
        self._persist()
        self._stop_all()

    def _vertex_of(self, name: str):
        return self._handles[name].vertex

    # ---- stop ---------------------------------------------------------------

    def stop(self):
        self._stopped.set()
        with self._lock:
            if self.stage == JobStage.RUNNING:
                self.stage = JobStage.STOPPING
            self._stop_all()
            if self.stage == JobStage.STOPPING:
                self.stage = JobStage.SUCCEEDED
            self._persist()

    def _stop_all(self):
        for handle in self._handles.values():
            try:
                self.backend.stop_worker(handle)
            except Exception:
                logger.warning("worker stop failed", exc_info=True)
