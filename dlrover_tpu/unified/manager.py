"""PrimeManager: the unified job's state machine + failover.

Parity: reference dlrover/python/unified/controller/manager.py:88-797
(PrimeManager: INIT/READY/RUNNING/STOPPING FSM; prepare -> schedule ->
create workers -> start; per-role SubMasters with check_child; job-level
failover; state persisted to a MasterStateBackend so a restarted manager
re-attaches to LIVE workers instead of killing the job).

Division of labor: each role's SubMaster (unified/submaster.py) owns
launch/supervision/gang-restart within its budget; the PrimeManager owns
scheduling (gang placement via unified/scheduler.py), job-level
failover, persistence, and terminal stages.
"""

import threading
import time
from typing import Dict, Optional

from dlrover_tpu.common.log import logger
from dlrover_tpu.unified.backend import Backend, create_backend
from dlrover_tpu.unified.config import DLJobConfig
from dlrover_tpu.unified.graph import ExecutionGraph, build_execution_graph
from dlrover_tpu.unified.scheduler import Placement, schedule
from dlrover_tpu.unified.state_backend import (
    MasterStateBackend,
    build_state_backend,
)
from dlrover_tpu.unified.submaster import (
    RoleStatus,
    SubMaster,
    create_submaster,
)


class JobStage:
    INIT = "INIT"
    READY = "READY"
    RUNNING = "RUNNING"
    STOPPING = "STOPPING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"


class PrimeManager:
    def __init__(
        self,
        config: DLJobConfig,
        backend: Optional[Backend] = None,
        state_backend: Optional[MasterStateBackend] = None,
        monitor_interval_s: float = 0.5,
        node_capacity: Optional[Dict[str, float]] = None,
    ):
        config.validate()
        self.config = config
        self.state_backend = state_backend or build_state_backend(
            config.master_state_path
        )
        self.graph: ExecutionGraph = build_execution_graph(config)
        self.placement: Placement = schedule(
            self.graph, config, node_capacity
        )
        # Backend selection AFTER scheduling so the Ray backend gets the
        # placement and can turn node slots into STRICT_PACK groups.
        self.backend = backend or create_backend(
            "auto", placement=self.placement
        )
        self.stage = JobStage.INIT
        self.submasters: Dict[str, SubMaster] = {
            role.name: create_submaster(
                role,
                self.graph.by_role(role.name),
                self.backend,
                config.job_name,
            )
            for role in config.roles
        }
        self._job_restarts = 0
        self._monitor_interval_s = monitor_interval_s
        self._stopped = threading.Event()
        self._lock = threading.Lock()
        self._restored_state = self.state_backend.load() or {}

    # ---- persistence --------------------------------------------------------

    def _persist(self):
        state = {
            "stage": self.stage,
            "role_restarts": {
                name: sm.restarts
                for name, sm in self.submasters.items()
            },
            "job_restarts": self._job_restarts,
            "workers": {
                name: sm.worker_records()
                for name, sm in self.submasters.items()
            },
        }
        # The supervision loop ticks twice a second; only actual state
        # changes hit the backend.
        if state != getattr(self, "_last_saved", None):
            self.state_backend.save(state)
            self._last_saved = state

    # ---- lifecycle ----------------------------------------------------------

    def prepare(self):
        """INIT -> READY (graph built, placement validated).

        Deliberately does NOT persist: overwriting a previous
        incarnation's RUNNING state with READY before re-attachment
        would lose the worker records a third incarnation needs if this
        one crashes mid-start."""
        if self.stage != JobStage.INIT:
            return
        # Publish the role -> world-size manifest so the in-worker data
        # plane's rpc_all (unified/rpc.py) can fan out before every
        # worker has registered.
        from dlrover_tpu.unified.rpc import write_manifest

        write_manifest(
            self.config.job_name,
            {r.name: r.total for r in self.config.roles},
            backend=self._registry_backend(),
        )
        self.stage = JobStage.READY

    def _registry_backend(self) -> str:
        """Which runtime-registry implementation this job's workers use
        (must match the UnifiedEnv.BACKEND the backend injects)."""
        from dlrover_tpu.unified.backend import RayBackend

        return "ray" if isinstance(self.backend, RayBackend) else "local"

    def start(self):
        """READY -> RUNNING.

        Self-failover: when the persisted state says a previous manager
        incarnation was RUNNING, adopt its live workers instead of
        launching doubles — the job survives a master restart without
        losing a single worker (reference manager self-failover from the
        state backend).
        """
        if self.stage not in (JobStage.INIT, JobStage.READY):
            raise RuntimeError(f"cannot start from stage {self.stage}")
        self.prepare()
        prev = self._restored_state
        resuming = prev.get("stage") == JobStage.RUNNING
        if not resuming:
            # Fresh start: drop stale data-plane registrations from any
            # previous run of this job name (live ones survive a
            # self-failover resume untouched).
            try:
                from dlrover_tpu.unified.rpc import create_registry

                create_registry(
                    self.config.job_name,
                    backend=self._registry_backend(),
                ).clear()
            except Exception:  # noqa: BLE001 - best-effort hygiene
                pass
        with self._lock:
            for name, sm in self.submasters.items():
                sm.restarts = prev.get("role_restarts", {}).get(name, 0)
                if resuming:
                    sm.reattach_or_launch(
                        prev.get("workers", {}).get(name, {})
                    )
                else:
                    sm.launch_all()
            self._job_restarts = prev.get("job_restarts", 0)
        self.stage = JobStage.RUNNING
        self._persist()
        logger.info(
            "unified job %s %s: %d workers across %d roles",
            self.config.job_name,
            "resumed" if resuming else "running",
            len(self.graph.vertices),
            len(self.config.roles),
        )

    # ---- supervision --------------------------------------------------------

    def wait(self, timeout: Optional[float] = None) -> str:
        """Supervise until the job finishes; returns the final stage."""
        deadline = time.time() + timeout if timeout else None
        while not self._stopped.is_set():
            if deadline and time.time() > deadline:
                break
            done = self._tick()
            if done:
                break
            time.sleep(self._monitor_interval_s)
        return self.stage

    def _tick(self) -> bool:
        with self._lock:
            statuses: Dict[str, Optional[str]] = {}
            for name, sm in self.submasters.items():
                statuses[name] = sm.check_children()
            failed = [
                n for n, s in statuses.items() if s == RoleStatus.FAILED
            ]
            if failed:
                if any(self.submasters[n].escalates_to_job for n in failed):
                    return self._job_failover()
                logger.error(
                    "roles %s failed beyond their budgets; failing job",
                    failed,
                )
                self._fail()
                return True
            self._persist()
            if all(s == RoleStatus.SUCCEEDED for s in statuses.values()):
                self.stage = JobStage.SUCCEEDED
                self._persist()
                return True
            return False

    def _job_failover(self) -> bool:
        role_budget = max(r.max_restarts for r in self.config.roles)
        if self._job_restarts >= role_budget:
            logger.error("job-level restarts exhausted; failing")
            self._fail()
            return True
        self._job_restarts += 1
        logger.warning(
            "job-level failover #%d: restarting all roles",
            self._job_restarts,
        )
        for sm in self.submasters.values():
            sm.gang_restart()
        self._persist()
        return False

    def _fail(self):
        self.stage = JobStage.FAILED
        self._persist()
        self._stop_all()

    # ---- stop ---------------------------------------------------------------

    def stop(self):
        self._stopped.set()
        with self._lock:
            if self.stage == JobStage.RUNNING:
                self.stage = JobStage.STOPPING
            self._stop_all()
            if self.stage == JobStage.STOPPING:
                self.stage = JobStage.SUCCEEDED
            self._persist()

    def _stop_all(self):
        for sm in self.submasters.values():
            sm.stop_all()
