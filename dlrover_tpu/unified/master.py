"""PrimeMaster facade + submit().

Parity: reference dlrover/python/unified/controller/master.py (PrimeMaster
detached actor; status/stop/wait RPC) and driver/main.py:24-74
(submit(JobConfig)). Locally the master is an in-process object whose
manager supervises subprocess workers; a Ray deployment wraps the same
PrimeManager in a detached actor.
"""

import threading
from typing import Optional

from dlrover_tpu.common.log import logger
from dlrover_tpu.unified.config import DLJobConfig
from dlrover_tpu.unified.manager import JobStage, PrimeManager


class PrimeMaster:
    def __init__(self, config: DLJobConfig, backend=None, state_backend=None):
        self._manager = PrimeManager(
            config, backend=backend, state_backend=state_backend
        )
        self._wait_thread: Optional[threading.Thread] = None

    @classmethod
    def create(cls, config: DLJobConfig, **kwargs) -> "PrimeMaster":
        return cls(config, **kwargs)

    def start(self):
        self._manager.start()
        self._wait_thread = threading.Thread(
            target=self._manager.wait, name="prime-wait", daemon=True
        )
        self._wait_thread.start()

    def status(self) -> str:
        return self._manager.stage

    def wait(self, timeout: Optional[float] = None) -> str:
        if self._wait_thread is not None:
            self._wait_thread.join(timeout)
        return self._manager.stage

    def stop(self):
        self._manager.stop()


def submit(
    config: DLJobConfig, blocking: bool = True, **kwargs
) -> PrimeMaster:
    """Run a unified job (reference driver.main submit())."""
    master = PrimeMaster.create(config, **kwargs)
    master.start()
    if blocking:
        stage = master.wait()
        logger.info("unified job %s finished: %s", config.job_name, stage)
        if stage != JobStage.SUCCEEDED:
            raise RuntimeError(f"job {config.job_name} ended in {stage}")
    return master
