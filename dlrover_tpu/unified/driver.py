"""Unified job driver CLI.

Parity: reference dlrover/python/unified/driver/main.py:58 — submit a job
described as JSON:

    python -m dlrover_tpu.unified.driver job.json

JSON shape mirrors DLJobConfig::

    {"job_name": "demo", "node_num": 1,
     "roles": [{"name": "trainer", "entrypoint": "my.module",
                "total": 2, "per_group": 1, "envs": {}, "args": []}],
     "collocations": [["trainer"]]}
"""

import json
import sys

from dlrover_tpu.common.log import logger
from dlrover_tpu.unified.config import DLJobConfig, RoleConfig
from dlrover_tpu.unified.manager import JobStage
from dlrover_tpu.unified.master import submit


def config_from_json(payload: dict) -> DLJobConfig:
    roles = [RoleConfig(**r) for r in payload.get("roles", [])]
    return DLJobConfig(
        job_name=payload.get("job_name", "unified-job"),
        roles=roles,
        collocations=payload.get("collocations", []),
        node_num=payload.get("node_num", 1),
        global_envs=payload.get("global_envs", {}),
        master_state_path=payload.get("master_state_path", ""),
    )


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 1:
        print(__doc__)
        return 2
    with open(argv[0]) as f:
        config = config_from_json(json.load(f))
    try:
        master = submit(config)
    except RuntimeError as e:
        logger.error("%s", e)
        return 1
    return 0 if master.status() == JobStage.SUCCEEDED else 1


if __name__ == "__main__":
    sys.exit(main())
