"""Gang/collocation placement: bundles -> node slots.

Parity: reference dlrover/python/unified/controller/schedule/scheduler.py
(placement-group creation with STRICT_PACK bundles). A bundle is the
unit of collocation — every vertex of a bundle (same collocation group,
same group index) must land on ONE node slot together; bundles spread
round-robin across the job's nodes. The scheduler validates feasibility
(per-node capacity in bundle slots and resources) BEFORE anything
launches, so an impossible collocation fails fast instead of
deadlocking half-scheduled (the Ray backend turns each slot into a
placement group; the local backend uses the assignment for env wiring
and capacity accounting).
"""

from dataclasses import dataclass, field
from typing import Dict, List

from dlrover_tpu.common.log import logger
from dlrover_tpu.unified.config import DLJobConfig
from dlrover_tpu.unified.graph import ExecutionGraph


@dataclass
class NodeSlot:
    index: int
    bundles: List[int] = field(default_factory=list)
    resource: Dict[str, float] = field(default_factory=dict)
    # Per-bundle aggregate resource (what a Ray placement-group bundle
    # must reserve for the collocated workers it packs).
    bundle_resources: Dict[int, Dict[str, float]] = field(
        default_factory=dict
    )


@dataclass
class Placement:
    slots: List[NodeSlot]
    bundle_to_slot: Dict[int, int]

    def slot_of(self, bundle_id: int) -> int:
        return self.bundle_to_slot[bundle_id]


def _bundle_resource(graph: ExecutionGraph, config, bundle_id) -> Dict:
    total: Dict[str, float] = {}
    for vertex in graph.bundles[bundle_id]:
        role = config.role(vertex.role)
        for key, val in role.resource.items():
            total[key] = total.get(key, 0.0) + val
    return total


def schedule(
    graph: ExecutionGraph,
    config: DLJobConfig,
    node_capacity: Dict[str, float] = None,
) -> Placement:
    """Assign every bundle to a node slot (STRICT_PACK) and stamp each
    vertex's ``node_slot``. Raises ValueError when the job cannot fit.

    ``node_capacity``: per-node resource limits (e.g. {"tpu_chips": 4});
    omitted keys are unconstrained.
    """
    node_capacity = node_capacity or {}
    n_nodes = max(config.node_num, 1)
    bundle_ids = sorted(graph.bundles)

    def fits(slot: NodeSlot, need: Dict[str, float]) -> bool:
        return all(
            slot.resource.get(key, 0.0) + need.get(key, 0.0) <= limit
            for key, limit in node_capacity.items()
        )

    slots = [NodeSlot(index=i) for i in range(n_nodes)]
    bundle_to_slot: Dict[int, int] = {}
    # First-fit-DECREASING with balance preference: big bundles place
    # first (small ones spread across nodes first would strand the big
    # one), each into the emptiest slot that fits.
    needs = {
        bundle_id: _bundle_resource(graph, config, bundle_id)
        for bundle_id in bundle_ids
    }

    def constrained_need(bundle_id: int) -> float:
        need = needs[bundle_id]
        if not node_capacity:
            return sum(need.values())
        return sum(need.get(key, 0.0) for key in node_capacity)

    for bundle_id in sorted(
        bundle_ids, key=lambda b: (-constrained_need(b), b)
    ):
        need = needs[bundle_id]
        slot = next(
            (
                s
                for s in sorted(slots, key=lambda s: len(s.bundles))
                if fits(s, need)
            ),
            None,
        )
        if slot is None:
            raise ValueError(
                f"bundle {bundle_id} needs {need} but no node slot has "
                f"capacity (per-node {node_capacity}, {n_nodes} nodes) "
                f"— reduce collocation or add nodes"
            )
        for key, val in need.items():
            slot.resource[key] = slot.resource.get(key, 0.0) + val
        slot.bundle_resources[bundle_id] = need
        slot.bundles.append(bundle_id)
        bundle_to_slot[bundle_id] = slot.index
        for vertex in graph.bundles[bundle_id]:
            vertex.node_slot = slot.index

    logger.info(
        "scheduled %d bundles onto %d node slots: %s",
        len(bundle_ids),
        n_nodes,
        {s.index: s.bundles for s in slots if s.bundles},
    )
    return Placement(slots=slots, bundle_to_slot=bundle_to_slot)
