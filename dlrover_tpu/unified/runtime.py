"""In-worker runtime helpers for unified jobs.

Parity: reference dlrover/python/unified/api/runtime
(current_worker() etc.) — a worker launched by the unified backend reads
its role coordinates from the injected env.
"""

import os
from dataclasses import dataclass

from dlrover_tpu.unified.backend import UnifiedEnv


@dataclass(frozen=True)
class WorkerInfo:
    job_name: str
    role: str
    rank: int
    world_size: int
    group_index: int
    bundle_id: int

    @property
    def is_leader(self) -> bool:
        return self.rank == 0


def current_worker() -> WorkerInfo:
    """Coordinates of this process within its unified job (all defaults
    when run outside one)."""
    return WorkerInfo(
        job_name=os.getenv(UnifiedEnv.JOB_NAME, ""),
        role=os.getenv(UnifiedEnv.ROLE, ""),
        rank=int(os.getenv(UnifiedEnv.ROLE_RANK, "0")),
        world_size=int(os.getenv(UnifiedEnv.ROLE_WORLD_SIZE, "1")),
        group_index=int(os.getenv(UnifiedEnv.GROUP_INDEX, "0")),
        bundle_id=int(os.getenv(UnifiedEnv.BUNDLE_ID, "-1")),
    )
