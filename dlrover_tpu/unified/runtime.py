"""In-worker runtime API for unified jobs.

Parity: reference dlrover/python/unified/api/runtime — current_worker()
coordinates, rpc_helper (export_rpc / rpc / rpc_all) and data queues
(create_queue / get_queue) so collocated roles (e.g. rollout -> reward
-> actor in an RL job) exchange real tensors through a sanctioned
channel instead of the filesystem. Transport + registry live in
unified/rpc.py and work on both the local-process and Ray backends.

Usage, in worker code::

    from dlrover_tpu.unified import runtime

    me = runtime.current_worker()
    runtime.export_rpc("update_weights", lambda w: apply(w))
    q = runtime.create_queue("rollouts")        # owner side
    ...
    q = runtime.get_queue("rollouts")           # consumer side
    batch = q.get()
    runtime.rpc("actor", "update_weights", weights, rank=0)
    losses = runtime.rpc_all("actor", "train_step", batch)
"""

import os
import threading
from dataclasses import dataclass
from typing import Optional

from dlrover_tpu.unified.backend import UnifiedEnv


@dataclass(frozen=True)
class WorkerInfo:
    job_name: str
    role: str
    rank: int
    world_size: int
    group_index: int
    bundle_id: int

    @property
    def is_leader(self) -> bool:
        return self.rank == 0


def current_worker() -> WorkerInfo:
    """Coordinates of this process within its unified job (all defaults
    when run outside one)."""
    return WorkerInfo(
        job_name=os.getenv(UnifiedEnv.JOB_NAME, ""),
        role=os.getenv(UnifiedEnv.ROLE, ""),
        rank=int(os.getenv(UnifiedEnv.ROLE_RANK, "0")),
        world_size=int(os.getenv(UnifiedEnv.ROLE_WORLD_SIZE, "1")),
        group_index=int(os.getenv(UnifiedEnv.GROUP_INDEX, "0")),
        bundle_id=int(os.getenv(UnifiedEnv.BUNDLE_ID, "-1")),
    )


# ---------------------------------------------------------------------------
# Process-level data plane (lazy: nothing binds until first use)
# ---------------------------------------------------------------------------

_state_lock = threading.Lock()
_endpoint = None
_client = None


def _ensure_endpoint():
    """Start this worker's TCP endpoint and register it (role, rank) in
    the job registry on first use."""
    global _endpoint
    with _state_lock:
        if _endpoint is None:
            from dlrover_tpu.unified.rpc import (
                WorkerEndpoint,
                create_registry,
            )

            info = current_worker()
            host = os.getenv("DLROVER_TPU_RUNTIME_HOST")
            advertise = None
            if host is None:
                if os.getenv(UnifiedEnv.BACKEND) == "ray":
                    # Cross-node job: bind everywhere, advertise this
                    # node's routable IP in the cluster-wide registry.
                    host = "0.0.0.0"
                    advertise = _node_ip()
                else:
                    host = "127.0.0.1"
            _endpoint = WorkerEndpoint(host=host, advertise_host=advertise)
            create_registry(info.job_name).register_worker(
                info.role, info.rank, _endpoint.addr
            )
        return _endpoint


def _node_ip() -> str:
    try:
        import ray

        return ray.util.get_node_ip_address()
    except Exception:  # noqa: BLE001 - fall back to hostname routing
        import socket

        return socket.gethostbyname(socket.gethostname())


def _ensure_client():
    global _client
    with _state_lock:
        if _client is None:
            from dlrover_tpu.unified.rpc import RuntimeClient

            _client = RuntimeClient(current_worker().job_name)
        return _client


def export_rpc(name: str, fn):
    """Expose ``fn`` to other workers as request/reply method ``name``
    (reference rpc_helper.export_rpc_method)."""
    _ensure_endpoint().export(name, fn)


def rpc(role: str, method: str, *args, rank: int = 0,
        timeout: float = 60.0, **kwargs):
    """Call ``method`` on worker (role, rank); returns its result or
    raises RpcError (reference rpc_helper.rpc_call)."""
    return _ensure_client().rpc(
        role, method, *args, rank=rank, timeout=timeout, **kwargs
    )


def rpc_all(role: str, method: str, *args, timeout: float = 60.0,
            **kwargs):
    """Call ``method`` on EVERY rank of ``role``; results in rank order
    (reference util/actor_helper batch invocation)."""
    return _ensure_client().rpc_all(
        role, method, *args, timeout=timeout, **kwargs
    )


def create_queue(name: str, maxsize: int = 0):
    """Create (and own) named queue ``name`` on this worker, register
    it job-wide, and return a handle to it."""
    ep = _ensure_endpoint()
    ep.create_queue(name, maxsize=maxsize)
    info = current_worker()
    from dlrover_tpu.unified.rpc import create_registry

    create_registry(info.job_name).register_queue(name, ep.addr)
    return get_queue(name)


def get_queue(name: str):
    """Handle to a queue another worker created (blocks briefly until
    the owner registers it)."""
    return _ensure_client().queue(name)


def reset(close: bool = True):
    """Tear down this process's endpoint/client (tests; forked
    workers)."""
    global _endpoint, _client
    with _state_lock:
        if close and _endpoint is not None:
            _endpoint.close()
        if close and _client is not None:
            _client.close()
        _endpoint = None
        _client = None
