"""In-cluster job-submission service: accept jobs over HTTP.

Parity: the receiving end of the reference's out-of-cluster submission
path (dlrover/python/client/platform/ray/ray_job_submitter.py:1-185
submits to Ray's job server; here the cluster entry is this small
token-authenticated HTTP service, typically run next to the operator or
on the head node):

    python -m dlrover_tpu.unified.submission --port 8910

Endpoints (JSON in/out, ``X-Submit-Token`` header required):

- ``POST /api/v1/jobs``           body = DLJobConfig JSON (the same
  shape ``unified/driver.py`` reads from a file) -> ``{"job_name"}``
- ``GET  /api/v1/jobs``           -> ``{"jobs": {name: stage}}``
- ``GET  /api/v1/jobs/<name>``    -> ``{"job_name", "stage", "error"}``
- ``POST /api/v1/jobs/<name>/stop`` -> ``{"job_name", "stage"}``

Each accepted job runs through :func:`unified.master.submit`
(non-blocking) — the same PrimeManager path the in-cluster driver uses.
The client side lives in :mod:`dlrover_tpu.client`.
"""

import argparse
import hmac
import json
import os
import secrets
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from dlrover_tpu.common.log import logger

SUBMIT_TOKEN_ENV = "DLROVER_TPU_SUBMIT_TOKEN"
_MAX_BODY = 4 << 20  # a job config, not a dataset


class _JobRecord:
    def __init__(self, master=None):
        self.master = master  # None while submit() is still starting it
        self.error = ""

    def stage(self) -> str:
        if self.master is None:
            return "INIT" if not self.error else "FAILED"
        try:
            stage = self.master.status()
        except Exception as e:  # noqa: BLE001 - status must not 500
            return f"UNKNOWN({type(e).__name__}: {e})"
        if stage == "FAILED" and not self.error:
            self.error = "job ended in FAILED (see master/worker logs)"
        return stage


class SubmissionServer:
    """Threaded HTTP server owning the submitted jobs' PrimeMasters."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 token: Optional[str] = None):
        self._token = (
            token or os.getenv(SUBMIT_TOKEN_ENV) or secrets.token_hex(16)
        )
        self._jobs: Dict[str, _JobRecord] = {}
        self._lock = threading.Lock()
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                logger.debug("submission: " + fmt, *args)

            def _reply(self, code: int, payload: dict):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _authorized(self) -> bool:
                got = self.headers.get("X-Submit-Token", "")
                return hmac.compare_digest(got, server._token)

            def do_GET(self):
                if not self._authorized():
                    return self._reply(403, {"error": "bad token"})
                parts = self.path.strip("/").split("/")
                if parts[:3] == ["api", "v1", "jobs"]:
                    if len(parts) == 3:
                        return self._reply(200, {"jobs": server.jobs()})
                    rec = server.job(parts[3])
                    if rec is None:
                        return self._reply(
                            404, {"error": f"no job {parts[3]!r}"}
                        )
                    return self._reply(200, {
                        "job_name": parts[3],
                        "stage": rec.stage(),
                        "error": rec.error,
                    })
                return self._reply(404, {"error": "unknown path"})

            def do_POST(self):
                if not self._authorized():
                    return self._reply(403, {"error": "bad token"})
                parts = self.path.strip("/").split("/")
                if parts[:3] != ["api", "v1", "jobs"]:
                    return self._reply(404, {"error": "unknown path"})
                if len(parts) == 5 and parts[4] == "stop":
                    rec = server.job(parts[3])
                    if rec is None:
                        return self._reply(
                            404, {"error": f"no job {parts[3]!r}"}
                        )
                    if rec.master is None:
                        return self._reply(409, {
                            "error": f"job {parts[3]!r} still starting",
                        })
                    rec.master.stop()
                    return self._reply(200, {
                        "job_name": parts[3], "stage": rec.stage(),
                    })
                if len(parts) != 3:
                    return self._reply(404, {"error": "unknown path"})
                size = int(self.headers.get("Content-Length", "0"))
                if size > _MAX_BODY:
                    return self._reply(413, {"error": "config too large"})
                try:
                    payload = json.loads(self.rfile.read(size))
                except (ValueError, OSError) as e:
                    return self._reply(
                        400, {"error": f"bad JSON: {e}"}
                    )
                try:
                    name = server.submit(payload)
                except Exception as e:  # noqa: BLE001 - surface to caller
                    return self._reply(400, {
                        "error": f"{type(e).__name__}: {e}",
                    })
                return self._reply(200, {"job_name": name})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="dlrover-tpu-submission",
        )
        self._thread.start()
        self.port = self._httpd.server_address[1]
        self.addr = f"{host}:{self.port}"
        logger.info("submission service on %s", self.addr)

    @property
    def token(self) -> str:
        return self._token

    # ---- job registry -----------------------------------------------------

    def submit(self, payload: dict) -> str:
        from dlrover_tpu.unified.driver import config_from_json
        from dlrover_tpu.unified.manager import JobStage
        from dlrover_tpu.unified.master import submit as run_job

        config = config_from_json(payload)
        config.validate()
        # Reserve the name under the lock, start the job OUTSIDE it —
        # master startup can take seconds and must not block concurrent
        # status/list/stop requests.
        rec = _JobRecord()
        with self._lock:
            existing = self._jobs.get(config.job_name)
            if existing is not None and existing.stage() not in (
                JobStage.SUCCEEDED, JobStage.FAILED,
            ):
                raise ValueError(
                    f"job {config.job_name!r} is already running"
                )
            self._jobs[config.job_name] = rec
        try:
            rec.master = run_job(config, blocking=False)
        except Exception as e:
            rec.error = f"{type(e).__name__}: {e}"
            raise
        logger.info("accepted job %s", config.job_name)
        return config.job_name

    def jobs(self) -> Dict[str, str]:
        with self._lock:
            return {n: r.stage() for n, r in self._jobs.items()}

    def job(self, name: str) -> Optional[_JobRecord]:
        with self._lock:
            return self._jobs.get(name)

    def close(self):
        with self._lock:
            jobs = list(self._jobs.values())
        for rec in jobs:
            try:
                if rec.master is not None:
                    rec.master.stop()
            except Exception:  # noqa: BLE001 - best-effort teardown
                pass
        self._httpd.shutdown()
        self._httpd.server_close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=8910)
    ns = ap.parse_args(argv)
    server = SubmissionServer(host=ns.host, port=ns.port)
    if not os.getenv(SUBMIT_TOKEN_ENV):
        logger.info("generated submit token: %s", server.token)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
