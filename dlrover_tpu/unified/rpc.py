"""In-worker runtime data plane for unified jobs: actor RPC + queues.

Parity: reference dlrover/python/unified/api/runtime/rpc_helper.py
(export_rpc_method / rpc_call), api/runtime/queue.py (named data queues
shipping rollouts between collocated roles), and util/actor_helper.py
(batch invocation over a role). The reference rides Ray actor handles;
here the transport is a tiny length-prefixed-pickle TCP endpoint every
worker can open, so the SAME API works on both backends:

- **endpoint**: each worker process lazily starts one threaded TCP
  server (port 0). RPC methods exported with :func:`export_rpc` and
  queues created with :func:`create_queue` live on it.
- **registry**: maps (role, rank) -> "host:port" and queue name ->
  owner address. Local backend: atomic JSON files in a job-derived
  runtime dir (same-host processes). Ray backend: a named detached
  registry actor (cluster-wide).
- **client**: :func:`rpc` (role/rank-addressed request/reply),
  :func:`rpc_all` (fan-out to every rank of a role, gathered with a
  thread pool — the actor_helper batch analogue), :func:`get_queue`
  (put/get against the owning worker's endpoint).

Payloads are pickled — numpy arrays (and anything picklable) ship
as-is; device arrays should be pulled to host first (np.asarray).

**Trust boundary**: pickle executes code on load, so every connection
must prove job membership BEFORE its first frame is parsed. On accept
the server sends a fresh random nonce; the client answers with
HMAC(sha256(token), nonce). The server verifies in constant time and
drops the connection on mismatch — nothing attacker-controlled ever
reaches ``pickle.loads``, the secret never crosses the wire, and a
captured handshake cannot be replayed (the MAC is bound to a dead
nonce). Payload frames after the handshake are not otherwise
integrity-protected: the threat model is job-membership gating inside
a cluster network, not a hostile man-in-the-middle (use an encrypted
overlay for that). The secret
is the manager-injected DLROVER_TPU_RUNTIME_TOKEN env (the manager
generates one per job, unified/backend.worker_envs), falling back to a
0600 token file in the job runtime dir for same-host/standalone use —
the same bearer-secret scheme as flash_ckpt/replica.py.
"""

import hashlib
import hmac
import io
import json
import os
import pickle
import queue as queue_mod
import secrets
import socket
import socketserver
import tempfile
import threading
import time
import traceback
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional

from dlrover_tpu.common.log import logger

# Per-frame cap: big enough for rollout tensor batches, small enough
# that a garbage length prefix cannot OOM the worker. Override with
# DLROVER_TPU_RUNTIME_MAX_MSG (bytes) for jobs shipping larger blobs.
_MAX_MSG = int(os.getenv("DLROVER_TPU_RUNTIME_MAX_MSG", str(256 << 20)))

RUNTIME_TOKEN_ENV = "DLROVER_TPU_RUNTIME_TOKEN"
_AUTH_MAGIC = b"DTRT2"
_NONCE_LEN = 16
_AUTH_CHALLENGE_LEN = len(_AUTH_MAGIC) + _NONCE_LEN
_AUTH_REPLY_LEN = len(_AUTH_MAGIC) + hashlib.sha256().digest_size


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def _require_private(path: str, what: str):
    """Refuse to trust a token dir/file another uid owns (or that other
    uids can read/replace) — a hostile local user pre-planting the
    predictable tmp path would otherwise hold the job secret (and with
    it the pickle endpoint). Squatting turns into a loud failure, not
    silent secret sharing. Files must be unreadable by others (they
    hold the secret); for the dir only foreign WRITE access matters
    (registry JSON lives there too and may be world-readable)."""
    st = os.stat(path)
    bad_bits = 0o022 if what == "dir" else 0o077
    if st.st_uid != os.getuid() or (st.st_mode & bad_bits):
        raise RuntimeError(
            f"runtime token {what} {path} is not private to uid "
            f"{os.getuid()} (owner {st.st_uid}, mode "
            f"{oct(st.st_mode & 0o777)}) — refusing to use it; remove "
            f"it or set {RUNTIME_TOKEN_ENV}"
        )


def resolve_runtime_token(job_name: str, create: bool = True) -> str:
    """Per-job shared secret for the runtime data plane.

    Order: operator/manager-injected env (works cross-node under Ray),
    then a 0600 owner-checked token file in the job runtime dir
    (same-host processes; atomically created by whoever gets there
    first). The env token only applies to this process's OWN job — a
    caller explicitly naming a different job (cross-job clients) gets
    that job's file token, not ours. ``create=False`` raises instead of
    minting a file token."""
    token = os.getenv(RUNTIME_TOKEN_ENV, "")
    if token:
        from dlrover_tpu.unified.backend import UnifiedEnv

        own_job = os.getenv(UnifiedEnv.JOB_NAME, job_name)
        if not job_name or job_name == own_job:
            return token
    path = os.path.join(runtime_dir(job_name), "token")
    for _ in range(100):
        try:
            with open(path) as f:
                token = f.read().strip()
            if token:
                _require_private(path, "file")
                return token
            time.sleep(0.01)  # creator mid-write (link happens after
            continue          # the write, so this is near-impossible)
        except OSError:
            break
    if not create:
        raise RuntimeError(
            f"no runtime token: set {RUNTIME_TOKEN_ENV} or start the "
            "job through a unified manager"
        )
    os.makedirs(runtime_dir(job_name), mode=0o700, exist_ok=True)
    _require_private(runtime_dir(job_name), "dir")
    token = secrets.token_hex(16)
    tmp = path + f".tmp{os.getpid()}"
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "w") as f:
        f.write(token)
    try:
        os.link(tmp, path)  # atomic publish: first creator wins
    except FileExistsError:
        with open(path) as f:
            token = f.read().strip()
        _require_private(path, "file")
    finally:
        os.unlink(tmp)
    return token


def _token_digest(token: str) -> bytes:
    return hashlib.sha256(token.encode()).digest()


# ---------------------------------------------------------------------------
# Wire protocol: auth preamble on connect, then 8-byte length + pickle
# ---------------------------------------------------------------------------


def _send(sock: socket.socket, obj: Any):
    buf = io.BytesIO()
    pickle.dump(obj, buf, protocol=pickle.HIGHEST_PROTOCOL)
    data = buf.getvalue()
    if len(data) > _MAX_MSG:
        # Enforced before any byte hits the wire so the peer never sees
        # a half-frame; the receiver enforces the same cap on garbage
        # length prefixes.
        raise _FrameTooLarge(len(data))
    sock.sendall(len(data).to_bytes(8, "big") + data)


class _FrameTooLarge(ValueError):
    """Oversized frame; carries the claimed size so the server can
    drain the body before replying with the reason."""

    def __init__(self, size: int):
        super().__init__(
            f"frame of {size} bytes exceeds the {_MAX_MSG}-byte cap — "
            "raise DLROVER_TPU_RUNTIME_MAX_MSG on both ends for jobs "
            "shipping larger payloads"
        )
        self.size = size


def _recv(sock: socket.socket) -> Any:
    hdr = b""
    while len(hdr) < 8:
        chunk = sock.recv(8 - len(hdr))
        if not chunk:
            raise ConnectionError("peer closed")
        hdr += chunk
    size = int.from_bytes(hdr, "big")
    if size > _MAX_MSG:
        raise _FrameTooLarge(size)
    parts, got = [], 0
    while got < size:
        chunk = sock.recv(min(1 << 20, size - got))
        if not chunk:
            raise ConnectionError("peer closed mid-message")
        parts.append(chunk)
        got += len(chunk)
    return pickle.loads(b"".join(parts))


# ---------------------------------------------------------------------------
# Worker endpoint (server side)
# ---------------------------------------------------------------------------


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        endpoint: "WorkerEndpoint" = self.server.endpoint  # type: ignore
        if not endpoint.authenticate(self.request):
            # No frame was parsed; close without a reply so the peer
            # learns nothing (parity with replica.py's 403-before-body).
            return
        endpoint.track(self.request)
        try:
            while True:
                try:
                    req = _recv(self.request)
                except _FrameTooLarge as e:
                    # Oversized request: drain the in-flight body first
                    # (otherwise the sender is still mid-sendall and
                    # sees a reset instead of our reply), then reply
                    # with the reason and drop the connection.
                    self._drain(e.size)
                    try:
                        _send(self.request, {"ok": False,
                                             "error": str(e)})
                    except OSError:
                        pass
                    break
                rsp = endpoint.dispatch(req)
                try:
                    # _send serializes fully before any byte hits the
                    # wire, so a pickling failure (or an over-cap
                    # reply) leaves the stream clean — report it
                    # instead of killing the connection (which would
                    # push the client into its reconnect-and-re-execute
                    # path; for non-idempotent methods or queue gets
                    # that means double execution / lost items).
                    _send(self.request, rsp)
                except (pickle.PicklingError, TypeError,
                        AttributeError, ValueError) as e:
                    _send(self.request, {
                        "ok": False,
                        "error": f"unsendable reply: "
                                 f"{type(e).__name__}: {e}",
                    })
        except (ConnectionError, OSError):
            pass
        finally:
            endpoint.untrack(self.request)

    def _drain(self, size: int):
        """Discard the in-flight body bytes so the sender's sendall
        completes and our error reply lands (instead of a reset). Time-
        bounded: a legit cap-mismatched frame drains at wire speed in
        seconds, while a hostile length prefix trickled slowly cannot
        pin this thread past the deadline."""
        left = size
        deadline = time.time() + 30.0
        try:
            while left > 0 and time.time() < deadline:
                self.request.settimeout(10.0)
                chunk = self.request.recv(min(1 << 20, left))
                if not chunk:
                    return
                left -= len(chunk)
            self.request.settimeout(None)
        except OSError:
            pass


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class WorkerEndpoint:
    """One per worker process: serves exported RPC methods and owned
    queues over TCP."""

    def __init__(self, host: str = "127.0.0.1",
                 advertise_host: Optional[str] = None,
                 token: Optional[str] = None,
                 job_name: Optional[str] = None):
        """``host`` is the bind address; ``advertise_host`` (default:
        host) is what goes into the registry — bind 0.0.0.0 and
        advertise the node IP for cross-node (Ray) jobs. ``token`` is
        the job secret every connection must present (default: resolved
        from env/token-file for ``job_name``, itself defaulting to this
        process's job env — pass one or the other when constructing an
        endpoint for a job you are not a worker of, or clients
        resolving the token for that job will never match)."""
        if token is None:
            if job_name is None:
                from dlrover_tpu.unified.backend import UnifiedEnv

                job_name = os.getenv(UnifiedEnv.JOB_NAME, "")
            token = resolve_runtime_token(job_name)
        self._digest = _token_digest(token)
        self._methods: Dict[str, Callable] = {}
        self._queues: Dict[str, queue_mod.Queue] = {}
        self._lock = threading.Lock()
        self._live_conns: set = set()
        self._server = _Server((host, 0), _Handler)
        self._server.endpoint = self  # type: ignore
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="dlrover-tpu-worker-endpoint",
        )
        self._thread.start()
        port = self._server.server_address[1]
        self.addr = f"{advertise_host or host}:{port}"

    def export(self, name: str, fn: Callable):
        with self._lock:
            self._methods[name] = fn

    def create_queue(self, name: str, maxsize: int = 0):
        with self._lock:
            if name not in self._queues:
                self._queues[name] = queue_mod.Queue(maxsize=maxsize)
            return self._queues[name]

    def dispatch(self, req: dict) -> dict:
        try:
            kind = req.get("kind")
            if kind == "rpc":
                fn = self._methods.get(req["method"])
                if fn is None:
                    return {
                        "ok": False,
                        "error": f"no rpc method {req['method']!r}; "
                        f"exported: {sorted(self._methods)}",
                    }
                value = fn(*req.get("args", ()), **req.get("kwargs", {}))
                return {"ok": True, "value": value}
            if kind == "qput":
                q = self._queues.get(req["queue"])
                if q is None:
                    return {"ok": False, "error": "no such queue"}
                try:
                    q.put(req["item"], timeout=req.get("timeout"))
                    return {"ok": True}
                except queue_mod.Full:
                    return {"ok": False, "error": "queue full"}
            if kind == "qget":
                q = self._queues.get(req["queue"])
                if q is None:
                    return {"ok": False, "error": "no such queue"}
                try:
                    item = q.get(timeout=req.get("timeout"))
                    return {"ok": True, "value": item}
                except queue_mod.Empty:
                    return {"ok": False, "error": "queue empty"}
            if kind == "qsize":
                q = self._queues.get(req["queue"])
                if q is None:
                    return {"ok": False, "error": "no such queue"}
                return {"ok": True, "value": q.qsize()}
            return {"ok": False, "error": f"unknown kind {kind!r}"}
        except Exception as e:  # noqa: BLE001 - serve the error to caller
            return {
                "ok": False,
                "error": f"{type(e).__name__}: {e}",
                "traceback": traceback.format_exc(),
            }

    def authenticate(self, sock: socket.socket) -> bool:
        """Challenge-response handshake, BEFORE any pickle byte is
        parsed: the server sends a fresh random nonce; the client
        proves job membership with HMAC(sha256(token), nonce). A
        passive observer of an earlier connection captures only a MAC
        bound to a dead nonce — replaying it fails (advisor r4: the
        previous static sha256(token) preamble was replayable). False
        closes the connection."""
        try:
            sock.settimeout(10.0)
            nonce = secrets.token_bytes(_NONCE_LEN)
            sock.sendall(_AUTH_MAGIC + nonce)
            buf = _recv_exact(sock, _AUTH_REPLY_LEN)
            if buf is None:
                return False
            sock.settimeout(None)
        except OSError:
            return False
        magic, mac = buf[: len(_AUTH_MAGIC)], buf[len(_AUTH_MAGIC):]
        expect = hmac.new(self._digest, nonce, hashlib.sha256).digest()
        if magic != _AUTH_MAGIC or not hmac.compare_digest(mac, expect):
            try:
                peer = sock.getpeername()
            except OSError:
                peer = "?"
            logger.warning(
                "runtime endpoint: rejected unauthenticated peer %s",
                peer,
            )
            return False
        return True

    def track(self, sock: socket.socket):
        with self._lock:
            self._live_conns.add(sock)

    def untrack(self, sock: socket.socket):
        with self._lock:
            self._live_conns.discard(sock)

    def close(self):
        try:
            self._server.shutdown()
            self._server.server_close()
        except OSError:
            pass
        # Sever live connections too — handler threads otherwise keep
        # answering on them after shutdown(), which would make a stale
        # client think a restarted worker never moved.
        with self._lock:
            conns = list(self._live_conns)
        for sock in conns:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------


def runtime_dir(job_name: str) -> str:
    """Job-derived registry dir — manager and workers compute the same
    path with no plumbing. Override with DLROVER_TPU_RUNTIME_DIR."""
    env = os.getenv("DLROVER_TPU_RUNTIME_DIR")
    if env:
        return env
    return os.path.join(
        tempfile.gettempdir(), f"dlrover_tpu_rt_{job_name}"
    )


class FileRegistry:
    """Atomic-JSON-file registry for same-host (local backend) jobs."""

    def __init__(self, job_name: str):
        self.dir = runtime_dir(job_name)
        os.makedirs(self.dir, exist_ok=True)

    def _write(self, key: str, value: dict):
        path = os.path.join(self.dir, key + ".json")
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(value, f)
        os.replace(tmp, path)

    def _read(self, key: str) -> Optional[dict]:
        try:
            with open(os.path.join(self.dir, key + ".json")) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def register_worker(self, role: str, rank: int, addr: str):
        self._write(f"w.{role}.{rank}", {"addr": addr})

    def lookup_worker(self, role: str, rank: int) -> Optional[str]:
        rec = self._read(f"w.{role}.{rank}")
        return rec["addr"] if rec else None

    def register_queue(self, name: str, addr: str):
        self._write(f"q.{name}", {"addr": addr})

    def lookup_queue(self, name: str) -> Optional[str]:
        rec = self._read(f"q.{name}")
        return rec["addr"] if rec else None

    def set_manifest(self, roles: Dict[str, int]):
        self._write("manifest", roles)

    def manifest(self) -> Dict[str, int]:
        return self._read("manifest") or {}

    def clear(self):
        """Drop stale worker/queue registrations (a previous run of the
        same job name). The manager calls this on a fresh start — never
        on a self-failover resume, whose workers are live and
        registered."""
        for name in os.listdir(self.dir):
            if name.startswith(("w.", "q.")) and name.endswith(".json"):
                try:
                    os.unlink(os.path.join(self.dir, name))
                except OSError:
                    pass


class RayRegistry:
    """Named detached Ray actor holding the same mappings — cluster-wide
    for the Ray backend (workers may sit on different nodes)."""

    ACTOR_FMT = "{job}-dlrover-tpu-runtime-registry"

    def __init__(self, job_name: str):
        import ray

        self._ray = ray
        name = self.ACTOR_FMT.format(job=job_name)

        @ray.remote
        class _Reg:
            def __init__(self):
                self.d = {}

            def put(self, k, v):
                self.d[k] = v

            def get(self, k):
                return self.d.get(k)

            def clear(self):
                self.d = {
                    k: v for k, v in self.d.items()
                    if not k.startswith(("w.", "q."))
                }

        try:
            self._actor = ray.get_actor(name)
        except ValueError:
            self._actor = _Reg.options(
                name=name, lifetime="detached"
            ).remote()

    def _put(self, k, v):
        self._ray.get(self._actor.put.remote(k, v))

    def _get(self, k):
        return self._ray.get(self._actor.get.remote(k))

    def register_worker(self, role, rank, addr):
        self._put(f"w.{role}.{rank}", addr)

    def lookup_worker(self, role, rank):
        return self._get(f"w.{role}.{rank}")

    def register_queue(self, name, addr):
        self._put(f"q.{name}", addr)

    def lookup_queue(self, name):
        return self._get(f"q.{name}")

    def set_manifest(self, roles):
        self._put("manifest", roles)

    def manifest(self):
        return self._get("manifest") or {}

    def clear(self):
        self._ray.get(self._actor.clear.remote())


def create_registry(job_name: str, backend: Optional[str] = None):
    from dlrover_tpu.unified.backend import UnifiedEnv

    backend = backend or os.getenv(UnifiedEnv.BACKEND, "local")
    if backend == "ray":
        return RayRegistry(job_name)
    return FileRegistry(job_name)


# ---------------------------------------------------------------------------
# Client side
# ---------------------------------------------------------------------------


class _Conn:
    """One persistent connection with a lock (requests are serialized
    per target — parallelism comes from rpc_all's thread pool opening
    distinct connections)."""

    def __init__(self, addr: str, timeout: float, digest: bytes):
        host, port = addr.rsplit(":", 1)
        self._sock = socket.create_connection(
            (host, int(port)), timeout=timeout
        )
        # Prove job membership before the first frame (see module doc):
        # answer the server's nonce challenge with an HMAC keyed on the
        # token digest — never the digest itself on the wire.
        challenge = _recv_exact(self._sock, _AUTH_CHALLENGE_LEN)
        if challenge is None:
            # Peer closed before sending the nonce — a worker dying or
            # mid-restart behind a stale registry address. Nothing was
            # sent yet, so this is safely retryable: raise the
            # ConnectionError the callers' dead-peer retry loops catch
            # (an RpcError here would turn a gang restart into a hard
            # failure).
            self._sock.close()
            raise ConnectionError(f"peer {addr} closed during handshake")
        if challenge[: len(_AUTH_MAGIC)] != _AUTH_MAGIC:
            self._sock.close()
            raise RpcError(f"bad auth challenge from {addr}")
        nonce = challenge[len(_AUTH_MAGIC):]
        mac = hmac.new(digest, nonce, hashlib.sha256).digest()
        self._sock.sendall(_AUTH_MAGIC + mac)
        self._lock = threading.Lock()

    def call(self, req: dict, timeout: Optional[float]) -> dict:
        with self._lock:
            self._sock.settimeout(timeout)
            _send(self._sock, req)
            return _recv(self._sock)

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


class RpcError(RuntimeError):
    pass


def _wait_lookup(fn, what: str, timeout: float):
    deadline = time.time() + timeout
    while True:
        got = fn()
        if got:
            return got
        if time.time() > deadline:
            raise TimeoutError(f"{what} not registered after {timeout}s")
        time.sleep(0.05)


class QueueHandle:
    """Named queue living on its creator's endpoint."""

    def __init__(self, name: str, registry, resolve_timeout: float = 60.0,
                 digest: Optional[bytes] = None):
        self.name = name
        self._registry = registry
        self._resolve_timeout = resolve_timeout
        if digest is None:
            from dlrover_tpu.unified.backend import UnifiedEnv

            digest = _token_digest(resolve_runtime_token(
                os.getenv(UnifiedEnv.JOB_NAME, "")
            ))
        self._digest = digest
        self._conn: Optional[_Conn] = None

    def _ensure(self) -> _Conn:
        if self._conn is None:
            try:
                addr = _wait_lookup(
                    lambda: self._registry.lookup_queue(self.name),
                    f"queue {self.name!r}",
                    self._resolve_timeout,
                )
            except TimeoutError as e:
                # Registration timeout, not a request timeout — must not
                # be caught by the callers' no-resend TimeoutError path.
                raise RpcError(str(e)) from None
            try:
                self._conn = _Conn(
                    addr, self._resolve_timeout, self._digest
                )
            except TimeoutError as e:
                # Connect-phase timeout (black-holed address): nothing
                # was sent, so this is safely retryable — route it into
                # the callers' dead-peer path, not the no-resend one.
                raise ConnectionError(
                    f"connect to {addr} timed out"
                ) from e
        return self._conn

    def _call(self, req: dict, timeout: Optional[float]) -> dict:
        # Dead peer -> reconnect within resolve_timeout (the owner may
        # be mid-gang-restart; its new address appears in the registry
        # when it re-registers). A socket TIMEOUT is different: the
        # request may still execute server-side, so re-sending could
        # double-apply it — raise instead.
        deadline = time.time() + self._resolve_timeout
        while True:
            try:
                return self._ensure().call(req, timeout)
            except TimeoutError:
                self.close()
                raise RpcError(
                    f"queue {self.name!r} request timed out "
                    f"(NOT retried: the peer may have executed it)"
                ) from None
            except ValueError as e:
                # Protocol error (oversized frame, either direction):
                # the stream is desynced — drop the connection and
                # surface the cause; never retry.
                self.close()
                raise RpcError(
                    f"queue {self.name!r} protocol error: {e}"
                ) from None
            except (ConnectionError, OSError) as e:
                self.close()
                if time.time() > deadline:
                    raise RpcError(
                        f"queue {self.name!r} owner unreachable: {e}"
                    ) from e
                time.sleep(0.1)

    def put(self, item, timeout: Optional[float] = 60.0):
        rsp = self._call(
            {"kind": "qput", "queue": self.name, "item": item,
             "timeout": timeout},
            None if timeout is None else timeout + 5.0,
        )
        if not rsp.get("ok"):
            raise RpcError(rsp.get("error"))

    def get(self, timeout: Optional[float] = 60.0):
        rsp = self._call(
            {"kind": "qget", "queue": self.name, "timeout": timeout},
            None if timeout is None else timeout + 5.0,
        )
        if not rsp.get("ok"):
            raise RpcError(rsp.get("error"))
        return rsp["value"]

    def qsize(self) -> int:
        rsp = self._call({"kind": "qsize", "queue": self.name}, 10.0)
        if not rsp.get("ok"):
            raise RpcError(rsp.get("error"))
        return rsp["value"]

    def close(self):
        if self._conn is not None:
            self._conn.close()
            self._conn = None


class RuntimeClient:
    """role/rank-addressed RPC + queue access. Workers normally use the
    module-level helpers in unified.runtime; tests and the manager can
    construct one directly for any job."""

    def __init__(self, job_name: str, backend: Optional[str] = None,
                 resolve_timeout: float = 60.0,
                 token: Optional[str] = None):
        self.job_name = job_name
        self.registry = create_registry(job_name, backend)
        self._resolve_timeout = resolve_timeout
        self._digest = _token_digest(
            token if token is not None
            else resolve_runtime_token(job_name)
        )
        self._conns: Dict[str, _Conn] = {}
        self._lock = threading.Lock()

    def _conn_for(self, role: str, rank: int) -> _Conn:
        key = f"{role}.{rank}"
        with self._lock:
            conn = self._conns.get(key)
        if conn is not None:
            return conn
        try:
            addr = _wait_lookup(
                lambda: self.registry.lookup_worker(role, rank),
                f"worker {role}[{rank}]",
                self._resolve_timeout,
            )
        except TimeoutError as e:
            # Registration timeout, not a request timeout — keep it out
            # of the callers' no-resend TimeoutError path.
            raise RpcError(str(e)) from None
        try:
            conn = _Conn(addr, self._resolve_timeout, self._digest)
        except TimeoutError as e:
            # Connect-phase timeout: nothing sent — retryable, so route
            # it into the dead-peer path, not the no-resend one.
            raise ConnectionError(f"connect to {addr} timed out") from e
        with self._lock:
            # Two threads can race past the cache miss; keep the first
            # registered connection and close the loser so no socket
            # leaks (concurrent rpc() calls outside rpc_all).
            existing = self._conns.get(key)
            if existing is not None:
                loser = conn
                conn = existing
            else:
                self._conns[key] = conn
                loser = None
        if loser is not None:
            loser.close()
        return conn

    def _drop_conn(self, role: str, rank: int):
        key = f"{role}.{rank}"
        with self._lock:
            conn = self._conns.pop(key, None)
        if conn is not None:
            conn.close()

    def rpc(self, role: str, method: str, *args,
            rank: int = 0, timeout: float = 60.0, **kwargs):
        """Request/reply against one worker's exported method.

        Transport semantics: a DEAD connection retries against the
        registry until ``resolve_timeout`` (the target may be mid-
        restart and re-register at a new address); a socket TIMEOUT
        raises immediately and is never re-sent — the peer may have
        executed the (possibly non-idempotent) method already.
        """
        req = {"kind": "rpc", "method": method, "args": args,
               "kwargs": kwargs}
        deadline = time.time() + self._resolve_timeout
        while True:
            try:
                rsp = self._conn_for(role, rank).call(req, timeout)
                break
            except TimeoutError:
                self._drop_conn(role, rank)
                raise RpcError(
                    f"rpc {role}[{rank}].{method} timed out after "
                    f"{timeout}s (NOT retried: the peer may have "
                    f"executed it)"
                ) from None
            except ValueError as e:
                # Protocol error (oversized frame, either direction):
                # the connection is desynced — drop it and surface the
                # cause; never retry.
                self._drop_conn(role, rank)
                raise RpcError(
                    f"rpc {role}[{rank}].{method} protocol error: {e}"
                ) from None
            except (ConnectionError, OSError) as e:
                self._drop_conn(role, rank)
                if time.time() > deadline:
                    raise RpcError(
                        f"rpc {role}[{rank}] unreachable: {e}"
                    ) from e
                time.sleep(0.1)
        if not rsp.get("ok"):
            raise RpcError(
                f"rpc {role}[{rank}].{method}: {rsp.get('error')}"
            )
        return rsp["value"]

    def rpc_all(self, role: str, method: str, *args,
                timeout: float = 60.0, **kwargs) -> List[Any]:
        """Fan out to every rank of ``role`` (actor_helper batch call);
        returns results in rank order, raising if any rank failed."""
        world = self.registry.manifest().get(role)
        if world is None:
            raise RpcError(
                f"role {role!r} not in manifest "
                f"{self.registry.manifest()} — is the job running?"
            )
        with ThreadPoolExecutor(max_workers=min(world, 32)) as pool:
            futs = [
                pool.submit(
                    self.rpc, role, method, *args,
                    rank=r, timeout=timeout, **kwargs,
                )
                for r in range(world)
            ]
            return [f.result() for f in futs]

    def queue(self, name: str) -> QueueHandle:
        return QueueHandle(
            name, self.registry, self._resolve_timeout,
            digest=self._digest,
        )

    def close(self):
        with self._lock:
            for conn in self._conns.values():
                conn.close()
            self._conns.clear()


def write_manifest(job_name: str, roles: Dict[str, int],
                   backend: Optional[str] = None):
    """Called by the manager before workers start so rpc_all knows each
    role's world size."""
    try:
        create_registry(job_name, backend).set_manifest(roles)
    except Exception as e:  # noqa: BLE001 - data plane must not kill jobs
        logger.warning("runtime manifest not written: %s", e)
