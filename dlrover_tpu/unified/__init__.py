from dlrover_tpu.unified.builder import DLJobBuilder  # noqa: F401
from dlrover_tpu.unified.config import DLJobConfig, RoleConfig  # noqa: F401
from dlrover_tpu.unified.master import PrimeMaster, submit  # noqa: F401
