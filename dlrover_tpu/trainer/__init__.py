"""Worker-facing training library: runtime init, elastic trainer, data."""

from dlrover_tpu.trainer.runtime import (  # noqa: F401
    DistributedContext,
    init_distributed,
    get_context,
)
