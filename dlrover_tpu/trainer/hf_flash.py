"""HuggingFace Trainer front-end for flash checkpointing.

Parity: reference trainer/torch/flash_checkpoint/hf_trainer.py
(FlashCkptTrainer) — HF ``Trainer`` users get second-scale in-memory
checkpoints + elastic resume without changing their training loop:

    from dlrover_tpu.trainer.hf_flash import FlashCkptCallback

    trainer = Trainer(..., callbacks=[FlashCkptCallback("/tmp/ckpt")])
    trainer.train()

On every HF save event the callback snapshots model + optimizer +
scheduler state to the flash engine (shm fast path; agent persists to
disk per its policy), and at train start it restores the newest
snapshot — so a relaunched worker resumes from the last flash save,
not the last (much older) disk save. Torch tensors cross into the
engine as numpy (zero-copy where possible); the engine is framework-
agnostic pytrees, which is exactly why this front-end is thin.
"""

from typing import Any, Optional

from dlrover_tpu.common.log import logger
from dlrover_tpu.flash_ckpt.checkpointer import Checkpointer, StorageType


def _tensor_to_numpy(t):
    import ml_dtypes
    import numpy as np
    import torch

    t = t.detach().cpu()
    if t.dtype == torch.bfloat16:
        # numpy has no native bf16: exact bit-level bridge via uint16.
        return t.view(torch.uint16).numpy().view(ml_dtypes.bfloat16)
    try:
        return t.numpy()
    except TypeError:
        # Other numpy-unsupported dtypes (fp8 etc.): upcast.
        return t.float().numpy().astype(np.float32)


def _to_numpy_tree(obj: Any):
    import numpy as np
    import torch

    if isinstance(obj, torch.Tensor):
        return _tensor_to_numpy(obj)
    if isinstance(obj, dict):
        return {k: _to_numpy_tree(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        converted = [_to_numpy_tree(v) for v in obj]
        return type(obj)(converted) if isinstance(obj, tuple) else converted
    if isinstance(obj, (int, float, bool, str)) or obj is None:
        return obj
    return np.asarray(obj)


def _to_torch_tree(obj: Any):
    import ml_dtypes
    import numpy as np
    import torch

    if isinstance(obj, np.ndarray):
        if obj.dtype == ml_dtypes.bfloat16:
            return torch.from_numpy(
                obj.view(np.uint16).copy()
            ).view(torch.bfloat16)
        return torch.from_numpy(obj.copy())
    if isinstance(obj, dict):
        return {k: _to_torch_tree(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        converted = [_to_torch_tree(v) for v in obj]
        return type(obj)(converted) if isinstance(obj, tuple) else converted
    return obj


def snapshot_training_state(model, optimizer=None, scheduler=None) -> dict:
    state = {"model": _to_numpy_tree(model.state_dict())}
    if optimizer is not None:
        state["optimizer"] = _to_numpy_tree(optimizer.state_dict())
    if scheduler is not None:
        state["scheduler"] = _to_numpy_tree(scheduler.state_dict())
    return state


def restore_training_state(
    state: dict, model, optimizer=None, scheduler=None
):
    model.load_state_dict(_to_torch_tree(state["model"]))
    if optimizer is not None and "optimizer" in state:
        optimizer.load_state_dict(_to_torch_tree(state["optimizer"]))
    if scheduler is not None and "scheduler" in state:
        scheduler.load_state_dict(_to_torch_tree(state["scheduler"]))


try:
    from transformers import TrainerCallback as _CallbackBase
except ImportError:  # transformers is optional for the rest of the repo

    class _CallbackBase:  # type: ignore[no-redef]
        pass


class FlashCkptCallback(_CallbackBase):
    """HF TrainerCallback: flash-save on HF's save cadence, restore at
    train begin. ``storage_interval`` additionally persists every Nth
    flash save to disk through the engine (0 = memory-only; the agent's
    async saver still persists on failure)."""

    def __init__(
        self,
        checkpoint_dir: str,
        storage_interval: int = 0,
        checkpointer: Optional[Checkpointer] = None,
    ):
        self._ckpt = checkpointer or Checkpointer(checkpoint_dir)
        self._storage_interval = storage_interval
        self._saves = 0

    # ---- HF hooks ----------------------------------------------------------

    def on_train_begin(self, args, state, control, **kw):
        model = kw.get("model")
        optimizer = kw.get("optimizer")
        scheduler = kw.get("lr_scheduler")
        restored = self._ckpt.load_checkpoint(to_device=False)
        if restored is None or model is None:
            return
        step, np_state, _ = restored
        restore_training_state(np_state, model, optimizer, scheduler)
        state.global_step = step
        logger.info("flash-restored HF trainer at step %d", step)

    def on_save(self, args, state, control, **kw):
        model = kw.get("model")
        if model is None:
            return
        self._saves += 1
        snap = snapshot_training_state(
            model, kw.get("optimizer"), kw.get("lr_scheduler")
        )
        storage = (
            StorageType.DISK
            if self._storage_interval
            and self._saves % self._storage_interval == 0
            else StorageType.MEMORY
        )
        block = self._ckpt.save_checkpoint(
            state.global_step, snap, storage
        )
        logger.info(
            "flash save at step %d (%s, blocked %.3fs)",
            state.global_step,
            storage,
            block,
        )

    def on_train_end(self, args, state, control, **kw):
        self._ckpt.wait_saving_complete()

    def close(self):
        self._ckpt.close()
