"""Sharded training step factory.

Builds a jitted train step whose state (params + optimizer moments) is
laid out by the logical-axis rules (parallel/sharding.py) over a
(dp, ep, pp, sp, tp) mesh: FSDP via embed-dim sharding, TP via heads/mlp/
vocab, EP via expert dims; pipeline via trainer/pipeline.py. Optimizer
moments inherit the param shardings (ZeRO), the step counter is
replicated. Gradient accumulation runs as a ``lax.scan`` so the global
batch is fixed regardless of data-parallel size — the JAX analogue of the
reference's ``ElasticTrainer`` fixed-batch grad-accum
(trainer/torch/elastic/trainer.py:53-86).
"""

import dataclasses
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dlrover_tpu.models import llama
from dlrover_tpu.parallel.sharding import (
    DEFAULT_RULES,
    logical_to_spec,
    sharding_tree,
    spec_tree,
)


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    learning_rate: float = 3e-4
    weight_decay: float = 0.1
    beta1: float = 0.9
    beta2: float = 0.95
    grad_clip: float = 1.0
    warmup_steps: int = 100
    grad_accum: int = 1              # microbatches per step (fixed batch)


def make_optimizer(tc: TrainConfig) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=tc.learning_rate,
        warmup_steps=max(tc.warmup_steps, 1),
        decay_steps=100_000,
        end_value=tc.learning_rate * 0.1,
    )
    return optax.chain(
        optax.clip_by_global_norm(tc.grad_clip),
        optax.adamw(
            schedule,
            b1=tc.beta1,
            b2=tc.beta2,
            weight_decay=tc.weight_decay,
        ),
    )


# ---------------------------------------------------------------------------
# State & sharding layout
# ---------------------------------------------------------------------------


def state_specs(
    config: llama.TpuLMConfig,
    optimizer: optax.GradientTransformation,
    rules=DEFAULT_RULES,
) -> Dict[str, Any]:
    """PartitionSpec pytree for {"params", "opt_state", "step"}."""
    pshapes = jax.eval_shape(
        lambda: llama.init_params(config, jax.random.key(0))[0]
    )
    param_specs = spec_tree(llama.param_axes(config), rules)
    opt_shapes = jax.eval_shape(optimizer.init, pshapes)
    opt_specs = optax.tree_map_params(
        optimizer,
        lambda _, s: s,
        opt_shapes,
        param_specs,
        transform_non_params=lambda _: P(),
        is_leaf=lambda x: isinstance(x, P),
    )
    return {"params": param_specs, "opt_state": opt_specs, "step": P()}


def state_shardings(specs, mesh: Mesh):
    return sharding_tree(specs, mesh)


def batch_spec(rules=DEFAULT_RULES) -> P:
    # tokens [batch, seq+1]: batch over (dp, ep); seq left unsharded at
    # input (activations get re-sharded onto sp by constraint).
    return logical_to_spec(("batch", None), rules)


def init_train_state(
    config: llama.TpuLMConfig,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    rng: jax.Array,
    rules=DEFAULT_RULES,
):
    """Initialize params+opt sharded directly on the mesh (no host blowup)."""
    specs = state_specs(config, optimizer, rules)
    shardings = state_shardings(specs, mesh)

    def init(rng):
        params, _ = llama.init_params(config, rng)
        return {
            "params": params,
            "opt_state": optimizer.init(params),
            "step": jnp.zeros((), jnp.int32),
        }

    with mesh:
        state = jax.jit(init, out_shardings=shardings)(rng)
    return state, specs


# ---------------------------------------------------------------------------
# Train step
# ---------------------------------------------------------------------------


def make_train_step(
    config: llama.TpuLMConfig,
    tc: TrainConfig,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    rules=DEFAULT_RULES,
    loss_fn: Optional[Callable] = None,
    donate: bool = True,
):
    """Returns jitted ``step(state, batch) -> (state, metrics)``.

    batch["tokens"]: [grad_accum * micro_batch, seq+1] int32. The leading
    dim is split into ``grad_accum`` scan iterations; gradients average in
    f32.
    """
    attention_fn = None
    if dict(mesh.shape).get("sp", 1) > 1:
        # Sequence-parallel mesh: attention must hop K/V around the sp
        # ring (plain attention over a seq-sharded constraint would make
        # XLA all-gather the full sequence on every layer).
        from dlrover_tpu.ops.ring_attention import make_ring_attention

        attention_fn = make_ring_attention(mesh, rules)
    _loss = loss_fn or (
        lambda params, batch: llama.loss_fn(
            config, params, batch, attention_fn=attention_fn
        )
    )
    specs = state_specs(config, optimizer, rules)
    shardings = state_shardings(specs, mesh)
    bspec = NamedSharding(mesh, batch_spec(rules))

    def single_grad(params, micro):
        (loss, metrics), grads = jax.value_and_grad(_loss, has_aux=True)(
            params, micro
        )
        return loss, metrics, grads

    def step(state, batch):
        params = state["params"]
        tokens = batch["tokens"]
        ga = tc.grad_accum
        if ga > 1:
            if tokens.shape[0] % ga:
                raise ValueError(
                    f"batch {tokens.shape[0]} not divisible by "
                    f"grad_accum {ga}"
                )
            mb = tokens.shape[0] // ga
            micro_tokens = tokens.reshape(ga, mb, tokens.shape[-1])

            def accum(carry, mt):
                loss, metrics, grads = single_grad(
                    params, {"tokens": mt}
                )
                g_acc, l_acc = carry
                g_acc = jax.tree_util.tree_map(
                    lambda a, g: a + g.astype(jnp.float32) / ga,
                    g_acc,
                    grads,
                )
                return (g_acc, l_acc + loss / ga), metrics

            zeros = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params
            )
            (grads, loss), _ = jax.lax.scan(
                accum, (zeros, jnp.zeros((), jnp.float32)), micro_tokens
            )
        else:
            loss, _, grads = single_grad(params, {"tokens": tokens})

        # named_scope: lands in trace metadata (tf_op) for the bench's
        # mfu_breakdown (tpu_timer/xla_capture.bucket_by_scope).
        with jax.named_scope("optimizer"):
            updates, new_opt = optimizer.update(
                grads, state["opt_state"], params
            )
            new_params = optax.apply_updates(params, updates)
            grad_norm = optax.global_norm(grads)
        new_state = {
            "params": new_params,
            "opt_state": new_opt,
            "step": state["step"] + 1,
        }
        metrics = {
            "loss": loss,
            "grad_norm": grad_norm,
            "step": new_state["step"],
        }
        return new_state, metrics

    jitted = jax.jit(
        step,
        in_shardings=(shardings, {"tokens": bspec}),
        out_shardings=(shardings, None),
        donate_argnums=(0,) if donate else (),
    )

    def run(state, batch):
        # Trace (first call) must happen inside the mesh context so the
        # logical sharding constraints in the model resolve.
        with mesh:
            return jitted(state, batch)

    # The raw jit object, for AOT compilation (``run.jitted.lower(
    # abstract_state, abstract_batch).compile()``) — restart paths
    # overlap the compile with the restore H2D (bench_e2e.py).
    run.jitted = jitted
    return run, specs


def make_eval_step(config, mesh, rules=DEFAULT_RULES):
    bspec = NamedSharding(mesh, batch_spec(rules))

    def ev(params, batch):
        loss, metrics = llama.loss_fn(config, params, batch)
        return metrics["ce"]

    jitted = jax.jit(ev, in_shardings=(None, {"tokens": bspec}))

    def run(params, batch):
        with mesh:  # trace inside the mesh so logical constraints apply
            return jitted(params, batch)

    return run
