"""SPMD pipeline parallelism (GPipe schedule) for TpuLM.

Idiomatic-TPU design: instead of per-rank send/recv (the Megatron pattern
the reference delegates to, SURVEY.md §2.9), the whole pipeline runs
inside ONE jitted program. Layer params carry a leading ``stage`` dim
sharded over the ``pp`` mesh axis; activations live in a
``[stages, microbatch, seq, embed]`` buffer with the same sharding. Each
tick vmaps the per-stage layer stack over the stage dim (XLA partitions
it so every pp group computes exactly its stage) and then shifts the
buffer one slot along ``stage`` — which GSPMD lowers to a
``collective-permute`` riding the ICI ring. ``lax.scan`` over
``num_microbatches + stages - 1`` ticks gives the GPipe schedule with
bubble fraction (S-1)/(M+S-1); gradients flow through the scan
automatically, so the same code serves forward and backward.

Parity note: the reference has no pipeline implementation of its own —
it is parallelism-aware only (rendezvous ``node_unit``, Megatron ckpt
layouts). This module is parity-plus work enabling the flagship model to
actually train with pp on TPU meshes.
"""

import jax
import jax.numpy as jnp

from dlrover_tpu.models import llama
from dlrover_tpu.parallel.sharding import with_logical_constraint


def pipelined_forward(
    config,
    params,
    tokens,                      # [b, s] int32
    positions=None,              # [b, s] global positions
    attention_fn=None,
):
    """Returns (logits [b, s, vocab] f32, aux_loss scalar).

    Requires ``b % config.num_microbatches == 0``. Embedding and unembed
    run outside the pipeline loop (their params are replicated over pp).
    """
    S = config.pp_stages
    M = config.num_microbatches
    b, s = tokens.shape
    if b % M:
        raise ValueError(f"batch {b} not divisible by microbatches {M}")
    mb = b // M
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))

    x = llama.embed_tokens(config, params, tokens)      # [b, s, d]
    d = x.shape[-1]
    micro_x = x.reshape(M, mb, s, d)
    micro_pos = positions.reshape(M, mb, s)

    def constrain_state(st):
        return with_logical_constraint(
            st, ("stage", "batch", "seq", "embed")
        )

    def stage_fn(stage_layers, xi, pos_i):
        return llama.run_layer_stack(
            config, stage_layers, xi, pos_i, attention_fn
        )

    state = constrain_state(jnp.zeros((S, mb, s, d), x.dtype))
    pos_state = jnp.zeros((S, mb, s), positions.dtype)
    outputs = jnp.zeros((M, mb, s, d), x.dtype)
    stage_ids = jnp.arange(S)

    def tick(carry, t):
        state, pos_state, outputs, aux = carry
        # Feed the next microbatch into stage 0 (garbage after t >= M;
        # masked out of aux/outputs below).
        inp = jax.lax.dynamic_index_in_dim(
            micro_x, jnp.clip(t, 0, M - 1), axis=0, keepdims=False
        )
        pin = jax.lax.dynamic_index_in_dim(
            micro_pos, jnp.clip(t, 0, M - 1), axis=0, keepdims=False
        )
        state = constrain_state(state.at[0].set(inp))
        pos_state = pos_state.at[0].set(pin)

        processed, aux_t = jax.vmap(stage_fn)(
            params["layers"], state, pos_state
        )
        processed = constrain_state(processed)

        # Stage i holds microbatch t - i this tick.
        valid = (t - stage_ids >= 0) & (t - stage_ids < M)
        aux = aux + jnp.sum(aux_t * valid.astype(aux_t.dtype))

        out_idx = jnp.clip(t - (S - 1), 0, M - 1)
        prev = jax.lax.dynamic_index_in_dim(
            outputs, out_idx, axis=0, keepdims=False
        )
        new_out = jnp.where(valid[S - 1], processed[S - 1], prev)
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs, new_out, out_idx, axis=0
        )

        # Shift along stage: processed[i] -> state[i+1]. On a pp-sharded
        # mesh axis this is a collective-permute over ICI; slot 0 is
        # overwritten at the next tick.
        state = constrain_state(jnp.roll(processed, 1, axis=0))
        pos_state = jnp.roll(pos_state, 1, axis=0)
        return (state, pos_state, outputs, aux), None

    aux0 = jnp.zeros((), jnp.float32)
    (state, pos_state, outputs, aux), _ = jax.lax.scan(
        tick,
        (state, pos_state, outputs, aux0),
        jnp.arange(M + S - 1),
    )

    x = outputs.reshape(b, s, d)
    x = with_logical_constraint(x, ("batch", "seq", "embed"))
    # Mean over microbatches: aux magnitude must not scale with M (same
    # convention as grad-accum averaging in make_train_step).
    return llama.unembed(config, params, x), aux / M
