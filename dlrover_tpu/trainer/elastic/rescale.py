"""Worker-side live-rescale client (docs/DESIGN.md §27).

Drives a worker through the coordinator's versioned plans without the
process ever exiting:

- :meth:`RescaleClient.poll_plan` — cheap pull of a plan newer than the
  one the worker is running under (the plan "broadcast");
- :meth:`RescaleClient.ack` / :meth:`RescaleClient.wait_barrier` — the
  three phase barriers ("barrier" → "restored" → "resumed"), each a
  bounded wait that resolves to ``ready``, ``superseded`` (a newer plan
  exists; pivot to it) or ``expired`` (the coordinator re-planned around
  dead ranks; re-poll).

Fault sites: every barrier poll passes ``rescale.barrier.wait`` (a
``crash`` rule there is a SIGKILL mid-barrier), and
:meth:`mark_resumed` passes ``rescale.resume.first_step`` AFTER acking
the resume — the kill window between restore and the first post-rescale
step the chaos matrix exercises.
"""

import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from dlrover_tpu.common.log import logger
from dlrover_tpu.fault import fault_point

BARRIER_READY = "ready"
BARRIER_EXPIRED = "expired"
BARRIER_SUPERSEDED = "superseded"


@dataclass
class PlanView:
    """Worker-side view of one rescale plan."""

    plan_id: int
    world: Dict[int, int]
    rank_order: List[int]
    restore_step: int
    reason: str
    created_at: float
    barrier_timeout_s: float

    @property
    def world_size(self) -> int:
        return len(self.world)

    def includes(self, rank: int) -> bool:
        return rank in self.world

    def new_rank_index(self, rank: int) -> int:
        """This rank's position in the NEW world's rank order — the
        value fed to ``sampler.rescale(rank, world)`` and used to pick
        the new addressable byte ranges."""
        return self.rank_order.index(rank)

    @classmethod
    def from_response(cls, resp) -> "PlanView":
        return cls(
            plan_id=resp.plan_id,
            world=dict(resp.world),
            rank_order=list(resp.rank_order),
            restore_step=resp.restore_step,
            reason=resp.reason,
            created_at=resp.created_at,
            barrier_timeout_s=resp.barrier_timeout_s,
        )


class RescaleClient:
    def __init__(self, master_client, node_rank: int,
                 poll_interval_s: float = 0.05):
        self._client = master_client
        self._rank = node_rank
        self._poll_s = poll_interval_s

    def join(self, local_world_size: int = 1, node_group: int = -1):
        """``node_group`` is this host's TPU slice/block index (from
        rendezvous); carrying it lets the coordinator keep plan worlds
        slice-complete."""
        self._client.rescale_join(
            self._rank, local_world_size, node_group=node_group
        )

    def poll_plan(self, current_plan_id: int = -1) -> Optional[PlanView]:
        resp = self._client.get_rescale_plan(self._rank, current_plan_id)
        if resp is None:
            return None
        return PlanView.from_response(resp)

    def wait_for_plan(
        self, current_plan_id: int = -1, timeout_s: float = 60.0
    ) -> Optional[PlanView]:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            plan = self.poll_plan(current_plan_id)
            if plan is not None:
                return plan
            time.sleep(self._poll_s)
        return None

    def ack(self, plan_id: int, phase: str):
        self._client.report_rescale_ack(self._rank, plan_id, phase)

    def wait_barrier(
        self, plan_id: int, phase: str, timeout_s: float = 60.0
    ) -> str:
        """Poll a plan's phase barrier; one of BARRIER_READY /
        BARRIER_SUPERSEDED / BARRIER_EXPIRED. The local timeout is a
        backstop only — the coordinator's bounded wait normally expires
        first and re-plans, which surfaces here as expired/superseded."""
        deadline = time.monotonic() + timeout_s
        while True:
            fault_point(
                "rescale.barrier.wait", plan_id=plan_id, phase=phase
            )
            ready, expired, superseded, missing = (
                self._client.get_rescale_barrier(self._rank, plan_id, phase)
            )
            if superseded:
                return BARRIER_SUPERSEDED
            if ready:
                return BARRIER_READY
            if expired:
                return BARRIER_EXPIRED
            if time.monotonic() > deadline:
                logger.warning(
                    "rescale plan %d phase %r: local barrier timeout "
                    "(missing %s)", plan_id, phase, missing
                )
                return BARRIER_EXPIRED
            time.sleep(self._poll_s)

    def mark_resumed(self, plan_id: int):
        """Ack the resume phase and pass the restore-to-first-step kill
        window. Call IMMEDIATELY before the first post-rescale step."""
        self.ack(plan_id, "resumed")
        fault_point("rescale.resume.first_step", plan_id=plan_id)
