"""Elastic distributed sampler.

Parity: reference trainer/torch/elastic/sampler.py
(ElasticDistributedSampler:155) — a deterministic per-epoch shuffle,
sharded round-robin over ranks, with ``state_dict``/``load_state_dict``
so a restarted (possibly re-scaled) job resumes mid-epoch without
revisiting consumed records: completed count is recorded globally and the
remaining indices are re-dealt over the *new* world size.
"""

from typing import Dict, Iterator

import numpy as np


class ElasticDistributedSampler:
    def __init__(
        self,
        dataset_size: int,
        rank: int = 0,
        world_size: int = 1,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
    ):
        if world_size <= 0 or not (0 <= rank < world_size):
            raise ValueError(f"bad rank/world {rank}/{world_size}")
        self.dataset_size = dataset_size
        self.rank = rank
        self.world_size = world_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        # Records consumed across ALL ranks this epoch (global position).
        self._completed = 0

    # ---- iteration ----------------------------------------------------------

    def _epoch_indices(self) -> np.ndarray:
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            return rng.permutation(self.dataset_size)
        return np.arange(self.dataset_size)

    def __iter__(self) -> Iterator[int]:
        indices = self._epoch_indices()[self._completed :]
        if self.drop_last:
            usable = len(indices) - len(indices) % self.world_size
            indices = indices[:usable]
        # Deal the remaining records round-robin over the current world:
        # after a re-scale every rank resumes from the same global cursor.
        for i in range(self.rank, len(indices), self.world_size):
            yield int(indices[i])

    def __len__(self) -> int:
        remaining = self.dataset_size - self._completed
        if self.drop_last:
            return remaining // self.world_size
        return (remaining + self.world_size - 1 - self.rank) // self.world_size

    # ---- bookkeeping ---------------------------------------------------------

    def record_batch(self, global_batch_size: int):
        """Advance the global cursor by one consumed global batch."""
        self._completed = min(
            self.dataset_size, self._completed + global_batch_size
        )

    def set_epoch(self, epoch: int):
        self.epoch = epoch
        self._completed = 0

    # ---- checkpoint ----------------------------------------------------------

    def state_dict(self) -> Dict[str, int]:
        return {
            "epoch": self.epoch,
            "completed": self._completed,
            "dataset_size": self.dataset_size,
        }

    def load_state_dict(self, state: Dict[str, int]):
        saved_size = int(state.get("dataset_size", self.dataset_size))
        if saved_size != self.dataset_size:
            raise ValueError(
                f"checkpoint was taken over a dataset of {saved_size} "
                f"records, this sampler covers {self.dataset_size}; "
                "refusing a silently misaligned cursor"
            )
        self.epoch = int(state.get("epoch", 0))
        self._completed = int(state.get("completed", 0))

    def rescale(self, rank: int, world_size: int):
        """Adopt a new world (after elastic re-mesh), keeping the cursor."""
        if world_size <= 0 or not (0 <= rank < world_size):
            raise ValueError(f"bad rank/world {rank}/{world_size}")
        self.rank = rank
        self.world_size = world_size
