"""Elastic data loaders: batches that keep the global batch fixed.

Parity: reference trainer/torch/elastic/dataloader.py (ElasticDataLoader)
— rebuilt around host-side numpy batching for JAX: the loader yields
stacked numpy batches selected by an ElasticDistributedSampler (static
split) or an IndexShardingClient (master-driven dynamic shards).

Two loaders:

- :class:`ElasticDataLoader` — the simple synchronous path (fetch and
  ``np.stack`` in the training thread), kept as the A/B baseline.
- :class:`PrefetchingDataLoader` — batches are assembled in a background
  thread into a ring of reusable preallocated buffers (no per-batch
  ``np.stack`` allocation churn) with a bounded depth, so record fetch
  and batch assembly overlap the training step. Buffer ownership rule
  (docs/DESIGN.md §24): a yielded batch's arrays are views into ring
  buffers and stay valid ONLY until the next batch is requested; anything
  that must outlive that (e.g. a host-side copy) must copy explicitly —
  ``jax.device_put`` via :func:`device_put_prefetch` is already safe.
"""

import queue
import threading
import time
from typing import Callable, Dict, Iterable, Iterator, Optional

import numpy as np

from dlrover_tpu.trainer.elastic.sampler import ElasticDistributedSampler

_END = object()


class ElasticDataLoader:
    def __init__(
        self,
        fetch_record: Callable[[int], dict],
        sampler: ElasticDistributedSampler,
        per_host_batch_size: int,
    ):
        """``fetch_record(index) -> dict of np arrays`` is the user's
        record accessor (memory-mapped file, array slice, ...)."""
        self._fetch = fetch_record
        self.sampler = sampler
        self.per_host_batch_size = per_host_batch_size

    @property
    def global_batch_size(self) -> int:
        return self.per_host_batch_size * self.sampler.world_size

    def __iter__(self) -> Iterator[dict]:
        batch = []
        for index in self.sampler:
            batch.append(self._fetch(index))
            if len(batch) == self.per_host_batch_size:
                # Advance the cursor BEFORE yielding: a checkpoint taken
                # after training on this batch must count it, or resume
                # would replay the same records.
                self.sampler.record_batch(self.global_batch_size)
                yield self._stack(batch)
                batch = []
        # Trailing partial batch dropped: static shapes keep XLA happy.

    def __len__(self) -> int:
        return len(self.sampler) // self.per_host_batch_size

    @staticmethod
    def _stack(records) -> dict:
        keys = records[0].keys()
        return {
            k: np.stack([np.asarray(r[k]) for r in records]) for k in keys
        }


class PrefetchingDataLoader:
    """Double-buffered batch assembly over any record-index source.

    A background assembler thread pulls indices from ``index_source``
    (an :class:`IndexShardingClient`, a sampler, or any iterable of
    ints), fetches records — optionally through a small thread pool —
    and writes them row-by-row into one of ``depth + 1`` preallocated
    buffer sets. Ready batches wait in a bounded queue; the consumer
    recycles the previously yielded buffer set each time it asks for the
    next batch.

    ``sampler``: when given, ``sampler.record_batch(global_batch)`` is
    called as each batch is YIELDED (not when it is assembled) so
    checkpoint cursors count exactly the batches handed to training —
    batches sitting assembled-but-unconsumed in the ring are not counted.
    """

    def __init__(
        self,
        fetch_record: Callable[[int], dict],
        index_source: Iterable[int],
        per_host_batch_size: int,
        depth: int = 2,
        num_workers: int = 0,
        sampler: Optional[ElasticDistributedSampler] = None,
        world_size: int = 1,
    ):
        if per_host_batch_size <= 0:
            raise ValueError("per_host_batch_size must be positive")
        self._fetch = fetch_record
        self._source = index_source
        self.per_host_batch_size = per_host_batch_size
        self.depth = max(depth, 1)
        self._num_workers = max(num_workers, 0)
        self.sampler = sampler
        self._world_size = (
            sampler.world_size if sampler is not None else max(world_size, 1)
        )
        # depth ready slots + the one the consumer currently holds.
        self._nslots = self.depth + 1
        self._buffers: list = [None] * self._nslots
        self._free: "queue.Queue[int]" = queue.Queue()
        self._ready: "queue.Queue" = queue.Queue(maxsize=self.depth)
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._pool = None
        from dlrover_tpu.observability.registry import default_registry

        reg = default_registry()
        self._assembly_hist = reg.histogram(
            "data_batch_assembly_seconds",
            "wall time to assemble one host batch into the ring",
        )
        self._batch_wait = reg.counter(
            "data_batch_wait_seconds_total",
            "seconds the training thread waited for an assembled batch",
        )
        self._batches_total = reg.counter(
            "data_batches_total", "host batches yielded to training"
        )
        self._ring_depth = reg.gauge(
            "data_ready_batches", "assembled batches waiting for training"
        )

    @property
    def global_batch_size(self) -> int:
        return self.per_host_batch_size * self._world_size

    # ---- assembler thread --------------------------------------------------

    def _alloc_slot(self, slot: int, proto: Dict[str, np.ndarray]):
        self._buffers[slot] = {
            k: np.empty(
                (self.per_host_batch_size,) + v.shape, dtype=v.dtype
            )
            for k, v in proto.items()
        }

    def _assemble_loop(self):
        try:
            rows_iter = iter(self._source)
            proto: Optional[Dict[str, np.ndarray]] = None
            while not self._stopped.is_set():
                try:
                    slot = self._free.get(timeout=0.2)
                except queue.Empty:
                    continue
                t0 = time.monotonic()
                indices = []
                for index in rows_iter:
                    indices.append(index)
                    if len(indices) == self.per_host_batch_size:
                        break
                    if self._stopped.is_set():
                        return
                if self._stopped.is_set():
                    return
                if len(indices) < self.per_host_batch_size:
                    # Trailing partial batch dropped: static shapes keep
                    # XLA happy (same contract as ElasticDataLoader).
                    break
                if self._pool is not None:
                    records = list(self._pool.map(self._fetch, indices))
                else:
                    records = [self._fetch(i) for i in indices]
                if proto is None:
                    proto = {
                        k: np.asarray(v) for k, v in records[0].items()
                    }
                if self._buffers[slot] is None:
                    self._alloc_slot(slot, proto)
                buf = self._buffers[slot]
                for row, rec in enumerate(records):
                    for k in buf:
                        buf[k][row] = rec[k]
                self._assembly_hist.observe(time.monotonic() - t0)
                self._put_ready((slot, None))
        except Exception as exc:  # noqa: BLE001 — surfaced to consumer
            self._put_ready((None, exc))
            return
        self._put_ready(_END)

    def _put_ready(self, item):
        while not self._stopped.is_set():
            try:
                self._ready.put(item, timeout=0.2)
                return
            except queue.Full:
                continue

    # ---- consumer ----------------------------------------------------------

    def __iter__(self) -> Iterator[dict]:
        if self._thread is not None:
            raise RuntimeError(
                "PrefetchingDataLoader is single-pass: its index source "
                "is consumed and its ring retired; build a new loader "
                "per epoch (IndexShardingClient sources span epochs "
                "master-side within one pass)"
            )
        if self._num_workers > 0:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(
                max_workers=self._num_workers,
                thread_name_prefix="data-fetch",
            )
        for slot in range(self._nslots):
            self._free.put(slot)
        self._thread = threading.Thread(
            target=self._assemble_loop,
            daemon=True,
            name="batch-assembler",
        )
        self._thread.start()
        held: Optional[int] = None
        try:
            while True:
                t0 = time.monotonic()
                while True:
                    try:
                        item = self._ready.get(timeout=0.2)
                        break
                    except queue.Empty:
                        if self._stopped.is_set():
                            # stop() from another thread while we were
                            # blocked: the assembler's sentinel may have
                            # been dropped — end cleanly, don't hang.
                            self._batch_wait.inc(time.monotonic() - t0)
                            return
                self._batch_wait.inc(time.monotonic() - t0)
                self._ring_depth.set(self._ready.qsize())
                if held is not None:
                    # The consumer is done with the previous buffers —
                    # only now may the assembler overwrite them.
                    self._free.put(held)
                    held = None
                if item is _END:
                    return
                slot, err = item
                if err is not None:
                    raise err
                held = slot
                if self.sampler is not None:
                    # Cursor advances when training RECEIVES the batch,
                    # mirroring ElasticDataLoader's resume contract.
                    self.sampler.record_batch(self.global_batch_size)
                self._batches_total.inc()
                yield self._buffers[slot]
        finally:
            if held is not None:
                self._free.put(held)
            self.stop()

    def stop(self):
        self._stopped.set()
        if self._thread is not None:
            # Short join: the assembler polls _stopped between queue ops
            # and index yields, but an index SOURCE wedged inside a
            # blocking call can't be interrupted — abandon the daemon
            # thread rather than stall teardown behind it.
            self._thread.join(timeout=1.0)
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None


def device_put_prefetch(batches, sharding=None):
    """Double-buffer host->device transfers: enqueue the H2D copy of the
    NEXT batch, hand the caller the previous one, and only recycle the
    host buffers after the in-flight transfer has landed. With a
    :class:`PrefetchingDataLoader` source this makes the copy out of the
    reusable ring buffers safe by construction, and the H2D of batch
    ``n+1`` overlaps the training step on batch ``n``."""
    import jax

    # On the CPU backend device_put may ALIAS aligned host memory
    # instead of copying — a jax.Array silently backed by a ring slot
    # would be corrupted when the slot is recycled. A real accelerator's
    # H2D is a true copy; there block_until_ready below is the fence.
    aliasing = jax.default_backend() == "cpu"
    prev = None
    for host_batch in batches:
        if aliasing:
            host_batch = jax.tree_util.tree_map(np.array, host_batch)
        if sharding is not None:
            dev = jax.device_put(host_batch, sharding)
        else:
            dev = jax.device_put(host_batch)
        if prev is not None:
            yield prev
        # The transfer reads from a reusable ring slot; it must complete
        # before the next iterator advance can recycle that slot. By the
        # time the caller asks for the next batch the previous step has
        # already overlapped this wait.
        jax.block_until_ready(dev)
        prev = dev
    if prev is not None:
        yield prev
