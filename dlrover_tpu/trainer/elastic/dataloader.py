"""Elastic data loader: batches that keep the global batch fixed.

Parity: reference trainer/torch/elastic/dataloader.py (ElasticDataLoader)
— rebuilt around host-side numpy batching for JAX: the loader yields
stacked numpy batches selected by an ElasticDistributedSampler (static
split) or an IndexShardingClient (master-driven dynamic shards).
"""

from typing import Callable, Iterator

import numpy as np

from dlrover_tpu.trainer.elastic.sampler import ElasticDistributedSampler


class ElasticDataLoader:
    def __init__(
        self,
        fetch_record: Callable[[int], dict],
        sampler: ElasticDistributedSampler,
        per_host_batch_size: int,
    ):
        """``fetch_record(index) -> dict of np arrays`` is the user's
        record accessor (memory-mapped file, array slice, ...)."""
        self._fetch = fetch_record
        self.sampler = sampler
        self.per_host_batch_size = per_host_batch_size

    @property
    def global_batch_size(self) -> int:
        return self.per_host_batch_size * self.sampler.world_size

    def __iter__(self) -> Iterator[dict]:
        batch = []
        for index in self.sampler:
            batch.append(self._fetch(index))
            if len(batch) == self.per_host_batch_size:
                # Advance the cursor BEFORE yielding: a checkpoint taken
                # after training on this batch must count it, or resume
                # would replay the same records.
                self.sampler.record_batch(self.global_batch_size)
                yield self._stack(batch)
                batch = []
        # Trailing partial batch dropped: static shapes keep XLA happy.

    def __len__(self) -> int:
        return len(self.sampler) // self.per_host_batch_size

    @staticmethod
    def _stack(records) -> dict:
        keys = records[0].keys()
        return {
            k: np.stack([np.asarray(r[k]) for r in records]) for k in keys
        }
