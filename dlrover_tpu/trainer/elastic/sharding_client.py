"""Worker-side dynamic data sharding client.

Parity: reference dlrover/python/elastic_agent/sharding/client.py
(ShardingClient:29, IndexShardingClient:232) — workers pull record-range
tasks from the master's TaskManager instead of statically partitioning
the dataset, so shards owned by a dead/slow worker are re-dispatched and
elasticity needs no data re-splitting.
"""

import queue
import threading
import time
from typing import Iterator, List, Optional

from dlrover_tpu.common import comm
from dlrover_tpu.common.constants import TaskType


class ShardingClient:
    """Task-granular client: fetch a shard, process it, report done."""

    def __init__(
        self,
        master_client,
        dataset_name: str,
        dataset_size: int,
        shard_size: int,
        num_epochs: int = 1,
        shuffle: bool = False,
        task_type: str = "training",
    ):
        self._client = master_client
        self.dataset_name = dataset_name
        self._current_task: Optional[comm.ShardTask] = None
        # Idempotent on the master: every worker reports the params, the
        # first one creates the dataset.
        self._client.report_dataset_shard_params(
            comm.DatasetShardParams(
                dataset_name=dataset_name,
                dataset_size=dataset_size,
                shard_size=shard_size,
                num_epochs=num_epochs,
                shuffle=shuffle,
                task_type=task_type,
            )
        )

    def fetch_task(self) -> Optional[comm.ShardTask]:
        """Next shard, or None when the dataset is exhausted.

        A WAIT response (peers hold the remaining shards in flight) polls
        until the master either re-dispatches a recovered shard or
        declares the dataset done — returning early would orphan shards
        re-queued after a peer failure.
        """
        while True:
            task = self._client.get_task(self.dataset_name)
            if task is None:
                return None
            if task.task_type == TaskType.WAIT:
                time.sleep(2.0)
                continue
            if task.task_id < 0:
                return None
            self._current_task = task
            return task

    def report_task_done(self, task: Optional[comm.ShardTask] = None):
        task = task or self._current_task
        if task is not None:
            self._client.report_task_done(self.dataset_name, task.task_id)

    # ---- shard checkpoint (dataset position survives restarts) ------------

    def get_shard_checkpoint(self) -> str:
        return self._client.get_shard_checkpoint(self.dataset_name)

    def restore_shard_checkpoint(self, checkpoint: str):
        if checkpoint:
            self._client.restore_shard_checkpoint(
                self.dataset_name, checkpoint
            )


class IndexShardingClient(ShardingClient):
    """Record-granular iterator: hides tasks behind ``next index``.

    Fetches one task at a time from the master, synchronously at shard
    boundaries; iteration ends when the master reports the dataset done.
    """

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._indices: "queue.Queue[int]" = queue.Queue()
        self._records_consumed = 0
        self._records_in_task = 0
        self._lock = threading.Lock()

    def fetch_record_index(self) -> Optional[int]:
        """Next global record index, or None at end of data."""
        with self._lock:
            if self._indices.empty():
                if not self._fill_from_next_task():
                    return None
            index = self._indices.get()
            self._records_consumed += 1
            self._records_in_task -= 1
            if self._records_in_task == 0 and self._current_task:
                self.report_task_done(self._current_task)
        return index

    def _fill_from_next_task(self) -> bool:
        task = self.fetch_task()
        if task is None:
            return False
        indices: List[int] = (
            task.record_indices
            if task.record_indices
            else list(range(task.start, task.end))
        )
        for i in indices:
            self._indices.put(i)
        self._records_in_task = len(indices)
        return bool(indices)

    def __iter__(self) -> Iterator[int]:
        while True:
            index = self.fetch_record_index()
            if index is None:
                return
            yield index
