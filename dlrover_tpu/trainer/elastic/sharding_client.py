"""Worker-side dynamic data sharding client.

Parity: reference dlrover/python/elastic_agent/sharding/client.py
(ShardingClient:29, IndexShardingClient:232) — workers pull record-range
tasks from the master's TaskManager instead of statically partitioning
the dataset, so shards owned by a dead/slow worker are re-dispatched and
elasticity needs no data re-splitting.

Pipelined: a background prefetcher keeps a bounded queue of shard leases
in flight (fetched ``fetch_batch`` at a time through the batched
``get_tasks`` verb) and done-reports are coalesced into batched RPCs, so
the training thread never blocks on a master round trip at a shard
boundary. Lease lifecycle and flush-ordering rules are documented in
docs/DESIGN.md §24:

- a lease lives in the master's ``doing`` table from the moment the
  batched fetch returns it, so a worker dying with prefetched-but-
  unconsumed leases gets them re-queued by ``recover_node_tasks``;
- pending done-reports are force-flushed before every fetch RPC, on a
  WAIT response, and before ``get_shard_checkpoint`` — the checkpoint
  must never hold a shard this worker already finished;
- the WAIT poll backs off with jitter inside the prefetcher thread,
  never the training thread.
"""

import queue
import random
import threading
import time
from typing import Iterator, List, Optional

from dlrover_tpu.common import comm
from dlrover_tpu.common.log import logger
from dlrover_tpu.fault import fault_point

# End-of-dataset sentinel in the prefetch queue (left in the queue so
# every later fetch also sees it).
_END = object()


def _data_metrics():
    from dlrover_tpu.observability.registry import default_registry

    reg = default_registry()
    return {
        "fetch_wait": reg.counter(
            "data_fetch_wait_seconds_total",
            "seconds the training thread waited for a shard lease",
        ),
        "queue_depth": reg.gauge(
            "data_prefetch_queue_depth",
            "shard leases currently prefetched and unconsumed",
        ),
        "tasks_fetched": reg.counter(
            "data_shard_tasks_fetched_total",
            "shard leases fetched from the master",
        ),
        "fetch_rpcs": reg.counter(
            "data_fetch_rpcs_total", "get-task round trips issued"
        ),
        "report_rpcs": reg.counter(
            "data_report_rpcs_total", "done-report round trips issued"
        ),
        "rpcs_saved": reg.counter(
            "data_rpcs_saved_total",
            "control RPCs avoided by task/report batching",
        ),
    }


class ShardingClient:
    """Task-granular client: fetch a shard, process it, report done.

    ``prefetch_depth=0`` disables the pipeline entirely (synchronous
    fetch, immediate reports) — the pre-batching behavior, kept for A/B
    benchmarking and as a debugging escape hatch.
    """

    def __init__(
        self,
        master_client,
        dataset_name: str,
        dataset_size: int,
        shard_size: int,
        num_epochs: int = 1,
        shuffle: bool = False,
        task_type: str = "training",
        prefetch_depth: int = 16,
        fetch_batch: int = 8,
        report_batch: int = 8,
        report_interval_s: float = 2.0,
        wait_backoff_s: float = 0.2,
        wait_backoff_max_s: float = 2.0,
        wait_flush_age_s: float = 0.25,
    ):
        self._client = master_client
        self.dataset_name = dataset_name
        self._current_task: Optional[comm.ShardTask] = None
        self._prefetch_depth = max(prefetch_depth, 0)
        self._fetch_batch = max(fetch_batch, 1)
        self._report_batch = max(report_batch, 1)
        self._report_interval_s = report_interval_s
        self._wait_backoff_s = wait_backoff_s
        self._wait_backoff_max_s = wait_backoff_max_s
        self._wait_flush_age_s = wait_flush_age_s
        self._queue: "queue.Queue" = queue.Queue(
            maxsize=self._prefetch_depth or 1
        )
        self._stopped = threading.Event()
        self._prefetcher: Optional[threading.Thread] = None
        self._prefetcher_lock = threading.Lock()
        # Coalesced done-reports (flushed on count/interval/WAIT/ckpt).
        # _report_lock guards the pending lists; _flush_lock is held
        # across the whole swap+RPC so "flush" means FLUSHED, not
        # "someone else's flush is still in flight" (lock order:
        # _flush_lock -> _report_lock, never the reverse).
        self._report_lock = threading.Lock()
        # RLock: the master-epoch listener fires on the RPC thread, so a
        # flush whose own RPC observes a new master incarnation re-enters
        # flush_reports from inside the lock; cross-thread exclusion (the
        # "flushed means FLUSHED" guarantee) is unchanged.
        self._flush_lock = threading.RLock()
        self._pending_done: List[int] = []
        self._pending_failed: List[int] = []
        self._pending_since = 0.0
        self._metrics = _data_metrics()
        from dlrover_tpu.observability.flight_recorder import active_recorder

        self._recorder = active_recorder()
        # Idempotent on the master: every worker reports the params, the
        # first one creates the dataset.
        self._shard_params = comm.DatasetShardParams(
            dataset_name=dataset_name,
            dataset_size=dataset_size,
            shard_size=shard_size,
            num_epochs=num_epochs,
            shuffle=shuffle,
            task_type=task_type,
        )
        self._client.report_dataset_shard_params(self._shard_params)
        # Master crash ride-through (DESIGN.md §37): when the client
        # observes a new master incarnation, re-register the dataset
        # params (no-op if the journal already rehydrated it) and flush
        # coalesced done-reports so exactly-once accounting re-converges
        # on the new epoch without restarting the prefetcher.
        add_listener = getattr(master_client, "add_epoch_listener", None)
        if callable(add_listener):
            add_listener(self._on_master_epoch_change)

    # ---- prefetcher --------------------------------------------------------

    @property
    def prefetching(self) -> bool:
        return self._prefetch_depth > 0

    def _ensure_prefetcher(self):
        if not self.prefetching or self._prefetcher is not None:
            return
        with self._prefetcher_lock:
            if self._prefetcher is None:
                self._prefetcher = threading.Thread(
                    target=self._prefetch_loop,
                    # Bound at spawn, NOT read from self inside the
                    # loop: resume_after_rescale swaps the stop event
                    # and queue attributes, and a stale thread that
                    # outlived its pause join (wedged in a slow RPC)
                    # must keep seeing ITS OWN set event and drain into
                    # ITS OWN dead queue — never the new epoch's.
                    args=(self._stopped, self._queue),
                    daemon=True,
                    name=f"shard-prefetch-{self.dataset_name}",
                )
                self._prefetcher.start()

    def _prefetch_loop(self, stopped: threading.Event, out_queue):
        backoff = self._wait_backoff_s
        while not stopped.is_set():
            # Reports first: keeps master-side shard accounting tight and
            # lets the master retire shards before handing out new ones.
            self._flush_if_due()
            try:
                fault_point(
                    "data.prefetch.fetch", dataset=self.dataset_name
                )
                tasks, wait = self._client.get_tasks(
                    self.dataset_name, self._fetch_batch
                )
            except Exception:
                # Never end iteration on transport failure — a silent
                # _END here would truncate the dataset. Retry with
                # growing backoff; if the master is really gone the
                # agent tears this worker down anyway.
                logger.warning(
                    "shard prefetch RPC failed; retrying", exc_info=True
                )
                if stopped.wait(backoff):
                    return
                backoff = min(backoff * 2, self._wait_backoff_max_s)
                continue
            self._metrics["fetch_rpcs"].inc()
            if wait:
                # Peers (or this worker's own unflushed dones) hold the
                # remaining shards. Flush reports older than
                # ``wait_flush_age_s`` — they may be exactly what the
                # master waits for — but keep young ones batching so a
                # drained queue doesn't degrade reports to one-per-RPC.
                flushed = 0
                with self._report_lock:
                    count = len(self._pending_done) + len(
                        self._pending_failed
                    )
                    aged = (
                        count > 0
                        and time.monotonic() - self._pending_since
                        >= self._wait_flush_age_s
                    )
                if count >= self._report_batch or aged:
                    flushed = self.flush_reports()
                if flushed:
                    # Our dones may have completed the dataset: re-poll
                    # soon, but not in a hot RPC loop.
                    if stopped.wait(0.05):
                        return
                else:
                    if stopped.wait(
                        backoff * (1.0 + random.uniform(-0.3, 0.3))
                    ):
                        return
                    backoff = min(backoff * 2, self._wait_backoff_max_s)
                continue
            backoff = self._wait_backoff_s
            if not tasks:
                # Dataset exhausted: flush the tail of reports, then park
                # the end sentinel for every future fetch.
                self.flush_reports()
                if self._recorder is not None:
                    self._recorder.annotate(
                        "data_exhausted", dataset=self.dataset_name
                    )
                out_queue.put(_END)
                return
            self._metrics["tasks_fetched"].inc(len(tasks))
            self._metrics["rpcs_saved"].inc(len(tasks) - 1)
            for task in tasks:
                while True:
                    try:
                        out_queue.put(task, timeout=0.2)
                        break
                    except queue.Full:
                        if stopped.is_set():
                            return
                self._metrics["queue_depth"].set(out_queue.qsize())

    def stop(self):
        """Stop the prefetcher and flush pending reports. Leases already
        prefetched but unconsumed stay in the master's ``doing`` table —
        on worker death they are re-queued by ``recover_node_tasks``."""
        self._stopped.set()
        if self._prefetcher is not None:
            self._prefetcher.join(timeout=5.0)
        self.flush_reports()

    def kill(self):
        """Chaos/testing: die WITHOUT flushing — pending done-reports
        are lost and prefetched leases stay unconsumed, exactly like a
        crashed worker. The master's ``recover_node_tasks`` (node death)
        or task timeout re-queues everything not already reported."""
        self._stopped.set()
        if self._prefetcher is not None:
            self._prefetcher.join(timeout=5.0)
        with self._report_lock:
            self._pending_done, self._pending_failed = [], []

    # ---- live rescale ------------------------------------------------------

    def pause_for_rescale(self) -> int:
        """Tear down ONLY the data-path prefetcher for a live rescale
        (docs/DESIGN.md §27): stop the prefetch thread, discard locally
        queued-but-unconsumed leases (they stay in the master's
        ``doing`` table and come back via the shard-snapshot restore or
        timeout recovery — consuming them here after the state rolled
        back would double-count their records), and force-flush pending
        done-reports so the master's ledger reflects every shard this
        worker actually finished BEFORE the rescale rolls the dataset
        cursor back. Returns the number of reports flushed."""
        self._stopped.set()
        if self._prefetcher is not None:
            self._prefetcher.join(timeout=5.0)
            if self._prefetcher.is_alive():
                # Wedged in a slow RPC. Safe to proceed: the thread
                # holds ITS OWN (now set) stop event and ITS OWN queue,
                # so it can neither feed the post-rescale queue nor
                # outlive its next loop check — but say so, because a
                # lease it fetches on the way out sits in the master's
                # doing table until timeout recovery.
                logger.warning(
                    "prefetcher still draining a slow RPC at rescale "
                    "pause; it will exit on its own stop event"
                )
        self._prefetcher = None
        discarded = 0
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is not _END:
                discarded += 1
        self._current_task = None
        flushed = self.flush_reports()
        self._metrics["queue_depth"].set(0)
        if self._recorder is not None:
            self._recorder.annotate(
                "rescale_pause",
                dataset=self.dataset_name,
                flushed=flushed,
                discarded=discarded,
            )
        return flushed

    def resume_after_rescale(self):
        """Bring the data path back after the new world's shard cursor
        is in place: fresh queue + stop flag, prefetcher restarts
        lazily on the next fetch. The end-of-data sentinel is dropped
        with the old queue — the snapshot restore may have re-queued
        shards a previous world left in flight."""
        self._stopped = threading.Event()
        self._queue = queue.Queue(maxsize=self._prefetch_depth or 1)
        self._prefetcher = None
        self._current_task = None

    # ---- fetch -------------------------------------------------------------

    def fetch_task(self) -> Optional[comm.ShardTask]:
        """Next shard, or None when the dataset is exhausted.

        With prefetch on this blocks only when the queue has run dry (the
        fetch-wait seconds counter tells you how often). A WAIT response
        (peers hold the remaining shards in flight) is polled by the
        prefetcher until the master either re-dispatches a recovered
        shard or declares the dataset done — returning early would
        orphan shards re-queued after a peer failure.
        """
        if not self.prefetching:
            return self._fetch_task_sync()
        self._ensure_prefetcher()
        t0 = time.monotonic()
        while True:
            try:
                item = self._queue.get(timeout=0.2)
                break
            except queue.Empty:
                if self._stopped.is_set():
                    # stop()/kill() while we were blocked: the
                    # prefetcher is gone and nothing else will ever
                    # arrive — report end-of-data instead of hanging.
                    self._metrics["fetch_wait"].inc(
                        time.monotonic() - t0
                    )
                    return None
        self._metrics["fetch_wait"].inc(time.monotonic() - t0)
        if item is _END:
            self._queue.put(_END)
            return None
        self._metrics["queue_depth"].set(self._queue.qsize())
        self._current_task = item
        return item

    def poll_task(self, timeout_s: float = 0.2):
        """Non-blocking lease poll for lockstep consumers: ("task", t)
        when a lease is ready, ("end", None) once the dataset is
        exhausted, ("wait", None) when nothing arrived within
        ``timeout_s`` (peers hold the remaining shards, or the
        prefetcher is still warming) — the caller keeps its collective
        step loop turning instead of blocking a whole world on one
        rank's empty queue."""
        if not self.prefetching:
            raise RuntimeError("poll_task requires prefetch_depth > 0")
        self._ensure_prefetcher()
        try:
            item = self._queue.get(timeout=max(timeout_s, 0.0))
        except queue.Empty:
            return ("end", None) if self._stopped.is_set() else ("wait", None)
        if item is _END:
            self._queue.put(_END)
            return "end", None
        self._metrics["queue_depth"].set(self._queue.qsize())
        self._current_task = item
        return "task", item

    def _fetch_task_sync(self) -> Optional[comm.ShardTask]:
        backoff = self._wait_backoff_s
        t0 = time.monotonic()
        while True:
            tasks, wait = self._client.get_tasks(self.dataset_name, 1)
            self._metrics["fetch_rpcs"].inc()
            if wait:
                if self.flush_reports() == 0:
                    time.sleep(backoff * (1.0 + random.uniform(-0.3, 0.3)))
                    backoff = min(backoff * 2, self._wait_backoff_max_s)
                continue
            self._metrics["fetch_wait"].inc(time.monotonic() - t0)
            if not tasks:
                self.flush_reports()
                return None
            self._metrics["tasks_fetched"].inc()
            self._current_task = tasks[0]
            return tasks[0]

    # ---- done reports ------------------------------------------------------

    def report_task_done(
        self, task: Optional[comm.ShardTask] = None, success: bool = True
    ):
        """Queue a done-report; coalesced into one batched RPC flushed on
        count (``report_batch``), age (``report_interval_s``, enforced by
        the prefetcher), WAIT responses, end-of-data, and — forcibly —
        before a shard checkpoint. Synchronous mode reports inline."""
        task = task or self._current_task
        if task is None:
            return
        if not self.prefetching:
            self._client.report_task_done(
                self.dataset_name, task.task_id, success
            )
            self._metrics["report_rpcs"].inc()
            return
        with self._report_lock:
            if not self._pending_done and not self._pending_failed:
                self._pending_since = time.monotonic()
            (self._pending_done if success else self._pending_failed).append(
                task.task_id
            )
            count = len(self._pending_done) + len(self._pending_failed)
        if count >= self._report_batch or not success:
            # Failures flush immediately: the sooner the master re-queues
            # the shard, the sooner a healthy peer picks it up.
            self.flush_reports()

    def flush_reports(self) -> int:
        """Send every pending done-report in one batched RPC; returns how
        many reports were flushed. Safe to call from any thread, and on
        return no flush is still in flight: the lock spans the RPC, so a
        caller that needs flushed-before-X ordering (shard checkpoints)
        really gets it, instead of racing another thread's send."""
        with self._flush_lock:
            with self._report_lock:
                done, failed = self._pending_done, self._pending_failed
                if not done and not failed:
                    return 0
                self._pending_done, self._pending_failed = [], []
            try:
                self._client.report_tasks_done_batch(
                    self.dataset_name, done, failed
                )
            except Exception:
                # Lost reports are re-queued locally; the master's
                # timeout recovery bounds the damage if we die before a
                # retry lands.
                logger.warning(
                    "batched done-report failed; re-queueing %d reports",
                    len(done) + len(failed),
                    exc_info=True,
                )
                with self._report_lock:
                    self._pending_done = done + self._pending_done
                    self._pending_failed = failed + self._pending_failed
                    self._pending_since = time.monotonic()
                return 0
            n = len(done) + len(failed)
        self._metrics["report_rpcs"].inc()
        self._metrics["rpcs_saved"].inc(n - 1)
        return n

    def _on_master_epoch_change(self, old_epoch: int, new_epoch: int):
        """Runs on the RPC thread that first reached the restarted
        master. Re-registering is idempotent (journal rehydration already
        recreated the dataset; a params report for an existing name is a
        no-op) and the flush drains done-reports coalesced during the
        outage so the new incarnation's ledger converges."""
        logger.info(
            "master epoch %d -> %d; re-registering dataset %s and "
            "flushing %s",
            old_epoch,
            new_epoch,
            self.dataset_name,
            "pending done-reports",
        )
        try:
            self._client.report_dataset_shard_params(self._shard_params)
        except Exception:  # noqa: BLE001 — prefetcher keeps retrying anyway
            logger.warning(
                "dataset re-register after master restart failed",
                exc_info=True,
            )
        self.flush_reports()

    def _flush_if_due(self):
        with self._report_lock:
            count = len(self._pending_done) + len(self._pending_failed)
            stale = (
                count > 0
                and time.monotonic() - self._pending_since
                >= self._report_interval_s
            )
        if count >= self._report_batch or stale:
            self.flush_reports()

    # ---- shard checkpoint (dataset position survives restarts) ------------

    def get_shard_checkpoint(self) -> str:
        """Snapshot of undone shards. Pending done-reports are FORCIBLY
        flushed first — otherwise the checkpoint would still hold shards
        this worker finished, and a restore would replay them. If the
        flush cannot land, the checkpoint is refused: snapshotting stale
        accounting would silently bake the replay in."""
        flushed = self.flush_reports()
        with self._report_lock:
            remaining = len(self._pending_done) + len(self._pending_failed)
        if remaining:
            raise RuntimeError(
                f"shard checkpoint refused: {remaining} done-reports "
                "could not be flushed to the master"
            )
        if flushed and self._recorder is not None:
            self._recorder.annotate(
                "shard_ckpt_flush",
                dataset=self.dataset_name,
                reports=flushed,
            )
        return self._client.get_shard_checkpoint(self.dataset_name)

    def restore_shard_checkpoint(self, checkpoint: str):
        if checkpoint:
            self._client.restore_shard_checkpoint(
                self.dataset_name, checkpoint
            )


class IndexShardingClient(ShardingClient):
    """Record-granular iterator: hides tasks behind ``next index``.

    Iteration ends when the master reports the dataset done; shard
    boundaries are hidden behind the prefetch queue.
    """

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._indices: "queue.Queue[int]" = queue.Queue()
        self._records_consumed = 0
        self._records_in_task = 0
        self._lock = threading.Lock()

    def fetch_record_index(self) -> Optional[int]:
        """Next global record index, or None at end of data."""
        with self._lock:
            if self._indices.empty():
                if not self._fill_from_next_task():
                    return None
            index = self._indices.get()
            self._records_consumed += 1
            self._records_in_task -= 1
            if self._records_in_task == 0 and self._current_task:
                self.report_task_done(self._current_task)
        return index

    def _fill_from_next_task(self) -> bool:
        # Loop, don't return on the first empty shard: an empty task must
        # not end iteration early — and it is reported done immediately so
        # the master doesn't hold it in ``doing`` until timeout.
        while True:
            task = self.fetch_task()
            if task is None:
                return False
            indices: List[int] = (
                task.record_indices
                if task.record_indices
                else list(range(task.start, task.end))
            )
            if not indices:
                self.report_task_done(task)
                continue
            for i in indices:
                self._indices.put(i)
            self._records_in_task = len(indices)
            return True

    def __iter__(self) -> Iterator[int]:
        while True:
            index = self.fetch_record_index()
            if index is None:
                return
            yield index
