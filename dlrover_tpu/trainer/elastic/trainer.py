"""Elastic trainer: fixed global batch across world-size changes.

Parity: reference trainer/torch/elastic/trainer.py (ElasticTrainer:336)
— the training semantics (global batch, LR schedule) must not depend on
how many hosts happen to be alive. JAX version: the global batch is
``micro_batch_per_device x dp_size x grad_accum``; on re-mesh the trainer
recomputes grad_accum for the new dp size and the train step's
``lax.scan`` accumulation loop absorbs the difference — no optimizer or
schedule surgery.
"""

import time
from dataclasses import dataclass
from typing import Callable, List

from dlrover_tpu.common.log import logger
from dlrover_tpu.fault import fault_point
from dlrover_tpu.observability import tracing


@dataclass
class ElasticBatchConfig:
    global_batch_size: int
    micro_batch_per_device: int

    def grad_accum_for(self, dp_size: int) -> int:
        """Microbatch steps per update for a data-parallel size."""
        denom = self.micro_batch_per_device * dp_size
        if denom <= 0 or self.global_batch_size % denom != 0:
            raise ValueError(
                f"global batch {self.global_batch_size} not divisible by "
                f"micro({self.micro_batch_per_device}) x dp({dp_size})"
            )
        return self.global_batch_size // denom

    def is_legal_dp(self, dp_size: int) -> bool:
        denom = self.micro_batch_per_device * dp_size
        return denom > 0 and self.global_batch_size % denom == 0

    def legal_dp_sizes(self, max_dp: int) -> List[int]:
        """Data-parallel sizes this batch config can train at."""
        return [dp for dp in range(1, max_dp + 1) if self.is_legal_dp(dp)]

    def legal_node_counts_fn(
        self, local_world_size: int = 1
    ) -> Callable[[int, int], List[int]]:
        """A ``legal_counts_fn`` for ``RendezvousManager`` and
        ``RescaleCoordinator``: node counts that are both topology-legal
        (multiples of ``node_unit``) AND batch-legal (``global_batch %
        (micro * nodes * local_world_size) == 0``). Without this wiring
        a 3-of-4-survivors rendezvous would form a world whose
        ``grad_accum_for`` raises — crashing the job it just saved."""

        def legal_counts(max_nodes: int, node_unit: int) -> List[int]:
            unit = max(node_unit, 1)
            return [
                n
                for n in range(unit, max_nodes + 1, unit)
                if self.is_legal_dp(n * max(local_world_size, 1))
            ]

        return legal_counts


class ElasticTrainer:
    """Step/epoch bookkeeping + master perf reporting around a jitted
    train step whose grad_accum tracks the live world."""

    def __init__(
        self,
        batch_config: ElasticBatchConfig,
        dp_size: int,
        master_client=None,
        report_interval_s: float = 15.0,
        flight_recorder=None,
    ):
        self.batch_config = batch_config
        self.dp_size = dp_size
        self.grad_accum = batch_config.grad_accum_for(dp_size)
        self._client = master_client
        self._report_interval_s = report_interval_s
        self.global_step = 0
        self._train_started = 0.0
        self._last_report = 0.0
        self._last_step_ts = 0.0
        # Per-step flight recording: explicit recorder, else whatever
        # runtime.init_distributed armed for this process (never create
        # one here — library code must not grab crash hooks).
        if flight_recorder is None:
            from dlrover_tpu.observability.flight_recorder import (
                active_recorder,
            )

            flight_recorder = active_recorder()
        self._flight_recorder = flight_recorder

    # ---- re-scale ------------------------------------------------------------

    def rescale(self, dp_size: int) -> bool:
        """Adopt a new data-parallel size; True if grad_accum changed
        (caller must rebuild its jitted step with the new accumulation)."""
        new_accum = self.batch_config.grad_accum_for(dp_size)
        changed = new_accum != self.grad_accum
        if changed:
            logger.info(
                "elastic re-scale: dp %d -> %d, grad_accum %d -> %d "
                "(global batch stays %d)",
                self.dp_size,
                dp_size,
                self.grad_accum,
                new_accum,
                self.batch_config.global_batch_size,
            )
        self.dp_size = dp_size
        self.grad_accum = new_accum
        return changed

    # ---- step bookkeeping ----------------------------------------------------

    def start_training(self):
        self._train_started = time.time()
        self._last_step_ts = self._train_started

    def step_completed(
        self,
        steps: int = 1,
        data_wait_s: float = 0.0,
        ckpt_block_s: float = 0.0,
        allreduce_wait_s: float = 0.0,
    ):
        self.global_step += steps
        # Chaos site: "mid-step" from the job's perspective — the step
        # landed on device but nothing downstream (reports, checkpoints
        # of this step) has run. A crash action here is the worker
        # SIGKILL the soak's recovery invariants are proved against.
        fault_point("agent.worker.crash", step=self.global_step)
        now = time.time()
        prev = self._last_step_ts or now
        step_time_s = max(now - prev, 0.0) / max(steps, 1)
        if self._flight_recorder is not None:
            # Host-side bookkeeping between steps — nothing here touches
            # the jitted path. Step wall time is the gap since the last
            # completion (covers dispatch + device + data).
            self._flight_recorder.record_step(
                self.global_step,
                step_time_s=step_time_s,
                data_wait_s=data_wait_s,
                ckpt_block_s=ckpt_block_s,
            )
        self._emit_step_spans(
            step_time_s * max(steps, 1),
            data_wait_s, allreduce_wait_s, ckpt_block_s,
        )
        # Progress beacon for the rolling-deadline hang watchdog (§29):
        # one global check when none is installed.
        from dlrover_tpu.observability.hang_watchdog import (
            active_watchdog,
        )

        watchdog = active_watchdog()
        if watchdog is not None:
            watchdog.beat()
        self._last_step_ts = now
        if (
            self._client is not None
            and now - self._last_report > self._report_interval_s
        ):
            self._last_report = now
            elapsed = now - self._train_started if self._train_started else 0
            try:
                self._client.report_global_step(
                    self.global_step,
                    elapsed_train_secs=elapsed,
                    # Straggler signal: the master skews this against
                    # the other ranks' reports.
                    step_time_s=step_time_s,
                )
                # Finished spans ride the same cadence (separate
                # best-effort verb; no-op when tracing is disarmed).
                report_spans = getattr(
                    self._client, "report_trace_spans", None
                )
                if callable(report_spans):
                    report_spans()
            except Exception:
                logger.warning("global step report failed", exc_info=True)

    def _emit_step_spans(
        self,
        step_wall_s: float,
        data_wait_s: float,
        allreduce_wait_s: float,
        ckpt_block_s: float,
    ):
        """Retrospective per-step phase tree: one ``train.step`` root
        per completed step with data-fetch / compute / allreduce-wait /
        ckpt-persist children cut from the durations the caller already
        measured. Phase placement inside the step is the canonical
        order (fetch -> compute -> allreduce -> persist); the exact
        durations ride as attrs. Disarmed: one global check."""
        tracer = tracing.active_tracer()
        if tracer is None:
            return
        end = time.monotonic()
        start = end - max(step_wall_s, 0.0)
        root = tracer.record_span(
            "train.step", start, end,
            attrs={"step": self.global_step, "dp_size": self.dp_size},
        )
        waits = data_wait_s + allreduce_wait_s + ckpt_block_s
        compute_s = max(step_wall_s - waits, 0.0)
        cursor = start
        for name, dur in (
            ("train.data_fetch", data_wait_s),
            ("train.step_compute", compute_s),
            ("train.allreduce_wait", allreduce_wait_s),
            ("train.ckpt_persist", ckpt_block_s),
        ):
            if dur <= 0.0:
                continue
            tracer.record_span(
                name, cursor, min(cursor + dur, end), parent=root,
                attrs={"seconds": round(dur, 6)},
            )
            cursor += dur

    def epoch_of(self, dataset_size: int) -> int:
        consumed = self.global_step * self.batch_config.global_batch_size
        return consumed // max(dataset_size, 1)

    # ---- data pipeline -------------------------------------------------------

    def device_prefetch(self, batches, sharding=None):
        """Wrap a host-batch iterator (typically a
        ``PrefetchingDataLoader``) with H2D double-buffering: the
        ``jax.device_put`` of batch n+1 overlaps the step on batch n.
        Yields on-device batches; safe over reusable ring buffers."""
        from dlrover_tpu.trainer.elastic.dataloader import (
            device_put_prefetch,
        )

        if self._flight_recorder is not None:
            self._flight_recorder.annotate(
                "device_prefetch_start", step=self.global_step
            )
        return device_put_prefetch(batches, sharding=sharding)

    # ---- restore -------------------------------------------------------------

    def restore_checkpoint(self, checkpointer, sharding_tree=None,
                           step=None):
        """Restore the newest (or ``step``) checkpoint through the
        sharding-aware partial path and adopt its step counter.

        With ``sharding_tree`` (a pytree of the CURRENT mesh's
        shardings) the storage restore reads only this process's
        addressable byte ranges from the mmap'd shard files — after an
        elastic re-mesh each surviving host pays O(its own bytes), not
        O(global state). Returns (state, user_meta) or None; on success
        ``self.global_step`` tracks the restored step and the restore
        bandwidth lands in the flight recorder's step ring.
        """
        t0 = time.time()
        result = checkpointer.load_checkpoint(
            step=step, sharding_tree=sharding_tree
        )
        if result is None:
            logger.info("no restorable checkpoint; starting fresh")
            return None
        restored_step, state, user_meta = result
        elapsed = max(time.time() - t0, 1e-9)
        self.global_step = int(restored_step)
        if self._flight_recorder is not None:
            try:
                # Local (addressable) bytes, not global nbytes: after a
                # partial restore on an N-host mesh, global/elapsed
                # would overstate disk bandwidth ~N-fold.
                from dlrover_tpu.flash_ckpt.engine import (
                    _state_local_nbytes,
                )

                nbytes = _state_local_nbytes(state)
                self._flight_recorder.annotate(
                    "ckpt_restore",
                    step=self.global_step,
                    seconds=round(elapsed, 4),
                    mb_per_s=round(nbytes / 1e6 / elapsed, 1),
                )
            except Exception:
                pass
        logger.info(
            "restored checkpoint step %d in %.2fs", restored_step, elapsed
        )
        return state, user_meta
