"""Worker-process runtime: consume the agent's env and bring up JAX.

The agent injects the ``jax.distributed.initialize`` triple (see
dlrover_tpu.common.constants.WorkerEnv); a training script calls
``init_distributed()`` first thing. Single-process worlds skip
``jax.distributed`` entirely so local runs work on any backend.

Parity note: replaces the reference's reliance on torchrun env
(WORLD_SIZE/RANK/MASTER_ADDR, training.py:_initialize_workers) with JAX's
coordination model.
"""

import os
import time
from dataclasses import dataclass
from typing import Optional

from dlrover_tpu.common.constants import NodeEnv, WorkerEnv
from dlrover_tpu.common.log import logger


@dataclass
class DistributedContext:
    coordinator_address: str
    num_processes: int
    process_id: int
    local_rank: int
    local_world_size: int
    restart_count: int
    rdzv_round: int
    node_ranks: tuple = ()
    num_slices: int = 1
    initialized_jax_distributed: bool = False

    @property
    def is_leader(self) -> bool:
        return self.process_id == 0



_context: Optional[DistributedContext] = None


def read_worker_env() -> DistributedContext:
    return DistributedContext(
        coordinator_address=os.getenv(WorkerEnv.COORDINATOR_ADDRESS, ""),
        num_processes=int(os.getenv(WorkerEnv.NUM_PROCESSES, "1")),
        process_id=int(os.getenv(WorkerEnv.PROCESS_ID, "0")),
        local_rank=int(os.getenv(WorkerEnv.LOCAL_RANK, "0")),
        local_world_size=int(os.getenv(WorkerEnv.LOCAL_WORLD_SIZE, "1")),
        restart_count=int(os.getenv(WorkerEnv.RESTART_COUNT, "0")),
        rdzv_round=int(os.getenv(WorkerEnv.RDZV_ROUND, "0")),
        node_ranks=tuple(
            int(r)
            for r in os.getenv(WorkerEnv.NODE_RANKS, "").split(",")
            if r.strip()
        ),
        num_slices=int(os.getenv(WorkerEnv.NUM_SLICES, "1")),
    )


def init_distributed(timeout_secs: int = 300) -> DistributedContext:
    """Initialize JAX multi-process coordination from agent-injected env.

    Idempotent per process. Must be called before any other JAX API touches
    the backend.
    """
    global _context
    if _context is not None:
        return _context
    ctx = read_worker_env()
    if ctx.num_processes > 1 and ctx.coordinator_address:
        import jax

        logger.info(
            "jax.distributed.initialize(%s, num=%d, id=%d)",
            ctx.coordinator_address,
            ctx.num_processes,
            ctx.process_id,
        )
        jax.distributed.initialize(
            coordinator_address=ctx.coordinator_address,
            num_processes=ctx.num_processes,
            process_id=ctx.process_id,
            initialization_timeout=timeout_secs,
        )
        ctx.initialized_jax_distributed = True
    # Always: SIGUSR2 (agent hang post-mortem) must never be fatal, and
    # faulthandler costs nothing until the signal arrives.
    try:
        from dlrover_tpu.tpu_timer.py_tracing import (
            install_stack_dump_handler,
        )

        install_stack_dump_handler()
    except Exception:
        logger.warning(
            "stack dump handler unavailable; SIGUSR2 will be fatal to "
            "workers",
            exc_info=True,
        )
    _maybe_start_tpu_timer(ctx)
    _setup_flight_recorder(ctx)
    _setup_tracing(ctx)
    _setup_hang_watchdog(ctx)
    _context = ctx
    return ctx


def _setup_hang_watchdog(ctx: DistributedContext):
    """Arm the rolling-deadline hang watchdog (on by default: a wedged
    worker that stops beating past ``max(DLROVER_TPU_HANG_DEADLINE_S,
    factor x EWMA(step gap))`` dumps all-thread stacks to the
    agent-collectable path). ``DLROVER_TPU_HANG_DEADLINE_S=0`` disables;
    the default 300s floor keeps slow-compile first steps quiet."""
    try:
        from dlrover_tpu.observability import hang_watchdog

        raw = os.getenv("DLROVER_TPU_HANG_DEADLINE_S", "300")
        try:
            floor_s = float(raw)
        except ValueError:
            floor_s = 300.0
        if floor_s <= 0:
            return
        node_rank = int(os.getenv(NodeEnv.NODE_RANK, "0"))
        hang_watchdog.install_watchdog(
            node_rank=node_rank,
            local_rank=ctx.local_rank,
            min_deadline_s=floor_s,
            meta={"process_id": ctx.process_id},
        )
    except Exception:
        logger.warning("hang watchdog unavailable", exc_info=True)


def _setup_tracing(ctx: DistributedContext):
    """Arm distributed tracing when the env rigging asks for it
    (``DLROVER_TPU_TRACE_FILE``, same contract the fleet replica worker
    honors). Per-process sink: ``<path>`` gets ``.rank<pid>`` inserted
    before the extension on multi-process worlds so workers never
    interleave writes into one file. Disarmed (env unset) costs nothing
    — every span site stays one global check."""
    try:
        from dlrover_tpu.observability import tracing

        path = os.getenv(tracing.TRACE_FILE_ENV, "")
        if not path:
            return
        if ctx.num_processes > 1:
            base, ext = os.path.splitext(path)
            path = f"{base}.rank{ctx.process_id}{ext or '.jsonl'}"
        tracing.arm(tracing.Tracer(
            service=f"worker{ctx.process_id}", sink_path=path
        ))
        logger.info("tracing armed -> %s", path)
    except Exception:
        logger.warning("tracing unavailable", exc_info=True)


def _setup_flight_recorder(ctx: DistributedContext):
    """Arm the per-step flight recorder: a host-side ring buffer (never
    touches the jitted path) dumped as JSON on crash/SIGTERM at a path
    the agent can reconstruct from (node_rank, local_rank), so the last
    N steps of a dead worker survive for diagnosis."""
    try:
        from dlrover_tpu.observability import flight_recorder

        node_rank = int(os.getenv(NodeEnv.NODE_RANK, "0"))
        flight_recorder.install_recorder(
            node_rank=node_rank,
            local_rank=ctx.local_rank,
            meta={
                "process_id": ctx.process_id,
                "num_processes": ctx.num_processes,
                "restart_count": ctx.restart_count,
                "rdzv_round": ctx.rdzv_round,
            },
        )
    except Exception:
        logger.warning("flight recorder unavailable", exc_info=True)


def _maybe_start_tpu_timer(ctx: DistributedContext):
    """Start the native profiler daemon when enabled (reference xpu_timer
    daemon at :18889; here BASE_PORT + local_rank per worker process).
    The actually-bound port is published to a port file the launcher-side
    collector re-reads, so an OS-assigned fallback port still gets
    scraped."""
    from dlrover_tpu.common.env_utils import get_env_bool

    if not get_env_bool("DLROVER_TPU_TIMER"):
        return
    try:
        from dlrover_tpu.tpu_timer import get_timer
        from dlrover_tpu.tpu_timer.bridge import publish_port
        from dlrover_tpu.tpu_timer.py_tracing import trace_gc

        timer = get_timer()
        port = timer.start_server(18889 + ctx.local_rank)
        if not port:  # port taken (e.g. stale process): let the OS pick
            port = timer.start_server(0)
        if port:
            publish_port(ctx.local_rank, port)
        trace_gc()
        # Kernel-level acquisition (PJRT trace listener) — the TPU
        # analogue of the reference's LD_PRELOAD hook layer; gated by
        # DLROVER_TPU_TIMER_XLA.
        from dlrover_tpu.tpu_timer.xla_capture import maybe_start_listener

        maybe_start_listener(ctx.local_rank)
    except Exception:
        logger.warning("tpu_timer daemon failed to start", exc_info=True)


def get_context() -> DistributedContext:
    if _context is None:
        return init_distributed()
    return _context


def shutdown_distributed():
    global _context
    if _context is not None and _context.initialized_jax_distributed:
        import jax

        try:
            jax.distributed.shutdown()
        except Exception:
            logger.warning("jax.distributed.shutdown failed", exc_info=True)
    _context = None
