"""Continuous-batching serving engine (slot-pooled KV cache, ragged
per-slot decode, iteration-level scheduling). See engine.py for the
design and docs/DESIGN.md §25 for the invariants."""

from dlrover_tpu.serving.engine import ServingEngine
from dlrover_tpu.serving.scheduler import (
    DECODE,
    DONE,
    PREFILL,
    QUEUED,
    Request,
    Scheduler,
)
from dlrover_tpu.serving.metrics import serving_metrics

__all__ = [
    "ServingEngine",
    "Scheduler",
    "Request",
    "QUEUED",
    "PREFILL",
    "DECODE",
    "DONE",
    "serving_metrics",
]
