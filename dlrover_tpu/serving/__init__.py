"""Continuous-batching serving engine (slot-pooled KV cache, ragged
per-slot decode, iteration-level scheduling). See engine.py for the
design and docs/DESIGN.md §25 for the invariants; serving/kvpool (§31)
is the paged block-table variant with cross-request prefix reuse."""

from dlrover_tpu.serving.engine import ServingEngine
from dlrover_tpu.serving.scheduler import (
    DECODE,
    DEFAULT_SLO_CLASSES,
    DONE,
    FLEET_SLO_CLASSES,
    PREFILL,
    QUEUED,
    Request,
    Scheduler,
    SloClass,
    parse_slo_classes,
)
from dlrover_tpu.serving.metrics import serving_metrics

__all__ = [
    "ServingEngine",
    "Scheduler",
    "Request",
    "SloClass",
    "DEFAULT_SLO_CLASSES",
    "FLEET_SLO_CLASSES",
    "parse_slo_classes",
    "QUEUED",
    "PREFILL",
    "DECODE",
    "DONE",
    "serving_metrics",
]
