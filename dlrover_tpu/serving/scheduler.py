"""Request state machine + iteration-level scheduler for the engine.

Orca-style continuous batching, host side: requests move QUEUED →
PREFILL → DECODE → DONE; a slot is the unit of admission (one request
owns one row of the engine's [slots, max_len] KV pool) and is recycled
the moment its request finishes — no drain, no re-prefill of survivors.
Stale KV left in a recycled slot is harmless by the visibility
invariant (rows >= length are never read; see docs/DESIGN.md §25), so
"compaction" is pure bookkeeping: the free-list.

Per-iteration token budget: one scheduler tick admits at most one
prefill CHUNK (``prefill_chunk`` prompt tokens) alongside the decode
step's one-token-per-active-slot, and the chunk only runs when
``decoding + prefill_chunk <= token_budget`` (or nothing is decoding).
Lowering the budget protects decode latency from prefill bursts;
the default (prefill_chunk + slots) never blocks a chunk.

**SLO classes (§31).** Admission is no longer bare FCFS: requests
carry a named :class:`SloClass` (e.g. ``interactive`` — TTFT-bound —
vs ``batch`` — throughput-bound), and free slots are granted by
weighted-fair deficit round-robin over the classes with queued work:
each replenish adds ``weight`` credits per class, each admission costs
one, the class with the most credit (ties break on declaration order)
admits its OLDEST request. One class degenerates to exact FCFS — the
pre-§31 behavior, and the default when no classes are configured.
Classes also carry a default deadline, and expiry is checked at
admission time too: a request whose deadline lapsed while it waited
for a free slot is shed the moment it would otherwise win a slot
(``drain_admission_shed``), not just at the engine's pump-time sweep.

The scheduler is deliberately jax-free — pure host bookkeeping the
engine drives — so its policies are unit-testable without tracing.
"""

import itertools
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Sequence, Tuple

import numpy as np

# Request lifecycle states.
QUEUED = "queued"
PREFILL = "prefill"
DECODE = "decode"
DONE = "done"


@dataclass(frozen=True)
class SloClass:
    """One named service class. ``weight`` is the admission share under
    weighted-fair deficit round-robin (interactive traffic typically
    outweighs batch); ``default_deadline_s`` applies when a submission
    names no deadline of its own (None = no TTL)."""

    name: str
    weight: float = 1.0
    default_deadline_s: Optional[float] = None

    def __post_init__(self):
        if not self.name:
            raise ValueError("SloClass needs a name")
        if self.weight <= 0:
            raise ValueError(
                f"SloClass {self.name!r} weight must be > 0"
            )


# The conventional two-class split: TTFT-bound interactive traffic gets
# 4x the admission share of throughput-bound batch work.
DEFAULT_SLO_CLASSES: Tuple[SloClass, ...] = (
    SloClass("interactive", weight=4.0),
    SloClass("batch", weight=1.0),
)

# What a fleet replica worker serves unless told otherwise: untagged
# traffic lands in "default" (the first class), and the conventional
# interactive/batch split is understood on the wire — a router's
# tagged request must not be REJECTED by a stock replica.
FLEET_SLO_CLASSES: Tuple[SloClass, ...] = (
    SloClass("default", weight=1.0),
) + DEFAULT_SLO_CLASSES


def parse_slo_classes(spec: str) -> Tuple[SloClass, ...]:
    """``"name:weight,name:weight"`` → SloClass tuple (CLI surface).
    The first named class is the default for untagged submissions."""
    classes = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            name, weight = part.split(":", 1)
            classes.append(SloClass(name.strip(), float(weight)))
        else:
            classes.append(SloClass(part))
    if not classes:
        raise ValueError(f"no SLO classes in spec {spec!r}")
    return tuple(classes)


@dataclass
class Request:
    """One generation request and its accumulated result."""

    rid: int
    prompt: np.ndarray                 # [prompt_len] int32
    max_new_tokens: int
    temperature: float = 0.0
    state: str = QUEUED
    slot: int = -1
    prefill_pos: int = 0               # prompt rows already in the cache
    tokens: List[int] = field(default_factory=list)
    truncated: bool = False            # hit max_len before max_new_tokens
    failed: bool = False               # explicitly failed (requeue budget)
    # Machine-readable terminal failure reason ("" while not failed):
    # "requeue_budget" (step-error restarts exhausted), "deadline"
    # (shed from the queue past its TTL), or a caller-supplied reason.
    failure_reason: str = ""
    requeues: int = 0                  # step-error restarts of this request
    preemptions: int = 0               # pool-pressure evictions (§31)
    submit_ts: float = 0.0
    # Absolute deadline on the submit clock; a QUEUED request past it is
    # shed (never admitted to prefill) — a dead client's request must
    # not occupy a slot. None = no TTL.
    deadline: Optional[float] = None
    first_token_ts: Optional[float] = None
    finish_ts: Optional[float] = None
    # Slot-admission time (monotonic): queue-wait = admit_ts -
    # submit_ts. The engine's retrospective phase spans (§29) are cut
    # at submit/admit/first-token/finish — plain floats recorded here,
    # zero tracing work inside the loop.
    admit_ts: Optional[float] = None
    # Upstream trace carrier ({"trace_id","span_id"} from the fleet
    # router's attempt span, or None): the emitted phase spans parent
    # to it so one request is one tree across processes.
    trace: Optional[dict] = None
    # Named SLO class this request was admitted under (§31); "default"
    # on single-class schedulers.
    slo_class: str = "default"
    # Paged engines (serving/kvpool): warm prefix-cache blocks this
    # request's block table started from — 0 on a miss or a flat engine.
    prefix_hit_blocks: int = 0
    # Speculative decoding (serving/spec_decode, §35): drafted /
    # accepted token counts and aggregate wall time attributed to the
    # draft vs verify phases (the engine splits each iteration's cost
    # evenly across its decoding slots; the retrospective spans and the
    # accept-rate accounting read these).
    spec_drafted: int = 0
    spec_accepted: int = 0
    draft_s: float = 0.0
    verify_s: float = 0.0
    # Block migration (serving/kvpool/migrate, §36): set on the
    # DESTINATION engine at import. The migrate window sits between
    # the (source-side) prefill and the local decode in the
    # retrospective span tree; all four stamps live on the local
    # monotonic clock (import reconstructs the source phases from
    # carried durations).
    migrate_start_ts: Optional[float] = None
    migrate_end_ts: Optional[float] = None

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.shape[0])

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_ts is None:
            return None
        return self.first_token_ts - self.submit_ts


class Scheduler:
    """Slot bookkeeping + admission policy (see module docstring)."""

    def __init__(
        self,
        slots: int,
        max_len: int,
        prefill_chunk: int,
        token_budget: Optional[int] = None,
        drain_mode: bool = False,
        slo_classes: Optional[Sequence[SloClass]] = None,
        decode_tokens_per_slot: int = 1,
    ):
        if prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        if decode_tokens_per_slot < 1:
            raise ValueError("decode_tokens_per_slot must be >= 1")
        self.slots = slots
        self.max_len = max_len
        self.prefill_chunk = prefill_chunk
        # Worst-case tokens one decoding slot consumes per iteration:
        # 1 for plain decode, 1 + spec_k under speculative decoding
        # (every drafted token is VERIFIED through the model whether or
        # not it is accepted — the budget must count verification work,
        # or spec decode would starve prefill at exactly the budgets
        # tuned for the one-token step).
        self.decode_tokens_per_slot = decode_tokens_per_slot
        self.token_budget = (
            token_budget if token_budget is not None
            else prefill_chunk + slots * decode_tokens_per_slot
        )
        # drain_mode is the NAIVE static baseline the serving bench A/Bs
        # against: admit a full batch, run it to completion, only then
        # refill — no slot is recycled while any peer still decodes.
        self.drain_mode = drain_mode
        classes = tuple(slo_classes) if slo_classes else (
            SloClass("default"),
        )
        self.slo_classes: Dict[str, SloClass] = {}
        for cls in classes:
            if cls.name in self.slo_classes:
                raise ValueError(f"duplicate SLO class {cls.name!r}")
            self.slo_classes[cls.name] = cls
        self._default_class = classes[0].name
        # Deficit round-robin credits; replenished by weight whenever
        # every class with queued work is out of credit.
        self._credits: Dict[str, float] = {
            name: 0.0 for name in self.slo_classes
        }
        self.queue: Deque[Request] = deque()
        # Requests shed at admission time (deadline lapsed while
        # waiting for a slot); the engine drains and reports them with
        # the same metrics/spans as pump-time sheds.
        self._admission_shed: List[Request] = []
        # Optional engine veto on the next admission (the paged
        # engine's block watermark: admitting a request the pool
        # cannot hold would only thrash preemptions). Returning False
        # stops THIS admission round; the request keeps its place.
        self.admission_gate = None
        self.by_slot: List[Optional[Request]] = [None] * slots
        self._free: Deque[int] = deque(range(slots))
        self._rid = itertools.count()

    # ---- submission / admission -------------------------------------------

    def submit(
        self,
        prompt,
        max_new_tokens: int,
        temperature: float = 0.0,
        now: Optional[float] = None,
        deadline_s: Optional[float] = None,
        slo_class: Optional[str] = None,
    ) -> Request:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.shape[0] < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if prompt.shape[0] >= self.max_len:
            raise ValueError(
                f"prompt_len {prompt.shape[0]} leaves no decode room in "
                f"max_len {self.max_len}"
            )
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError("deadline_s must be positive")
        cls_name = slo_class if slo_class is not None else (
            self._default_class
        )
        cls = self.slo_classes.get(cls_name)
        if cls is None:
            raise ValueError(
                f"unknown SLO class {cls_name!r}; configured: "
                f"{sorted(self.slo_classes)}"
            )
        if deadline_s is None:
            deadline_s = cls.default_deadline_s
        submit_ts = now if now is not None else time.monotonic()
        req = Request(
            rid=next(self._rid),
            prompt=prompt,
            max_new_tokens=max_new_tokens,
            temperature=float(temperature),
            submit_ts=submit_ts,
            deadline=(
                submit_ts + deadline_s if deadline_s is not None else None
            ),
            slo_class=cls_name,
        )
        self.queue.append(req)
        return req

    def queue_depth_by_class(self) -> Dict[str, int]:
        depths = {name: 0 for name in self.slo_classes}
        for req in self.queue:
            depths[req.slo_class] = depths.get(req.slo_class, 0) + 1
        return depths

    def shed_expired(self, now: Optional[float] = None) -> List[Request]:
        """Drop QUEUED requests past their deadline — they are never
        admitted to prefill, so a dead client's request cannot occupy a
        slot. In-slot requests are untouched: their KV investment is
        sunk and they finish on their own. Shed requests land in DONE
        with ``failed=True`` / ``failure_reason="deadline"`` so callers
        see an explicit terminal outcome, never silence."""
        if now is None:
            now = time.monotonic()
        shed: List[Request] = []
        kept: Deque[Request] = deque()
        for req in self.queue:
            if req.deadline is not None and now > req.deadline:
                req.state = DONE
                req.failed = True
                req.failure_reason = "deadline"
                req.finish_ts = now
                shed.append(req)
            else:
                kept.append(req)
        if shed:
            self.queue = kept
        return shed

    def admit(self, now: Optional[float] = None) -> List[Request]:
        """Bind queued requests to free slots — weighted-fair deficit
        round-robin across SLO classes, FCFS within a class (one class
        = exact FCFS). A request whose deadline lapsed while it waited
        is shed HERE, the moment it would have won a slot, and surfaces
        through :meth:`drain_admission_shed`. Under drain_mode, admits
        only when EVERY slot is free — the drain-and-refill baseline."""
        if self.drain_mode and len(self._free) < self.slots:
            return []
        if now is None:
            now = time.monotonic()
        admitted = []
        while self.queue and self._free:
            req = self._next_admission(now)
            if req is None:
                break
            req.slot = self._free.popleft()
            req.state = PREFILL
            req.admit_ts = now
            self.by_slot[req.slot] = req
            admitted.append(req)
        return admitted

    def free_slots(self) -> int:
        return len(self._free)

    def admit_decode(
        self,
        prompt,
        tokens: Sequence[int],
        max_new_tokens: int,
        temperature: float = 0.0,
        slo_class: Optional[str] = None,
        now: Optional[float] = None,
    ) -> Request:
        """DECODE-entry admission (§36): bind a FREE slot directly in
        DECODE state for a request whose prefill already ran elsewhere
        (block migration). No queue, no prefill — the caller installs
        blocks/table/fill and owns the timeline stamps; this method
        seeds them with ``now`` so an un-adjusted request still has a
        consistent (zero-width) phase history. Raises when no slot is
        free — the import path must check :meth:`free_slots` first."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.shape[0] < 1:
            raise ValueError("empty prompt")
        if prompt.shape[0] >= self.max_len:
            raise ValueError(
                f"prompt_len {prompt.shape[0]} leaves no decode room "
                f"in max_len {self.max_len}"
            )
        tokens = list(tokens)
        if not tokens:
            raise ValueError(
                "decode-entry admission needs >= 1 sampled token "
                "(prefill must have completed at the source)"
            )
        if len(tokens) >= max_new_tokens:
            raise ValueError(
                f"request already complete ({len(tokens)} of "
                f"{max_new_tokens} tokens) — nothing to migrate"
            )
        cls_name = slo_class if slo_class is not None else (
            self._default_class
        )
        if cls_name not in self.slo_classes:
            raise ValueError(
                f"unknown SLO class {cls_name!r}; configured: "
                f"{sorted(self.slo_classes)}"
            )
        if not self._free:
            raise RuntimeError(
                "no free slot for decode-entry admission"
            )
        if now is None:
            now = time.monotonic()
        req = Request(
            rid=next(self._rid),
            prompt=prompt,
            max_new_tokens=max_new_tokens,
            temperature=float(temperature),
            state=DECODE,
            slo_class=cls_name,
            submit_ts=now,
        )
        req.admit_ts = now
        req.first_token_ts = now
        req.prefill_pos = int(prompt.shape[0])
        req.tokens = tokens
        req.slot = self._free.popleft()
        self.by_slot[req.slot] = req
        return req

    def _next_admission(self, now: float) -> Optional[Request]:
        """The weighted-fair winner among per-class queue heads;
        expired candidates are shed on the way (admission-time TTL).
        DRR credit is charged only for an admission that actually
        happens: sheds and admission-gate vetoes are free, so pool
        pressure cannot invert the configured class weights. The
        single-class path is O(1) (queue head); the multi-class head
        scan stops once every class has a head, and ``deque.remove``
        of a head is near-front."""
        while True:
            if not self.queue:
                return None
            charge = False
            if len(self.slo_classes) == 1:
                req = self.queue[0]
                if (
                    not self._expired(req, now)
                    and self._gate_vetoes(req)
                ):
                    return None
                self.queue.popleft()
            else:
                heads: Dict[str, Request] = {}
                for queued in self.queue:
                    if queued.slo_class not in heads:
                        heads[queued.slo_class] = queued
                        if len(heads) == len(self.slo_classes):
                            break
                if len(heads) == 1:
                    name = next(iter(heads))
                    charge = False
                else:
                    cands = {n: self._credits[n] for n in heads}
                    if max(cands.values()) <= 0:
                        # Replenish the classes with queued work; idle
                        # classes reset — credit hoarded while idle
                        # would let a burst starve everyone else later.
                        for n, cls in self.slo_classes.items():
                            self._credits[n] = (
                                self._credits[n] + cls.weight
                                if n in heads else 0.0
                            )
                        cands = {n: self._credits[n] for n in heads}
                    # Deterministic tie-break: declaration order.
                    name = max(
                        heads,
                        key=lambda n: (
                            cands[n],
                            -list(self.slo_classes).index(n),
                        ),
                    )
                    charge = True
                req = heads[name]
                if (
                    not self._expired(req, now)
                    and self._gate_vetoes(req)
                ):
                    # Veto before any charge or removal: the request
                    # keeps its place AND its class keeps its credit.
                    return None
                if charge:
                    self._credits[name] -= 1.0
                self.queue.remove(req)
            if self._expired(req, now):
                # Lapsed while waiting for a slot: shed instead of
                # burning prefill on a dead client (single-head paths
                # charged nothing; a charged multi-class credit is
                # refunded — sheds must not tilt the DRR ratio).
                if charge:
                    self._credits[req.slo_class] += 1.0
                req.state = DONE
                req.failed = True
                req.failure_reason = "deadline"
                req.finish_ts = now
                self._admission_shed.append(req)
                continue
            return req

    def _expired(self, req: Request, now: float) -> bool:
        return req.deadline is not None and now > req.deadline

    def _gate_vetoes(self, req: Request) -> bool:
        return (
            self.admission_gate is not None
            and not self.admission_gate(req)
        )

    def drain_admission_shed(self) -> List[Request]:
        """Requests shed by :meth:`admit`'s deadline check; the engine
        reports them exactly like pump-time sheds."""
        out, self._admission_shed = self._admission_shed, []
        return out

    # ---- per-iteration work selection -------------------------------------

    def decoding(self) -> List[Request]:
        return [r for r in self.by_slot if r is not None and r.state == DECODE]

    def active(self) -> List[Request]:
        return [r for r in self.by_slot if r is not None]

    def pick_prefill(self) -> Optional[Request]:
        """The prefill chunk to run this iteration, or None. FCFS among
        PREFILL slots (lowest rid = longest waiting); gated by the
        token budget so a prompt burst cannot starve decode."""
        cands = [
            r for r in self.by_slot
            if r is not None and r.state == PREFILL
        ]
        if not cands:
            return None
        n_decoding = len(self.decoding()) * self.decode_tokens_per_slot
        if n_decoding and n_decoding + self.prefill_chunk > self.token_budget:
            return None
        return min(cands, key=lambda r: r.rid)

    # ---- completion --------------------------------------------------------

    def finish(self, req: Request, now: Optional[float] = None) -> None:
        """DONE + recycle the slot. The stale KV stays in place: rows
        >= the next occupant's fill are invisible and every row is
        overwritten before its fill cursor passes it."""
        req.state = DONE
        req.finish_ts = now if now is not None else time.monotonic()
        if req.slot >= 0:
            self.by_slot[req.slot] = None
            self._free.append(req.slot)
            req.slot = -1

    def evict(self, req: Request, now: Optional[float] = None) -> None:
        """Drop a live request (cancellation). Identical bookkeeping to
        finish(); split so callers/metrics can tell outcomes apart."""
        self.finish(req, now)

    def preempt(self, req: Request) -> None:
        """Pool-pressure preemption (paged engine, §31): return ONE
        in-slot request to the FRONT of the queue with its progress
        reset, freeing its slot (and, at the engine, its blocks) for an
        older request. Unlike a step-error requeue this does NOT count
        against the request's requeue budget — being the youngest when
        the pool runs dry is scheduling, not failure."""
        if req.slot >= 0:
            self.by_slot[req.slot] = None
            self._free.append(req.slot)
            req.slot = -1
        req.state = QUEUED
        req.prefill_pos = 0
        req.tokens = []
        req.truncated = False
        req.first_token_ts = None
        req.admit_ts = None
        req.prefix_hit_blocks = 0
        req.migrate_start_ts = None
        req.migrate_end_ts = None
        self._reset_spec_progress(req)
        req.preemptions += 1
        self.queue.appendleft(req)

    @staticmethod
    def _reset_spec_progress(req: Request) -> None:
        """Progress resets (preemption, step-error requeue) restart a
        request from scratch — its speculative accounting restarts with
        it, or replayed drafts would double-count."""
        req.spec_drafted = 0
        req.spec_accepted = 0
        req.draft_s = 0.0
        req.verify_s = 0.0

    # ---- failure recovery --------------------------------------------------

    def requeue_active(self) -> List[Request]:
        """Return every in-slot request to the FRONT of the queue with
        its progress reset — the engine calls this when a step raises
        and the KV pool can no longer be trusted (donated buffers may be
        invalidated by the failed call). Requests restart from scratch:
        their sampled tokens depended on cache state that is gone.
        Queue order preserves rid order (oldest first) so recovery does
        not reorder service. Returns the re-queued requests."""
        victims = sorted(self.active(), key=lambda r: r.rid)
        for req in reversed(victims):
            if req.slot >= 0:
                self.by_slot[req.slot] = None
                self._free.append(req.slot)
                req.slot = -1
            req.state = QUEUED
            req.prefill_pos = 0
            req.tokens = []
            req.truncated = False
            req.first_token_ts = None
            req.admit_ts = None
            req.prefix_hit_blocks = 0
            req.migrate_start_ts = None
            req.migrate_end_ts = None
            self._reset_spec_progress(req)
            req.requeues += 1
            self.queue.appendleft(req)
        return victims
