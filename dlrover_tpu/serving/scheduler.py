"""Request state machine + iteration-level scheduler for the engine.

Orca-style continuous batching, host side: requests move QUEUED →
PREFILL → DECODE → DONE; a slot is the unit of admission (one request
owns one row of the engine's [slots, max_len] KV pool) and is recycled
the moment its request finishes — no drain, no re-prefill of survivors.
Stale KV left in a recycled slot is harmless by the visibility
invariant (rows >= length are never read; see docs/DESIGN.md §25), so
"compaction" is pure bookkeeping: the free-list.

Per-iteration token budget: one scheduler tick admits at most one
prefill CHUNK (``prefill_chunk`` prompt tokens) alongside the decode
step's one-token-per-active-slot, and the chunk only runs when
``decoding + prefill_chunk <= token_budget`` (or nothing is decoding).
Lowering the budget protects decode latency from prefill bursts;
the default (prefill_chunk + slots) never blocks a chunk.

The scheduler is deliberately jax-free — pure host bookkeeping the
engine drives — so its policies are unit-testable without tracing.
"""

import itertools
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, List, Optional

import numpy as np

# Request lifecycle states.
QUEUED = "queued"
PREFILL = "prefill"
DECODE = "decode"
DONE = "done"


@dataclass
class Request:
    """One generation request and its accumulated result."""

    rid: int
    prompt: np.ndarray                 # [prompt_len] int32
    max_new_tokens: int
    temperature: float = 0.0
    state: str = QUEUED
    slot: int = -1
    prefill_pos: int = 0               # prompt rows already in the cache
    tokens: List[int] = field(default_factory=list)
    truncated: bool = False            # hit max_len before max_new_tokens
    failed: bool = False               # explicitly failed (requeue budget)
    # Machine-readable terminal failure reason ("" while not failed):
    # "requeue_budget" (step-error restarts exhausted), "deadline"
    # (shed from the queue past its TTL), or a caller-supplied reason.
    failure_reason: str = ""
    requeues: int = 0                  # step-error restarts of this request
    submit_ts: float = 0.0
    # Absolute deadline on the submit clock; a QUEUED request past it is
    # shed (never admitted to prefill) — a dead client's request must
    # not occupy a slot. None = no TTL.
    deadline: Optional[float] = None
    first_token_ts: Optional[float] = None
    finish_ts: Optional[float] = None
    # Slot-admission time (monotonic): queue-wait = admit_ts -
    # submit_ts. The engine's retrospective phase spans (§29) are cut
    # at submit/admit/first-token/finish — plain floats recorded here,
    # zero tracing work inside the loop.
    admit_ts: Optional[float] = None
    # Upstream trace carrier ({"trace_id","span_id"} from the fleet
    # router's attempt span, or None): the emitted phase spans parent
    # to it so one request is one tree across processes.
    trace: Optional[dict] = None

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.shape[0])

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_ts is None:
            return None
        return self.first_token_ts - self.submit_ts


class Scheduler:
    """Slot bookkeeping + admission policy (see module docstring)."""

    def __init__(
        self,
        slots: int,
        max_len: int,
        prefill_chunk: int,
        token_budget: Optional[int] = None,
        drain_mode: bool = False,
    ):
        if prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        self.slots = slots
        self.max_len = max_len
        self.prefill_chunk = prefill_chunk
        self.token_budget = (
            token_budget if token_budget is not None
            else prefill_chunk + slots
        )
        # drain_mode is the NAIVE static baseline the serving bench A/Bs
        # against: admit a full batch, run it to completion, only then
        # refill — no slot is recycled while any peer still decodes.
        self.drain_mode = drain_mode
        self.queue: Deque[Request] = deque()
        self.by_slot: List[Optional[Request]] = [None] * slots
        self._free: Deque[int] = deque(range(slots))
        self._rid = itertools.count()

    # ---- submission / admission -------------------------------------------

    def submit(
        self,
        prompt,
        max_new_tokens: int,
        temperature: float = 0.0,
        now: Optional[float] = None,
        deadline_s: Optional[float] = None,
    ) -> Request:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.shape[0] < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if prompt.shape[0] >= self.max_len:
            raise ValueError(
                f"prompt_len {prompt.shape[0]} leaves no decode room in "
                f"max_len {self.max_len}"
            )
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError("deadline_s must be positive")
        submit_ts = now if now is not None else time.monotonic()
        req = Request(
            rid=next(self._rid),
            prompt=prompt,
            max_new_tokens=max_new_tokens,
            temperature=float(temperature),
            submit_ts=submit_ts,
            deadline=(
                submit_ts + deadline_s if deadline_s is not None else None
            ),
        )
        self.queue.append(req)
        return req

    def shed_expired(self, now: Optional[float] = None) -> List[Request]:
        """Drop QUEUED requests past their deadline — they are never
        admitted to prefill, so a dead client's request cannot occupy a
        slot. In-slot requests are untouched: their KV investment is
        sunk and they finish on their own. Shed requests land in DONE
        with ``failed=True`` / ``failure_reason="deadline"`` so callers
        see an explicit terminal outcome, never silence."""
        if now is None:
            now = time.monotonic()
        shed: List[Request] = []
        kept: Deque[Request] = deque()
        for req in self.queue:
            if req.deadline is not None and now > req.deadline:
                req.state = DONE
                req.failed = True
                req.failure_reason = "deadline"
                req.finish_ts = now
                shed.append(req)
            else:
                kept.append(req)
        if shed:
            self.queue = kept
        return shed

    def admit(self, now: Optional[float] = None) -> List[Request]:
        """Bind queued requests to free slots (FCFS). Under drain_mode,
        only when EVERY slot is free — the drain-and-refill baseline."""
        if self.drain_mode and len(self._free) < self.slots:
            return []
        admitted = []
        while self.queue and self._free:
            req = self.queue.popleft()
            req.slot = self._free.popleft()
            req.state = PREFILL
            req.admit_ts = now if now is not None else time.monotonic()
            self.by_slot[req.slot] = req
            admitted.append(req)
        return admitted

    # ---- per-iteration work selection -------------------------------------

    def decoding(self) -> List[Request]:
        return [r for r in self.by_slot if r is not None and r.state == DECODE]

    def active(self) -> List[Request]:
        return [r for r in self.by_slot if r is not None]

    def pick_prefill(self) -> Optional[Request]:
        """The prefill chunk to run this iteration, or None. FCFS among
        PREFILL slots (lowest rid = longest waiting); gated by the
        token budget so a prompt burst cannot starve decode."""
        cands = [
            r for r in self.by_slot
            if r is not None and r.state == PREFILL
        ]
        if not cands:
            return None
        n_decoding = len(self.decoding())
        if n_decoding and n_decoding + self.prefill_chunk > self.token_budget:
            return None
        return min(cands, key=lambda r: r.rid)

    # ---- completion --------------------------------------------------------

    def finish(self, req: Request, now: Optional[float] = None) -> None:
        """DONE + recycle the slot. The stale KV stays in place: rows
        >= the next occupant's fill are invisible and every row is
        overwritten before its fill cursor passes it."""
        req.state = DONE
        req.finish_ts = now if now is not None else time.monotonic()
        if req.slot >= 0:
            self.by_slot[req.slot] = None
            self._free.append(req.slot)
            req.slot = -1

    def evict(self, req: Request, now: Optional[float] = None) -> None:
        """Drop a live request (cancellation). Identical bookkeeping to
        finish(); split so callers/metrics can tell outcomes apart."""
        self.finish(req, now)

    # ---- failure recovery --------------------------------------------------

    def requeue_active(self) -> List[Request]:
        """Return every in-slot request to the FRONT of the queue with
        its progress reset — the engine calls this when a step raises
        and the KV pool can no longer be trusted (donated buffers may be
        invalidated by the failed call). Requests restart from scratch:
        their sampled tokens depended on cache state that is gone.
        Queue order preserves rid order (oldest first) so recovery does
        not reorder service. Returns the re-queued requests."""
        victims = sorted(self.active(), key=lambda r: r.rid)
        for req in reversed(victims):
            if req.slot >= 0:
                self.by_slot[req.slot] = None
                self._free.append(req.slot)
                req.slot = -1
            req.state = QUEUED
            req.prefill_pos = 0
            req.tokens = []
            req.truncated = False
            req.first_token_ts = None
            req.admit_ts = None
            req.requeues += 1
            self.queue.appendleft(req)
        return victims
