"""Serving metrics: the engine's view into the observability hub.

One call wires the continuous-batching engine into the SAME process
registry the master scrapes (observability/registry.py) — queue depth,
slot occupancy, TTFT, per-token latency, token/request counters — so a
serving job's health rides the existing /metrics exposition and the
flight-recorder ring with zero new plumbing.

Registration is idempotent (the registry returns existing families), so
multiple engines in one process share counters; gauges describe the
LAST engine to update them, which is the single-engine common case.
"""

from typing import Optional

from dlrover_tpu.observability.registry import default_registry

# Sub-second buckets: decode iterations are milliseconds, not the
# registry's default 5ms..300s I/O scale.
_LATENCY_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0,
)
_TTFT_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0,
)


class ServingMetrics:
    """Handle bundle over the registry families the engine updates."""

    def __init__(self, registry=None):
        reg = registry or default_registry()
        self.queue_depth = reg.gauge(
            "serving_queue_depth", "requests waiting for a slot"
        )
        self.active_slots = reg.gauge(
            "serving_active_slots", "slots holding a live request"
        )
        self.slots_total = reg.gauge(
            "serving_slots_total", "slot-pool size of the engine"
        )
        self.requests = reg.counter(
            "serving_requests_total",
            "requests by lifecycle outcome",
            labelnames=("outcome",),
        )
        self.tokens = reg.counter(
            "serving_tokens_total",
            "tokens processed, prefill (prompt) vs decode (generated)",
            labelnames=("kind",),
        )
        self.tokens_wasted = reg.counter(
            "serving_tokens_wasted_total",
            "computed tokens thrown away by progress resets (step-error "
            "requeues, pool preemptions) — the serving side of the §34 "
            "useful-token fraction in /api/goodput",
            labelnames=("kind",),
        )
        self.iterations = reg.counter(
            "serving_iterations_total", "engine scheduler iterations"
        )
        self.retraces = reg.counter(
            "serving_retraces_total",
            "step-program traces (must stay flat after warmup)",
        )
        self.step_errors = reg.counter(
            "serving_step_errors_total",
            "engine iterations that raised and re-queued their in-flight "
            "requests",
        )
        self.shed = reg.counter(
            "serving_requests_shed_total",
            "queued requests dropped before admission, by reason "
            '(reason="deadline": past their TTL, never prefillled) '
            "and SLO class",
            labelnames=("reason", "slo_class"),
        )
        self.class_queue_depth = reg.gauge(
            "serving_class_queue_depth",
            "requests waiting for a slot, per SLO class",
            labelnames=("slo_class",),
        )
        self.failures = reg.counter(
            "serving_requests_failed_total",
            "terminally failed requests by machine-readable reason "
            "(requeue_budget, deadline, ...)",
            labelnames=("reason",),
        )
        self.ttft = reg.histogram(
            "serving_ttft_seconds",
            "submit-to-first-token latency",
            buckets=_TTFT_BUCKETS,
        )
        self.token_latency = reg.histogram(
            "serving_token_latency_seconds",
            "per-decoded-token latency (iteration wall time)",
            buckets=_LATENCY_BUCKETS,
        )
        # ---- paged KV pool (serving/kvpool, §31) ------------------------
        self.kv_blocks = reg.gauge(
            "serving_kv_blocks",
            "paged KV pool blocks by state (free | used: referenced by "
            "a live slot's block table | cached: held warm by the "
            "prefix cache only); states sum to the managed pool size",
            labelnames=("state",),
        )
        self.kv_blocks_total = reg.gauge(
            "serving_kv_blocks_total",
            "managed (allocatable) blocks in the paged KV pool",
        )
        self.kv_bytes_in_use = reg.gauge(
            "serving_kv_bytes_in_use",
            "bytes of KV pool HBM referenced by live slots or the "
            "prefix cache (allocated blocks x block bytes, K+V)",
        )
        self.prefix_lookups = reg.counter(
            "serving_prefix_lookups_total",
            "prefix-cache lookups at admission, by outcome",
            labelnames=("outcome",),
        )
        self.prefix_hit_blocks = reg.counter(
            "serving_prefix_hit_blocks_total",
            "warm blocks handed to admitted requests by the prefix "
            "cache (each skips block_size tokens of prefill)",
        )
        self.kv_cow_copies = reg.counter(
            "serving_kv_cow_copies_total",
            "copy-on-write block privatizations (a shared block was "
            "about to be rewritten)",
        )
        self.kv_preemptions = reg.counter(
            "serving_kv_preemptions_total",
            "requests preempted (re-queued, progress reset) to free "
            "blocks for an older request under pool pressure",
        )
        # ---- speculative decoding (serving/spec_decode, §35) ------------
        self.spec_tokens = reg.counter(
            "serving_spec_tokens_total",
            "speculative-decoding tokens by fate (drafted: proposed by "
            "the drafter and verified; accepted: survived verification "
            "and committed; rejected: rolled back by the fill rewind)",
            labelnames=("kind",),
        )
        self.spec_tokens_per_step = reg.gauge(
            "serving_spec_accepted_tokens_per_step",
            "running mean of tokens committed per verify step across "
            "decoding slots (accepted drafts + the correction/bonus "
            "token; 1.0 = no speculation win, K+1 = every draft lands)",
        )
        self.spec_accept_rate = reg.histogram(
            "serving_spec_accept_rate",
            "per-slot fraction of drafted tokens accepted by one "
            "verify step (observed only for slots that drafted)",
            buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0),
        )

    def annotate(self, event: str, **fields):
        """Drop a marker in the flight-recorder ring IF one is armed —
        admissions/evictions then land in the merged job timeline next
        to training steps. Never creates a recorder."""
        from dlrover_tpu.observability.flight_recorder import (
            active_recorder,
        )

        rec = active_recorder()
        if rec is not None:
            rec.annotate(event, **fields)


_metrics: Optional[ServingMetrics] = None


def serving_metrics(registry=None) -> ServingMetrics:
    """Process-wide handle (or a private one for a passed registry)."""
    global _metrics
    if registry is not None:
        return ServingMetrics(registry)
    if _metrics is None:
        _metrics = ServingMetrics()
    return _metrics


def reset_serving_metrics():
    """Tests only: forget the cached handle (the registry itself is
    reset separately via reset_default_registry)."""
    global _metrics
    _metrics = None
