"""Cross-request prefix cache: token prefixes → warm KV block chains.

Shared system prompts are the serving fleet's biggest redundant work:
every request carrying the same leading tokens re-prefills identical
K/V on every replica. This cache keys FULL blocks of prompt tokens by a
cumulative chain hash (block k's key folds block k-1's key, so equal
keys mean equal token paths from position 0, not just an equal k-th
block) and keeps the finished blocks warm in the paged pool under a
cache-owned reference.

Structure is a trie over blocks: one entry per (parent chain, block
tokens), each holding one cache reference on its block. Lookup walks
root→leaf while keys match, increfs every hit block, and hands the
chain to the engine — the hit blocks slot straight into the request's
block table and prefill SKIPS the covered chunks. Insert registers a
finished prompt's full blocks (partial tails are never cached: a
partial block is still written by its owner's decode appends, and
shared blocks must stay immutable — COW handles the one legal rewrite,
a chunk-aligned re-prefill over a shared block).

Eviction is leaf-first LRU: only entries with no children are
evictable (evicting a mid-chain entry would orphan its suffix —
unreachable entries silently pinning blocks forever), and eviction
drops the cache's reference, freeing the block once no slot still
points at it. ``evict_lru`` is also the allocator's relief valve: the
engine calls it before preempting a request when the pool runs dry.
"""

from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from dlrover_tpu.serving.kvpool.allocator import BlockAllocator

# The chain root: block 0's parent. Any non-key value works; None keeps
# the trie honest (no token path hashes to it).
_ROOT = None


@dataclass
class _Entry:
    key: Tuple
    parent_key: Optional[Tuple]
    block_id: int
    # The block's literal tokens: verified on every hit, so a chain-hash
    # collision degrades to a miss instead of serving another prompt's
    # KV (correctness must not hang on 64-bit hash uniqueness).
    tokens: Tuple[int, ...] = ()
    children: Set[Tuple] = field(default_factory=set)


class PrefixCache:
    """See module docstring. Not thread-safe — engine-loop owned."""

    def __init__(
        self,
        allocator: BlockAllocator,
        block_size: int,
        capacity_blocks: Optional[int] = None,
    ):
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self._alloc = allocator
        self.block_size = block_size
        # None = bounded only by the pool itself (eviction then happens
        # purely through the allocator-pressure relief valve).
        self.capacity_blocks = capacity_blocks
        self._entries: "OrderedDict[Tuple, _Entry]" = OrderedDict()
        self.hits_total = 0
        self.misses_total = 0
        self.hit_blocks_total = 0
        self.evicted_blocks_total = 0

    # ---- keys --------------------------------------------------------------

    def _chain_keys(
        self, prompt: Sequence[int]
    ) -> List[Tuple[Tuple, Tuple[int, ...]]]:
        """Per-full-block ``(cumulative key, block tokens)`` pairs."""
        bs = self.block_size
        keys: List[Tuple[Tuple, Tuple[int, ...]]] = []
        parent: Optional[Tuple] = _ROOT
        for k in range(len(prompt) // bs):
            block = tuple(int(t) for t in prompt[k * bs:(k + 1) * bs])
            key = (hash((parent, block)), k)
            keys.append((key, block))
            parent = key
        return keys

    # ---- lookup / insert ---------------------------------------------------

    def lookup(self, prompt: Sequence[int]) -> List[int]:
        """Longest cached chain of full prompt blocks. Every returned
        block is INCREF'd for the caller — the hit is a loan the slot
        must decref like any other block it owns."""
        blocks: List[int] = []
        for key, tokens in self._chain_keys(prompt):
            entry = self._entries.get(key)
            if entry is None or entry.tokens != tokens:
                break
            self._entries.move_to_end(key)
            self._alloc.incref(entry.block_id)
            blocks.append(entry.block_id)
        if blocks:
            self.hits_total += 1
            self.hit_blocks_total += len(blocks)
        else:
            self.misses_total += 1
        return blocks

    def insert(self, prompt: Sequence[int], blocks: Sequence[int]) -> int:
        """Register a prefilled prompt's full blocks (``blocks[k]``
        holds rows ``[k*bs, (k+1)*bs)``). Newly cached blocks gain one
        cache-owned reference; chains already present are touched, not
        re-owned (a concurrent twin's identical blocks stay owned by
        its slot alone). Returns the number of blocks newly cached."""
        keys = self._chain_keys(prompt)
        n_full = min(len(keys), len(blocks))
        added = 0
        parent: Optional[Tuple] = _ROOT
        for k in range(n_full):
            key, tokens = keys[k]
            entry = self._entries.get(key)
            if entry is None:
                self._alloc.incref(blocks[k])
                entry = _Entry(
                    key=key, parent_key=parent, block_id=blocks[k],
                    tokens=tokens,
                )
                self._entries[key] = entry
                if parent is not _ROOT and parent in self._entries:
                    self._entries[parent].children.add(key)
                added += 1
            elif entry.tokens != tokens:
                # Chain-hash collision with a different token path:
                # cannot extend THIS chain past it (the child links
                # would corrupt the trie) — stop registering here.
                break
            else:
                self._entries.move_to_end(key)
            parent = key
        if self.capacity_blocks is not None:
            over = len(self._entries) - self.capacity_blocks
            if over > 0:
                self.evict_lru(over)
        return added

    # ---- eviction ----------------------------------------------------------

    def evict_lru(self, n_blocks: int) -> int:
        """Release up to ``n_blocks`` cache references, oldest LEAF
        first (never a mid-chain entry — an orphaned suffix would pin
        blocks unreachably). Returns how many entries were evicted; the
        underlying blocks free only once no slot references them."""
        evicted = 0
        while evicted < n_blocks:
            victim = None
            for key, entry in self._entries.items():
                if not entry.children:
                    victim = entry
                    break
            if victim is None:
                break
            del self._entries[victim.key]
            if (
                victim.parent_key is not _ROOT
                and victim.parent_key in self._entries
            ):
                self._entries[victim.parent_key].children.discard(
                    victim.key
                )
            self._alloc.decref(victim.block_id)
            evicted += 1
            self.evicted_blocks_total += 1
        return evicted

    def clear(self) -> None:
        """Drop every cache reference (pool rebuild after a step error:
        the device blocks are gone, the warm set with them)."""
        for entry in self._entries.values():
            self._alloc.decref(entry.block_id)
        self._entries.clear()

    # ---- accounting --------------------------------------------------------

    @property
    def cached_entries(self) -> int:
        return len(self._entries)

    def cached_block_ids(self) -> Set[int]:
        return {e.block_id for e in self._entries.values()}

    def hit_rate(self) -> float:
        total = self.hits_total + self.misses_total
        return self.hits_total / total if total else 0.0

    def stats(self) -> Dict[str, float]:
        return {
            "entries": len(self._entries),
            "hits": self.hits_total,
            "misses": self.misses_total,
            "hit_blocks": self.hit_blocks_total,
            "evicted_blocks": self.evicted_blocks_total,
            "hit_rate": round(self.hit_rate(), 4),
        }
