"""Block allocator for the paged KV pool: free list + refcounts + COW.

Pure host bookkeeping (no jax anywhere — the same discipline as the
scheduler): the device holds one ``[layers, num_blocks, block_size,
kv_heads, head_dim]`` slab per K and V, and THIS object decides which
block ids are free, which are owned by live slots, and which are kept
warm by the prefix cache. A block id is just an int32 row index into
the pool's block axis.

Ownership is refcounted, not owned-by-one: a block holding a shared
prompt prefix is referenced by every slot whose block table points at
it PLUS the prefix cache keeping it warm. The invariants the chaos
episode asserts live here:

- **conservation** — ``free + allocated == managed`` at every moment
  (``managed = num_blocks - reserved``; block 0 is the reserved
  garbage-sink sentinel that inactive slots scatter into, never
  allocated, never read);
- **no negative refcounts** — ``decref`` below zero raises instead of
  silently corrupting the free list;
- **copy-on-write** — a block with refcount > 1 is NEVER written; a
  writer calls :meth:`ensure_private` first, which hands back the same
  id when the caller is the sole owner and a fresh id (caller then
  device-copies the rows) when the block is shared.
"""

from collections import deque
from typing import Deque, Dict, Iterable, List, Tuple


class BlockPoolExhausted(RuntimeError):
    """alloc() could not satisfy the request; the caller decides the
    relief policy (evict prefix-cache LRU, preempt a request)."""


class BlockAllocator:
    """Free-list block allocator with refcounts. Not thread-safe — the
    engine drives it from its single serve loop."""

    def __init__(self, num_blocks: int, reserved: int = 1):
        if num_blocks <= reserved:
            raise ValueError(
                f"num_blocks {num_blocks} must exceed reserved "
                f"{reserved}"
            )
        self.num_blocks = num_blocks
        self.reserved = reserved
        self._free: Deque[int] = deque(range(reserved, num_blocks))
        self._ref: Dict[int, int] = {}
        # Monotone counters for metrics/bench.
        self.allocs_total = 0
        self.frees_total = 0
        self.cow_copies_total = 0

    # ---- core --------------------------------------------------------------

    @property
    def managed(self) -> int:
        """Allocatable blocks (sentinels excluded)."""
        return self.num_blocks - self.reserved

    def free_count(self) -> int:
        return len(self._free)

    def allocated_count(self) -> int:
        return len(self._ref)

    def alloc(self, n: int = 1) -> List[int]:
        """n fresh blocks at refcount 1 — all or nothing, so a partial
        grant can never strand half an allocation on failure."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            raise BlockPoolExhausted(
                f"need {n} blocks, {len(self._free)} free "
                f"({len(self._ref)} allocated of {self.managed})"
            )
        out = [self._free.popleft() for _ in range(n)]
        for b in out:
            self._ref[b] = 1
        self.allocs_total += n
        return out

    def incref(self, block_id: int, n: int = 1) -> None:
        if block_id not in self._ref:
            raise ValueError(f"incref on unallocated block {block_id}")
        self._ref[block_id] += n

    def decref(self, block_id: int) -> bool:
        """Drop one reference; returns True when the block was freed.
        Going below zero raises — a double free is a bug, not a state."""
        count = self._ref.get(block_id)
        if count is None or count <= 0:
            raise ValueError(
                f"decref on block {block_id} with refcount "
                f"{0 if count is None else count}"
            )
        if count == 1:
            del self._ref[block_id]
            self._free.append(block_id)
            self.frees_total += 1
            return True
        self._ref[block_id] = count - 1
        return False

    def refcount(self, block_id: int) -> int:
        return self._ref.get(block_id, 0)

    def ensure_private(self, block_id: int) -> Tuple[int, bool]:
        """COW: returns ``(block_id, False)`` when the caller is the
        sole owner; otherwise drops the caller's reference, allocates a
        fresh block, and returns ``(new_id, True)`` — the caller must
        then copy the device rows ``old -> new`` BEFORE writing."""
        if self.refcount(block_id) <= 1:
            return block_id, False
        new = self.alloc(1)[0]          # may raise BlockPoolExhausted
        self.decref(block_id)
        self.cow_copies_total += 1
        return new, True

    # ---- invariants / accounting -------------------------------------------

    def stats(self, live_blocks: Iterable[int] = ()) -> Dict[str, int]:
        """Accounting snapshot. ``live_blocks`` is the union of every
        occupied slot's block table; allocated blocks outside it are
        the prefix cache's warm set. ``free + used + cached == total``
        always — the chaos episode's block-reclaim invariant."""
        live = set(live_blocks)
        used = sum(1 for b in self._ref if b in live)
        return {
            "total": self.managed,
            "free": len(self._free),
            "used": used,
            "cached": len(self._ref) - used,
            "min_ref": min(self._ref.values(), default=0),
            "negative_refs": sum(
                1 for c in self._ref.values() if c < 0
            ),
        }

    def check(self) -> None:
        """Raise on any broken invariant (tests + soak call this)."""
        if len(self._free) + len(self._ref) != self.managed:
            raise AssertionError(
                f"block conservation broken: free {len(self._free)} + "
                f"allocated {len(self._ref)} != managed {self.managed}"
            )
        bad = {b: c for b, c in self._ref.items() if c <= 0}
        if bad:
            raise AssertionError(f"non-positive refcounts: {bad}")
        dup = set(self._free) & set(self._ref)
        if dup:
            raise AssertionError(f"blocks both free and allocated: {dup}")
